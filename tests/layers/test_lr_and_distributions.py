"""LR schedules (static counter-driven) and layers.distributions."""
import math

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.layers import distributions as D


def _run_steps(build_lr, n):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        lr = build_lr()
    exe = fluid.Executor()
    out = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        for _ in range(n):
            out.append(float(np.asarray(exe.run(main,
                                                fetch_list=[lr])[0]).item()))
    return out


def test_exponential_decay():
    got = _run_steps(lambda: layers.exponential_decay(0.1, 10, 0.5), 3)
    want = [0.1 * 0.5 ** (i / 10) for i in range(3)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_piecewise_decay():
    got = _run_steps(lambda: layers.piecewise_decay([2, 4], [1.0, 0.5, 0.1]), 6)
    np.testing.assert_allclose(got, [1.0, 1.0, 0.5, 0.5, 0.1, 0.1], rtol=1e-6)


def test_noam_decay():
    got = _run_steps(lambda: layers.noam_decay(512, 4000), 2)
    want = [512 ** -0.5 * min(n ** -0.5, n * 4000 ** -1.5) for n in (1, 2)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_polynomial_and_cosine_and_warmup():
    got = _run_steps(lambda: layers.polynomial_decay(0.1, 10, 0.01, 2.0), 2)
    want = [(0.1 - 0.01) * (1 - i / 10) ** 2 + 0.01 for i in range(2)]
    np.testing.assert_allclose(got, want, rtol=1e-5)

    got = _run_steps(lambda: layers.cosine_decay(0.1, 2, 4), 3)
    want = [0.1 * 0.5 * (math.cos(math.floor(i / 2) * math.pi / 4) + 1)
            for i in range(3)]
    np.testing.assert_allclose(got, want, rtol=1e-5)

    got = _run_steps(
        lambda: layers.linear_lr_warmup(0.1, 3, 0.0, 0.1), 5)
    want = [0.0, 0.1 / 3, 0.2 / 3, 0.1, 0.1]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_inverse_and_natural_exp_decay():
    got = _run_steps(lambda: layers.inverse_time_decay(0.1, 5, 0.5, True), 7)
    want = [0.1 / (1 + 0.5 * (i // 5)) for i in range(7)]
    np.testing.assert_allclose(got, want, rtol=1e-5)
    got = _run_steps(lambda: layers.natural_exp_decay(0.1, 5, 0.5), 3)
    want = [0.1 * math.exp(-0.5 * i / 5) for i in range(3)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def _fetch(build):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        outs = build()
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        return exe.run(main, fetch_list=list(outs))


def test_normal_distribution():
    ent, lp, kl = _fetch(lambda: (
        D.Normal(0.0, 2.0).entropy(),
        D.Normal(0.0, 2.0).log_prob(layers.fill_constant([1], 'float32', 1.0)),
        D.Normal(0.0, 2.0).kl_divergence(D.Normal(1.0, 1.0))))
    np.testing.assert_allclose(ent, 0.5 + 0.5 * math.log(2 * math.pi)
                               + math.log(2.0), rtol=1e-5)
    np.testing.assert_allclose(
        lp, -1.0 / 8 - 0.5 * math.log(2 * math.pi) - math.log(2.0), rtol=1e-5)
    np.testing.assert_allclose(kl, 0.5 * (4 + 1 - 1 - math.log(4.0)), rtol=1e-5)


def test_uniform_sample_and_categorical():
    s, = _fetch(lambda: D.Uniform(1.0, 3.0).sample([1000], seed=7))
    assert s.shape == (1000, 1) and s.min() >= 1.0 and s.max() <= 3.0
    ent, kl = _fetch(lambda: (
        D.Categorical(layers.fill_constant([4], 'float32', 0.0)).entropy(),
        D.Categorical(layers.fill_constant([4], 'float32', 0.0)).kl_divergence(
            D.Categorical(layers.fill_constant([4], 'float32', 1.0)))))
    np.testing.assert_allclose(ent, math.log(4.0), rtol=1e-4)
    np.testing.assert_allclose(kl, 0.0, atol=1e-5)


def test_mvn_diag():
    ent, kl = _fetch(lambda: (
        D.MultivariateNormalDiag(layers.zeros([2], 'float32'),
                                 layers.ones([2], 'float32')).entropy(),
        D.MultivariateNormalDiag(layers.zeros([2], 'float32'),
                                 layers.ones([2], 'float32')).kl_divergence(
            D.MultivariateNormalDiag(layers.zeros([2], 'float32'),
                                     layers.ones([2], 'float32')))))
    np.testing.assert_allclose(ent, 0.5 * 2 * (1 + math.log(2 * math.pi)),
                               rtol=1e-4)
    np.testing.assert_allclose(kl, 0.0, atol=1e-4)


def test_dygraph_warmup_steps_inner_schedule():
    from paddle_tpu.dygraph.learning_rate_scheduler import (
        LinearLrWarmup, NaturalExpDecay)
    sched = LinearLrWarmup(NaturalExpDecay(0.1, 10, 0.5), 3, 0.0, 0.1, begin=0)
    vals = []
    for _ in range(6):
        vals.append(float(sched()))
        sched.step()
    want = [0.0, 0.1 / 3, 0.2 / 3] + [0.1 * math.exp(-0.5 * n / 10)
                                      for n in (3, 4, 5)]
    np.testing.assert_allclose(vals, want, rtol=1e-6)


def test_dygraph_schedulers():
    with fluid.dygraph.guard():
        sched = layers.piecewise_decay([2, 4], [1.0, 0.5, 0.1])
        vals = []
        for _ in range(5):
            vals.append(float(sched()))
            sched.step()
    np.testing.assert_allclose(vals, [1.0, 1.0, 0.5, 0.5, 0.1], rtol=1e-6)
