"""Benchmark driver: ResNet-50 fwd+bwd+update images/sec/chip (bf16 compute)
plus BERT-base pretrain seq/s and MFU for both (SURVEY §5 metrics).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"bert_base_seq_per_sec", "bert_mfu", "chip", ...}.
Baseline (BASELINE.json north star): CUDA V100 ResNet-50 ≈ 383 img/s fp32
(PaddlePaddle's published reference-class number for the 1.x benchmark suite).

MFU = delivered FLOP/s ÷ chip peak bf16 FLOP/s, with analytic model FLOPs:
- ResNet-50 @224: ≈ 4.09 GFLOP fwd/img (2×MACs) → ×3 for fwd+bwd ≈ 12.3 GF.
- BERT: 6·P FLOP per token (P = non-embedding params, train fwd+bwd)
  + 12·L·h·S per token of attention score/context work (see PERF.md).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

V100_BASELINE_IMG_S = 383.0
RESNET50_TRAIN_GFLOP_PER_IMG = 12.3

# chip peak bf16 TFLOP/s by device_kind substring (dense, no sparsity)
_CHIP_PEAK_TFLOPS = [
    ('v6', 918.0), ('v5p', 459.0), ('v5 lite', 197.0), ('v5e', 197.0),
    ('v4', 275.0), ('v3', 123.0), ('v2', 45.0),
]


def chip_peak_tflops(device):
    kind = getattr(device, 'device_kind', '').lower()
    for sub, peak in _CHIP_PEAK_TFLOPS:
        if sub in kind:
            return peak
    return None


def bench_resnet(on_tpu):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.models import ResNet50
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.dygraph.tape import dispatch_op

    batch = 128 if on_tpu else 8
    img = 224 if on_tpu else 32
    iters = 20 if on_tpu else 3
    # NHWC on TPU: convs lower without layout transposes — measured ~6%
    # faster end-to-end than NCHW on v5e (PERF.md §2)
    fmt = 'NHWC' if on_tpu else 'NCHW'

    with dygraph.guard():
        model = ResNet50(class_dim=1000, data_format=fmt)
        opt = fluid.optimizer.Momentum(0.1, momentum=0.9,
                                       parameter_list=model.parameters())

        def loss_fn(m, x, y):
            logits = m(x)
            logits = dispatch_op('cast', {'x': logits}, {'dtype': 'float32'})
            l, _ = dispatch_op('softmax_with_cross_entropy',
                               {'logits': logits, 'label': y}, {})
            return dispatch_op('reduce_mean', {'x': l}, {})

        # bf16 compute with fp32 master weights (AMP) on TPU; param dtypes
        # stay fp32 across steps so the fused step compiles exactly once
        step = TrainStep(model, loss_fn, opt,
                         amp_dtype=jnp.bfloat16 if on_tpu else None)
        xshape = (batch, 3, img, img) if fmt == 'NCHW' \
            else (batch, img, img, 3)
        x = np.random.randn(*xshape).astype(np.float32)
        y = np.random.randint(0, 1000, (batch, 1)).astype(np.int64)
        if on_tpu:
            x = jnp.asarray(x, jnp.bfloat16)

        # warmup/compile; float() forces a device→host transfer, which is
        # the only reliable barrier on the axon remote backend
        # (block_until_ready returns before remote execution finishes)
        l = step(x, y)
        float(l)
        t0 = time.perf_counter()
        for _ in range(iters):
            l = step(x, y)
        float(l)
        dt = time.perf_counter() - t0
    return batch * iters / dt


def bench_bert(on_tpu):
    """BERT-base MLM+NSP pretrain step, bf16, XLA attention —
    sequences/sec on one chip (SURVEY §5 'BERT-base seq/s')."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        pretrain_loss)

    if on_tpu:
        # XLA attention, not the pallas flash path: measured faster at
        # S=128 on v5e (PERF.md §3 — scores fit on-chip at this size)
        cfg = BertConfig(attention_probs_dropout_prob=0.0,
                         hidden_dropout_prob=0.0,
                         max_position_embeddings=128)
        # bs sweep on v5e (PERF.md §7): 32/64/128/256 →
        # 1022/1270/1294/1172 seq/s — 128 is the knee
        batch, seq, iters = 128, 128, 20
    else:
        cfg = BertConfig.tiny()
        batch, seq, iters = 4, 32, 2

    with dygraph.guard():
        model = BertForPretraining(cfg)
        opt = fluid.optimizer.Adam(1e-4, parameter_list=model.parameters())

        def loss_fn(m, ids, tt, mlm, nsp):
            return pretrain_loss(m, ids, tt, mlm, nsp)

        step = TrainStep(model, loss_fn, opt,
                         amp_dtype=jnp.bfloat16 if on_tpu else None)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
        tt = np.zeros((batch, seq), np.int64)
        mlm = np.where(rng.rand(batch, seq) < 0.15,
                       rng.randint(0, cfg.vocab_size, (batch, seq)),
                       -1).astype(np.int64)
        nsp = rng.randint(0, 2, (batch, 1)).astype(np.int64)

        l = step(ids, tt, mlm, nsp)
        float(l)
        t0 = time.perf_counter()
        for _ in range(iters):
            l = step(ids, tt, mlm, nsp)
        float(l)
        dt = time.perf_counter() - t0

    seq_per_sec = batch * iters / dt
    # analytic train FLOPs/seq (fwd+bwd = 3× fwd, 2 FLOPs per MAC):
    #   block matmuls: 6 · 12·L·h²  per token  (QKVO 4h² + FFN 8h²)
    #   attention scores+context: 12·L·h·S per token (QKᵀ and PV, 2·S²·h
    #   each per layer fwd)
    #   MLM head: 6·h·V per token
    h, L, V = cfg.hidden_size, cfg.num_hidden_layers, cfg.vocab_size
    flops_per_seq = seq * (72.0 * L * h * h + 12.0 * L * h * seq
                           + 6.0 * h * V)
    return seq_per_sec, flops_per_seq


def main():
    import jax
    on_tpu = jax.default_backend() != 'cpu'
    dev = jax.devices()[0]
    peak = chip_peak_tflops(dev) if on_tpu else None

    img_per_sec = bench_resnet(on_tpu)
    resnet_mfu = (img_per_sec * RESNET50_TRAIN_GFLOP_PER_IMG / 1e3
                  / peak) if peak else None

    bert_seq_s, bert_flops_per_seq = bench_bert(on_tpu)
    bert_mfu = (bert_seq_s * bert_flops_per_seq / 1e12 / peak) \
        if peak else None

    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / V100_BASELINE_IMG_S, 3),
        "mfu": round(resnet_mfu, 4) if resnet_mfu else None,
        "bert_base_seq_per_sec": round(bert_seq_s, 2),
        "bert_mfu": round(bert_mfu, 4) if bert_mfu else None,
        "chip": getattr(dev, 'device_kind', str(dev)),
        "chip_peak_bf16_tflops": peak,
    }))


if __name__ == '__main__':
    main()
