"""Benchmark driver: ResNet-50 fwd+bwd+update images/sec/chip (bf16 compute)
plus BERT-base pretrain seq/s and MFU for both (SURVEY §5 metrics).

Output protocol (hardened after the r4 tunnel outage lost all evidence):
- each metric is printed as its OWN JSON line the moment it is measured,
  flushed, so a mid-run crash still leaves every completed number on stdout;
- the LAST line is the combined summary in the original driver contract
  {"metric", "value", "unit", "vs_baseline", ...};
- backend init runs under a watchdog: if `jax.devices()` does not answer
  within $PADDLE_TPU_BACKEND_TIMEOUT (default 120 s — a dead axon tunnel
  hangs it forever), a diagnostic JSON line is printed and we exit 3 fast
  instead of burning the driver's whole timeout budget;
- a failing bench section prints its own error line and the run exits
  nonzero only AFTER printing whatever was measured;
- a `dygraph_eager_overhead` line (valid on CPU too) carries the dispatch
  microbench from tools/bench_dispatch.py: eager tape step with the per-op
  kernel cache off/on vs the fused TrainStep, slope-method ms/step for a
  ResNet bottleneck block and a BERT layer (PERF.md §9).

Baseline (BASELINE.json north star): CUDA V100 ResNet-50 ≈ 383 img/s fp32
(PaddlePaddle's published reference-class number for the 1.x benchmark suite).

MFU = delivered FLOP/s ÷ chip peak bf16 FLOP/s, with analytic model FLOPs:
- ResNet-50 @224: ≈ 4.09 GFLOP fwd/img (2×MACs) → ×3 for fwd+bwd ≈ 12.3 GF.
- BERT: 6·P FLOP per token (P = non-embedding params, train fwd+bwd)
  + 12·L·h·S per token of attention score/context work (see PERF.md).
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np


def emit(obj):
    """One JSON object per line, flushed immediately (partial-evidence
    protocol: anything measured survives a later crash)."""
    print(json.dumps(obj), flush=True)


def init_backend_or_die():
    """Bounded backend init: on a hang or an init error, print a diagnostic
    JSON line (partial-evidence protocol) and exit 3 fast instead of
    burning the driver's whole timeout budget (the r4 failure mode)."""
    from paddle_tpu.utils.backend_probe import probe_backend
    try:
        # in-process watchdog (single init): bench exits on failure, so
        # the subprocess isolation buys nothing here
        devices, backend = probe_backend(isolated=False)
    except BaseException as e:
        emit({"metric": "backend_init",
              "error": f"{type(e).__name__}: {e}"})
        os._exit(3)
    import jax
    return jax, devices, backend

V100_BASELINE_IMG_S = 383.0
RESNET50_TRAIN_GFLOP_PER_IMG = 12.3

# chip peak bf16 TFLOP/s by device_kind substring (dense, no sparsity)
_CHIP_PEAK_TFLOPS = [
    ('v6', 918.0), ('v5p', 459.0), ('v5 lite', 197.0), ('v5e', 197.0),
    ('v4', 275.0), ('v3', 123.0), ('v2', 45.0),
]


def chip_peak_tflops(device):
    kind = getattr(device, 'device_kind', '').lower()
    for sub, peak in _CHIP_PEAK_TFLOPS:
        if sub in kind:
            return peak
    return None


def _resnet_rate(on_tpu, batch, img, iters, fmt, s2d):
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.models import ResNet50
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.dygraph.tape import dispatch_op

    with dygraph.guard():
        model = ResNet50(class_dim=1000, data_format=fmt,
                         stem_space_to_depth=s2d)
        opt = fluid.optimizer.Momentum(0.1, momentum=0.9,
                                       parameter_list=model.parameters())

        def loss_fn(m, x, y):
            logits = m(x)
            logits = dispatch_op('cast', {'x': logits}, {'dtype': 'float32'})
            l, _ = dispatch_op('softmax_with_cross_entropy',
                               {'logits': logits, 'label': y}, {})
            return dispatch_op('reduce_mean', {'x': l}, {})

        # bf16 compute with fp32 master weights (AMP) on TPU; param dtypes
        # stay fp32 across steps so the fused step compiles exactly once
        step = TrainStep(model, loss_fn, opt,
                         amp_dtype=jnp.bfloat16 if on_tpu else None)
        xshape = (batch, 3, img, img) if fmt == 'NCHW' \
            else (batch, img, img, 3)
        x = np.random.randn(*xshape).astype(np.float32)
        y = np.random.randint(0, 1000, (batch, 1)).astype(np.int64)
        if on_tpu:
            x = jnp.asarray(x, jnp.bfloat16)

        # warmup/compile; float() forces a device→host transfer, which is
        # the only reliable barrier on the axon remote backend
        # (block_until_ready returns before remote execution finishes)
        l = step(x, y)
        float(l)
        t0 = time.perf_counter()
        for _ in range(iters):
            l = step(x, y)
        float(l)
        dt = time.perf_counter() - t0
    return batch * iters / dt


def bench_resnet(on_tpu):
    batch = 128 if on_tpu else 8
    img = 224 if on_tpu else 32
    iters = 20 if on_tpu else 3
    # NHWC on TPU: convs lower without layout transposes — measured ~6%
    # faster end-to-end than NCHW on v5e (PERF.md §2)
    fmt = 'NHWC' if on_tpu else 'NCHW'
    rate = _resnet_rate(on_tpu, batch, img, iters, fmt, s2d=False)
    if on_tpu and os.environ.get('PADDLE_TPU_STEM_S2D', '1') != '0':
        # self-measuring A/B of the space-to-depth stem (PERF.md §8): one
        # extra compile+short run; the headline stays the measured winner
        # and both numbers land in the captured evidence. The plain rate
        # is already measured — an A/B failure must not lose it (the
        # partial-evidence protocol this file promises).
        try:
            rate_s2d = _resnet_rate(on_tpu, batch, img,
                                    max(iters // 2, 5), fmt, s2d=True)
        except Exception as e:
            emit({"metric": "resnet50_stem_s2d_ab",
                  "plain_img_per_sec": round(rate, 2),
                  "error": f"{type(e).__name__}: {e}"[:500]})
        else:
            emit({"metric": "resnet50_stem_s2d_ab",
                  "plain_img_per_sec": round(rate, 2),
                  "s2d_img_per_sec": round(rate_s2d, 2),
                  "winner": "s2d" if rate_s2d > rate else "plain"})
            rate = max(rate, rate_s2d)
    return rate


def bench_bert(on_tpu):
    """BERT-base MLM+NSP pretrain step, bf16, XLA attention —
    sequences/sec on one chip (SURVEY §5 'BERT-base seq/s')."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        pretrain_loss)

    if on_tpu:
        # XLA attention, not the pallas flash path: measured faster at
        # S=128 on v5e (PERF.md §3 — scores fit on-chip at this size)
        cfg = BertConfig(attention_probs_dropout_prob=0.0,
                         hidden_dropout_prob=0.0,
                         max_position_embeddings=128)
        # bs sweep on v5e (PERF.md §7): 32/64/128/256 →
        # 1022/1270/1294/1172 seq/s — 128 is the knee
        batch, seq, iters = 128, 128, 20
    else:
        cfg = BertConfig.tiny()
        batch, seq, iters = 4, 32, 2

    with dygraph.guard():
        model = BertForPretraining(cfg)
        opt = fluid.optimizer.Adam(1e-4, parameter_list=model.parameters())

        def loss_fn(m, ids, tt, mlm, nsp):
            return pretrain_loss(m, ids, tt, mlm, nsp)

        step = TrainStep(model, loss_fn, opt,
                         amp_dtype=jnp.bfloat16 if on_tpu else None)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
        tt = np.zeros((batch, seq), np.int64)
        mlm = np.where(rng.rand(batch, seq) < 0.15,
                       rng.randint(0, cfg.vocab_size, (batch, seq)),
                       -1).astype(np.int64)
        nsp = rng.randint(0, 2, (batch, 1)).astype(np.int64)

        l = step(ids, tt, mlm, nsp)
        float(l)
        t0 = time.perf_counter()
        for _ in range(iters):
            l = step(ids, tt, mlm, nsp)
        float(l)
        dt = time.perf_counter() - t0

    seq_per_sec = batch * iters / dt
    # analytic train FLOPs/seq (fwd+bwd = 3× fwd, 2 FLOPs per MAC):
    #   block matmuls: 6 · 12·L·h²  per token  (QKVO 4h² + FFN 8h²)
    #   attention scores+context: 12·L·h·S per token (QKᵀ and PV, 2·S²·h
    #   each per layer fwd)
    #   MLM head: 6·h·V per token
    h, L, V = cfg.hidden_size, cfg.num_hidden_layers, cfg.vocab_size
    flops_per_seq = seq * (72.0 * L * h * h + 12.0 * L * h * seq
                           + 6.0 * h * V)
    return seq_per_sec, flops_per_seq


def bench_transformer_big(on_tpu):
    """Transformer-big WMT en-de train step (BASELINE.json config[3]):
    tokens/sec on one chip, bf16, fused step (the ParallelExecutor
    fused-allreduce path collapses to the single fused XLA program on one
    chip; multi-chip uses the same step dp-sharded)."""
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.models.transformer import (Transformer,
                                               TransformerConfig,
                                               transformer_loss)

    if on_tpu:
        cfg = TransformerConfig.big(dropout=0.0, max_length=64)
        batch, seq, iters = 64, 64, 10
    else:
        cfg = TransformerConfig.tiny(dropout=0.0)
        batch, seq, iters = 2, 8, 2

    with dygraph.guard():
        model = Transformer(cfg)
        opt = fluid.optimizer.Adam(1e-4, parameter_list=model.parameters())

        def loss_fn(m, src, trg, lbl):
            logits = m(src, trg)
            return transformer_loss(logits, lbl)

        step = TrainStep(model, loss_fn, opt,
                         amp_dtype=jnp.bfloat16 if on_tpu else None)
        rng = np.random.RandomState(0)
        src = rng.randint(1, cfg.src_vocab_size, (batch, seq)).astype(np.int64)
        trg = rng.randint(1, cfg.trg_vocab_size, (batch, seq)).astype(np.int64)
        lbl = rng.randint(1, cfg.trg_vocab_size,
                          (batch, seq, 1)).astype(np.int64)

        l = step(src, trg, lbl)
        float(l)
        t0 = time.perf_counter()
        for _ in range(iters):
            l = step(src, trg, lbl)
        float(l)
        dt = time.perf_counter() - t0

    tokens_per_sec = batch * 2 * seq * iters / dt  # src + trg tokens
    # analytic train FLOPs per token (2 FLOP/MAC, train = 3× fwd), averaged
    # over the src+trg token count; embedding lookups free, logits matmul
    # charged to trg tokens:
    d, di, L = cfg.d_model, cfg.d_inner, cfg.n_layer
    V = cfg.trg_vocab_size
    enc_lin = 2.0 * (4 * d * d + 2 * d * di)       # QKVO + FFN, per tok/layer
    dec_lin = 2.0 * (8 * d * d + 2 * d * di)       # + cross-attn QKVO
    attn = 4.0 * seq * d                           # QKᵀ + PV, per tok/layer
    fwd_per_pair = (L * (enc_lin + attn)           # encoder, src token
                    + L * (dec_lin + 2 * attn)     # decoder, trg token
                    + 2.0 * d * V)                 # output projection
    flops_per_tok = 3.0 * fwd_per_pair / 2.0       # per (src+trg)-avg token
    return tokens_per_sec, flops_per_tok


def bench_ernie(on_tpu):
    """ERNIE-base finetune step (BASELINE.json config[4]): AMP bf16 +
    gradient merge k=4 (the reference recipe), seq/sec on one chip."""
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.models.ernie import (ErnieConfig,
                                         ErnieForSequenceClassification)
    from paddle_tpu.dygraph.tape import dispatch_op

    if on_tpu:
        cfg = ErnieConfig.base(attention_probs_dropout_prob=0.0,
                               hidden_dropout_prob=0.0,
                               max_position_embeddings=128)
        batch, seq, iters = 64, 128, 16
    else:
        cfg = ErnieConfig(vocab_size=128, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64, max_position_embeddings=32)
        batch, seq, iters = 4, 16, 4

    with dygraph.guard():
        model = ErnieForSequenceClassification(cfg, num_labels=2, dropout=0.0)
        opt = fluid.optimizer.Adam(5e-5, parameter_list=model.parameters())

        def loss_fn(m, ids, tt, y):
            logits = dispatch_op('cast', {'x': m(ids, tt)},
                                 {'dtype': 'float32'})
            l, _ = dispatch_op('softmax_with_cross_entropy',
                               {'logits': logits, 'label': y}, {})
            return dispatch_op('reduce_mean', {'x': l}, {})

        step = TrainStep(model, loss_fn, opt,
                         amp_dtype=jnp.bfloat16 if on_tpu else None,
                         accum_steps=4)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
        tt = np.zeros((batch, seq), np.int64)
        y = rng.randint(0, 2, (batch, 1)).astype(np.int64)

        l = step(ids, tt, y)
        float(l)
        t0 = time.perf_counter()
        for _ in range(iters):
            l = step(ids, tt, y)
        float(l)
        dt = time.perf_counter() - t0

    seq_per_sec = batch * iters / dt
    h, L = cfg.hidden_size, cfg.num_hidden_layers
    flops_per_seq = seq * (72.0 * L * h * h + 12.0 * L * h * seq)
    return seq_per_sec, flops_per_seq


def bench_dispatch_overhead(on_tpu):
    """Eager-tape step vs fused TrainStep on a ResNet bottleneck block and a
    BERT layer, with the per-op kernel cache off/on (slope-method timing —
    PERF.md §9). Measurable on CPU: the quantity under test is host-side
    dispatch, not FLOPs."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_dispatch import measure_all
    return measure_all(iters=8 if on_tpu else 4)


def bench_ir_passes(on_tpu):
    """Pass-pipeline front-end bench (PERF.md §10): jaxpr eqn count and
    trace+lower seconds pass-off vs pass-on (fuse knobs live) for the
    multi-param Adam MLP / ResNet block / BERT layer, plus the
    executor_compile_seconds cold/warm A/B. Valid on CPU: the quantity
    under test is host-side trace+lower, not FLOPs."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_passes import measure_all
    return measure_all(iters=3 if on_tpu else 2, smoke=not on_tpu)


def bench_verify_overhead(on_tpu):
    """Static-verifier cost (PERF.md §17): paddle_tpu/analysis/ at
    PADDLE_TPU_VERIFY=passes on the multi-param Adam MLP recipe — the
    verifier's fraction of the cold lower+compile it rides on (must be
    ≤2%) and the warm-step ratio (must be ~1.0: build-time only). Valid
    on CPU: the quantity under test is host-side analysis time."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_verify import measure_all
    return measure_all(iters=5 if on_tpu else 3, smoke=not on_tpu)


def bench_memory_plan(on_tpu):
    """Static memory-planner bench (PERF.md §20): plan latency as a
    fraction of the cold lower+compile it informs (≤1% acceptance) and
    the auto-remat memory-vs-steps/s tradeoff on an activation-heavy MLP
    (fits a simulated PADDLE_TPU_HBM_BUDGET_MB the unplanned program
    exceeds, bitwise losses). Valid on CPU: the quantities under test
    are host-side planning time and byte arithmetic."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_plan import measure_all
    return measure_all(smoke=not on_tpu, iters=7 if on_tpu else 5)


def bench_partitioner(on_tpu):
    """Unified SPMD partitioner bench (docs/PARTITIONER.md): per-Program
    spec-resolution time (zero tracing — the cost the Executor pays per
    compile-cache miss on a partitioned program), spec parity vs the
    retired per-module plumbing, and dp×fsdp / dp×tp SpmdTrainStep
    composition parity with the quantized-collective sync counters
    asserted. Runs in a SUBPROCESS: the composed meshes need ≥8 devices
    (XLA_FLAGS before backend init on CPU). Valid on CPU: the quantities
    under test are host-side resolution time + scheduling/shape
    discipline."""
    import subprocess
    env = dict(os.environ)
    if not on_tpu:
        env['JAX_PLATFORMS'] = 'cpu'
    flags = env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)), 'tools',
                      'bench_partition.py')]
        + ([] if on_tpu else ['--smoke']),
        env=env, capture_output=True, text=True, timeout=1200)
    if r.returncode != 0:
        raise RuntimeError(f'bench_partition failed: {r.stderr[-2000:]}')
    out = {}
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith('{'):
            d = json.loads(line)
            out[d['bench']] = d
    return out


def bench_sparse_section(on_tpu):
    """Sparse embedding fast path (PERF.md §21, docs/SPARSE.md): rows-only
    grad+update step vs the dense-scatter legacy at V=1e6 / nnz≈4k,
    lookups/sec, DP bytes-on-wire (dense all-reduce vs quantized COO
    push), and executor-spine sparse-vs-dense parity. Valid on CPU: the
    quantities are HBM-traffic asymmetry (O(V·D) vs O(nnz·D)) and byte
    accounting, not device-specific kernels."""
    import subprocess
    env = dict(os.environ)
    if not on_tpu:
        env['JAX_PLATFORMS'] = 'cpu'
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)), 'tools',
                      'bench_sparse.py')],
        env=env, capture_output=True, text=True, timeout=1200)
    if r.returncode != 0:
        raise RuntimeError(f'bench_sparse failed: {r.stderr[-2000:]}')
    out = {}
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith('{'):
            d = json.loads(line)
            key = d['bench']
            if key == 'sparse_step_time':
                key = f"sparse_step_time_v{d['vocab']}"
            out[key] = d
    return out


def bench_serving_batcher(on_tpu):
    """Serving-path load bench (PERF.md §11): closed-loop clients through
    the dynamic micro-batcher (paddle_tpu/serving/) vs serial single-request
    Predictor.run — throughput, p50/p99, padding waste, bitwise parity.
    Valid on CPU: the quantity under test is dispatch amortization."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_serving import measure_all
    return measure_all(smoke=not on_tpu)


def bench_decode_engine(on_tpu):
    """Stateful decode engine bench (PERF.md §13): uncached whole-sequence
    greedy vs the paged-KV continuous-batching engine vs drain-then-refill
    wave batching, on a heavy-tailed mixed-length workload — tokens/s,
    slot occupancy, prefill/decode split, bitwise token parity — plus the
    sampled-replay section (pinned request_ids run twice, bitwise) and
    speculative decoding vs lockstep (n-gram drafts, batched (S, k)
    verify). Valid on CPU: the quantity under test is scheduling + shape
    discipline."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_decode import measure_all
    return measure_all(smoke=not on_tpu)


def bench_serving_tier(on_tpu):
    """Serving-tier bench (PERF.md §19): open-loop Poisson p50/p99 through
    the multi-replica router (1 vs 2 replicas), prefix-cache hit rate +
    prefill-compute-saved on a shared-system-prompt workload, disaggregated
    handoff parity, and a zero-drop failover drill. Valid on CPU: routing,
    caching, and scheduling are the quantities under test."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_router import measure_all
    return measure_all(smoke=not on_tpu)


def bench_async_pipeline(on_tpu):
    """Async train-loop pipeline A/B (PERF.md §12): host-bound reader +
    compute-bound step, sync (per-step np.asarray) vs the K=2 in-flight
    FetchHandle window, plus the zero-copy staged-feed check. Valid on
    CPU: the quantity under test is host/device overlap, not FLOPs."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_pipeline import measure_all
    return measure_all(smoke=not on_tpu)


def bench_pipeline_parallel(on_tpu):
    """Pipeline-parallel schedules (PERF.md "Pipeline parallelism"):
    GPipe vs 1F1B at the same auto-cut — bitwise loss parity, predicted
    (staged planner) AND measured (XLA memory_analysis) peak residency,
    and auto-cut quality vs every manual cut on bert_layer. Valid on
    CPU: parity, planner-vs-XLA agreement and cut quality are
    host-independent; steps/s is trend-only."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_pp import measure_all
    return measure_all(smoke=not on_tpu)


def bench_resilience(on_tpu):
    """Checkpoint stall + restart lost-work (PERF.md §14) and self-healing
    (PERF.md §15): async checkpointing must add < 1 step of stall, the
    supervisor+watchdog must be ≤2% on the healthy path, and neither may
    ever perturb the losses. Valid on CPU: host/IO overlap under test."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_resilience import measure_all
    return measure_all(smoke=not on_tpu)


def bench_elastic(on_tpu):
    """Elastic runtime (ISSUE 19): the autoscaler's Poisson ramp drill
    (replica count follows load, zero drops through scale-up/drain, every
    decision recorded with its trigger) and the goodput resize-vs-crash
    bucket separation. Valid on CPU: control-loop and accounting
    behaviour are the quantities under test."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from bench_elastic import measure_all
    return measure_all(smoke=not on_tpu)


def bench_collectives_section(on_tpu):
    """Quantized + bucketed gradient collectives (PERF.md §16). Runs in a
    SUBPROCESS: the 8-device virtual CPU mesh needs XLA_FLAGS set before
    backend init, which this process has already done. Valid on CPU: the
    headline number is telemetry-counted bytes-on-wire reduction (≥3.5×
    int8 acceptance), which is backend-independent."""
    import subprocess
    env = dict(os.environ)
    if not on_tpu:
        env['JAX_PLATFORMS'] = 'cpu'
    flags = env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)), 'tools',
                      'bench_collectives.py')]
        + ([] if on_tpu else ['--smoke']),
        env=env, capture_output=True, text=True, timeout=1200)
    if r.returncode != 0:
        raise RuntimeError(f'bench_collectives failed: {r.stderr[-2000:]}')
    out = {}
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith('{'):
            d = json.loads(line)
            out[d['bench']] = d
    return out


def bench_fleet_section(on_tpu):
    """Fleet weak scaling (PERF.md §18). Runs in a SUBPROCESS per fleet
    size: each worker is a REAL jax.distributed process (gloo CPU
    collectives) through the executor spine. Valid on CPU: the quantity
    under test is the fleet runtime's overhead against perfect
    timesharing (samples/s-normalized weak-scaling efficiency), which is
    the transferable number; acceptance ≥0.8 at nproc=2 for the
    compute-bound recipe."""
    import subprocess
    env = dict(os.environ)
    if not on_tpu:
        env['JAX_PLATFORMS'] = 'cpu'
    env.pop('XLA_FLAGS', None)        # workers own one device each
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)), 'tools',
                      'bench_fleet.py'), '--nprocs', '1,2,4']
        + ([] if on_tpu else []),
        env=env, capture_output=True, text=True, timeout=1500)
    if r.returncode != 0:
        raise RuntimeError(f'bench_fleet failed: {r.stderr[-2000:]}')
    out = {}
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith('{'):
            d = json.loads(line)
            if d['bench'] == 'fleet_weak_scaling_summary':
                out = d
    return out


def bench_telemetry_sidecar(on_tpu):
    """Telemetry sidecar for the bench run: the headline benches above run
    with telemetry off (their numbers stay comparable across PRs), then the
    on-vs-off eager A/B from bench_dispatch runs here — its enabled half
    populates the metrics registry — and the registry dict export is written
    next to the BENCH_*.json evidence."""
    from bench_dispatch import measure_telemetry_overhead
    from paddle_tpu import observability as obs
    ab = measure_telemetry_overhead(iters=4 if on_tpu else 2, smoke=True)
    sidecar = {
        'telemetry_overhead': ab,
        'metrics': obs.registry.to_dict(),
    }
    out_dir = os.environ.get('PADDLE_TPU_METRICS_DIR') or os.getcwd()
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, 'BENCH_telemetry.json')
    with open(path, 'w') as f:
        json.dump(sidecar, f, indent=1)
    return {'path': path, 'on_over_off': ab['on_over_off']}


def main():
    jax, devices, backend = init_backend_or_die()
    on_tpu = backend != 'cpu'
    dev = devices[0]
    chip = getattr(dev, 'device_kind', str(dev))
    peak = chip_peak_tflops(dev) if on_tpu else None
    emit({"metric": "backend_init", "backend": backend, "chip": chip,
          "chip_peak_bf16_tflops": peak})

    failures = []
    summary = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": None, "unit": "images/sec/chip", "vs_baseline": None,
        "mfu": None, "bert_base_seq_per_sec": None, "bert_mfu": None,
        "chip": chip, "chip_peak_bf16_tflops": peak,
    }

    def run(name, fn):
        try:
            return fn()
        except Exception as e:  # print the section's own error, keep going
            traceback.print_exc(file=sys.stderr)
            emit({"metric": name, "error": f"{type(e).__name__}: {e}"})
            failures.append(name)
            return None

    r = run("resnet50_train_images_per_sec_per_chip",
            lambda: bench_resnet(on_tpu))
    if r is not None:
        mfu = (r * RESNET50_TRAIN_GFLOP_PER_IMG / 1e3 / peak) if peak \
            else None
        summary.update(value=round(r, 2),
                       vs_baseline=round(r / V100_BASELINE_IMG_S, 3),
                       mfu=round(mfu, 4) if mfu else None)
        emit({"metric": "resnet50_train_images_per_sec_per_chip",
              "value": summary["value"], "unit": "images/sec/chip",
              "vs_baseline": summary["vs_baseline"], "mfu": summary["mfu"]})

    b = run("bert_base_seq_per_sec", lambda: bench_bert(on_tpu))
    if b is not None:
        seq_s, flops_per_seq = b
        bert_mfu = (seq_s * flops_per_seq / 1e12 / peak) if peak else None
        summary.update(bert_base_seq_per_sec=round(seq_s, 2),
                       bert_mfu=round(bert_mfu, 4) if bert_mfu else None)
        emit({"metric": "bert_base_seq_per_sec",
              "value": summary["bert_base_seq_per_sec"], "unit": "seq/sec",
              "mfu": summary["bert_mfu"]})

    t = run("transformer_big_tokens_per_sec",
            lambda: bench_transformer_big(on_tpu))
    if t is not None:
        tok_s, flops_per_tok = t
        t_mfu = (tok_s * flops_per_tok / 1e12 / peak) if peak else None
        summary.update(transformer_big_tokens_per_sec=round(tok_s, 1),
                       transformer_big_mfu=round(t_mfu, 4) if t_mfu
                       else None)
        emit({"metric": "transformer_big_tokens_per_sec",
              "value": summary["transformer_big_tokens_per_sec"],
              "unit": "tokens/sec", "mfu": summary.get("transformer_big_mfu")})

    e = run("ernie_finetune_seq_per_sec", lambda: bench_ernie(on_tpu))
    if e is not None:
        seq_s, flops_per_seq = e
        e_mfu = (seq_s * flops_per_seq / 1e12 / peak) if peak else None
        summary.update(ernie_finetune_seq_per_sec=round(seq_s, 2),
                       ernie_mfu=round(e_mfu, 4) if e_mfu else None)
        emit({"metric": "ernie_finetune_seq_per_sec",
              "value": summary["ernie_finetune_seq_per_sec"],
              "unit": "seq/sec", "mfu": summary.get("ernie_mfu")})

    d = run("dygraph_eager_overhead", lambda: bench_dispatch_overhead(on_tpu))
    if d is not None:
        rb, bl = d['resnet_block'], d['bert_layer']
        emit({"metric": "dygraph_eager_overhead",
              "resnet_block": rb, "bert_layer": bl})
        summary.update(
            eager_cache_speedup_resnet_block=rb["cache_speedup"],
            eager_vs_fused_resnet_block=rb["eager_cached_vs_fused"])

    p = run("ir_pass_pipeline", lambda: bench_ir_passes(on_tpu))
    if p is not None:
        emit({"metric": "ir_pass_pipeline",
              "mlp_adam": p['mlp_adam'], "resnet_block": p['resnet_block'],
              "bert_layer": p['bert_layer'],
              "executor_compile": p['executor_compile']})
        summary.update(
            ir_pass_eqn_reduction_mlp_adam=p['mlp_adam']['eqn_reduction'],
            ir_pass_trace_lower_speedup_mlp_adam=(
                p['mlp_adam']['trace_lower_speedup']))

    sv = run("serving_batcher", lambda: bench_serving_batcher(on_tpu))
    if sv is not None:
        emit({"metric": "serving_batcher",
              "serial": sv['serial'], "batcher": sv['batcher'],
              "overload": sv['overload']})
        summary.update(
            serving_batcher_speedup=sv['batcher']['speedup_vs_serial'],
            serving_batcher_p99_ms=sv['batcher']['p99_ms'])

    de = run("decode_engine", lambda: bench_decode_engine(on_tpu))
    if de is not None:
        emit({"metric": "decode_engine",
              "uncached": de['uncached'], "continuous": de['continuous'],
              "drain": de['drain'], "sampled": de['sampled'],
              "speculative": de['speculative'],
              "kv_quant": de['kv_quant']})
        summary.update(
            decode_continuous_vs_drain=de['continuous']['speedup_vs_drain'],
            decode_tokens_per_s=de['continuous']['tokens_per_s'],
            decode_bitwise=de['continuous']['bitwise_equal'])
        summary.update(
            spec_decode_vs_lockstep=de['speculative']['speedup_vs_lockstep'],
            spec_decode_acceptance=de['speculative']['acceptance'],
            spec_decode_bitwise=de['speculative']['bitwise_equal'],
            decode_sampled_replayable=de['sampled']['replayable'])
        kv = de['kv_quant']
        summary.update(
            kv_quant_hbm_bytes_f32_over_int8=kv['hbm_bytes_f32_over_int8'],
            kv_quant_int8_match_rate=(
                kv['per_dtype']['int8']['match_rate_vs_f32']),
            kv_quant_f32_bitwise=kv['per_dtype']['f32']['bitwise_equal'],
            kv_quant_int8_slots_per_chip=kv['slots_per_chip']['int8'])

    st = run("serving_tier", lambda: bench_serving_tier(on_tpu))
    if st is not None:
        emit({"metric": "serving_tier",
              "scaling": st['scaling'], "prefix_cache": st['prefix_cache'],
              "disagg": st['disagg'], "failover": st['failover']})
        summary.update(
            serving_tier_hit_rate=st['prefix_cache']['cache_on']['hit_rate'],
            serving_tier_prefill_tokens_saved=(
                st['prefix_cache']['cache_on']['prefill_tokens_saved']),
            serving_tier_cache_speedup=st['prefix_cache']['speedup'],
            serving_tier_failover_dropped=st['failover']['dropped'],
            serving_tier_bitwise=(
                st['prefix_cache']['cache_on']['bitwise_equal']
                and st['disagg']['bitwise_equal']))

    pl = run("async_pipeline", lambda: bench_async_pipeline(on_tpu))
    if pl is not None:
        emit({"metric": "async_pipeline",
              "async_pipeline": pl['async_pipeline'],
              "staged_feeds": pl['staged_feeds']})
        summary.update(
            async_pipeline_speedup=pl['async_pipeline']['speedup'],
            async_pipeline_bitwise=pl['async_pipeline']
            ['bitwise_identical'])

    pp = run("pipeline_parallel", lambda: bench_pipeline_parallel(on_tpu))
    if pp is not None:
        emit({"metric": "pipeline_parallel",
              "schedules": pp['schedules'], "autocut": pp['autocut']})
        summary.update(
            pp_bitwise=pp['schedules']['bitwise_identical'],
            pp_1f1b_peak_le_gpipe=(
                pp['schedules']['predicted_1f1b_le_gpipe']
                and pp['schedules']['measured_1f1b_le_gpipe']),
            pp_autocut_within_tolerance=pp['autocut']
            ['within_tolerance'])

    rz = run("resilience", lambda: bench_resilience(on_tpu))
    if rz is not None:
        emit({"metric": "resilience",
              "stall": rz['resilience_stall'],
              "restart": rz['resilience_restart'],
              "supervised": rz['resilience_supervised'],
              "nan_recovery": rz['resilience_nan_recovery']})
        summary.update(
            ckpt_stall_steps=rz['resilience_stall']['async_stall_steps'],
            ckpt_bitwise=rz['resilience_stall']['bitwise_identical'],
            supervisor_overhead_frac=rz['resilience_supervised']
            ['overhead_frac'],
            supervisor_bitwise=rz['resilience_supervised']
            ['bitwise_identical'],
            nan_recovery_ok=rz['resilience_nan_recovery']['recovered'])

    el = run("elastic", lambda: bench_elastic(on_tpu))
    if el is not None:
        emit({"metric": "elastic",
              "autoscale_ramp": el['elastic_autoscale_ramp'],
              "resize_accounting": el['elastic_resize_accounting']})
        summary.update(
            elastic_autoscale_dropped=el['elastic_autoscale_ramp']
            ['dropped'],
            elastic_autoscale_bitwise=el['elastic_autoscale_ramp']
            ['bitwise_equal'],
            elastic_max_replicas_seen=el['elastic_autoscale_ramp']
            ['max_replicas_seen'],
            elastic_resize_buckets_separate=el['elastic_resize_accounting']
            ['buckets_separate'])

    co = run("collectives", lambda: bench_collectives_section(on_tpu))
    if co is not None:
        emit({"metric": "collectives",
              "bytes": co['collectives_bytes'],
              "steps": co['collectives_steps'],
              "convergence": co['collectives_convergence'],
              "bucketing": co['collectives_bucketing']})
        summary.update(
            collective_bytes_reduction_int8=co['collectives_bytes']
            ['bytes_reduction_int8'],
            collective_convergence_parity=co['collectives_convergence']
            ['parity'],
            collective_bucketing_bitwise=co['collectives_bucketing']
            ['bitwise_identical'])

    vo = run("verify_overhead", lambda: bench_verify_overhead(on_tpu))
    if vo is not None:
        emit({"metric": "verify_overhead",
              "overhead": vo['verify_overhead'],
              "pipeline_ab": vo['verify_pipeline_ab']})
        summary.update(
            verify_frac_of_compile=vo['verify_overhead']
            ['verify_frac_of_compile'],
            verify_warm_step_ratio=vo['verify_overhead']
            ['warm_step_ratio'])

    mp = run("memory_plan", lambda: bench_memory_plan(on_tpu))
    if mp is not None:
        emit({"metric": "memory_plan",
              "latency": mp['plan_latency'], "remat": mp['plan_remat']})
        summary.update(
            plan_frac_of_compile=mp['plan_latency']
            ['plan_frac_of_compile'],
            auto_remat_fits_budget=mp['plan_remat']['fits_budget'],
            auto_remat_bitwise=mp['plan_remat']['bitwise_identical'])

    pt = run("partitioner", lambda: bench_partitioner(on_tpu))
    if pt is not None:
        emit({"metric": "partitioner",
              "spec_resolution": pt['partition_spec_resolution'],
              "parity": pt['partition_parity'],
              "composition": pt['partition_composition']})
        summary.update(
            partition_resolve_s=pt['partition_spec_resolution']
            ['resolve_s'],
            partition_parity_ok=pt['partition_parity']['ok'],
            partition_composition_ok=pt['partition_composition']['ok'])

    fw = run("fleet_runtime", lambda: bench_fleet_section(on_tpu))
    if fw is not None:
        emit({"metric": "fleet_runtime",
              "steps_per_s": fw.get('steps_per_s'),
              "samples_per_s": fw.get('samples_per_s'),
              "efficiency": fw.get('efficiency')})
        summary.update(
            fleet_efficiency_nproc2=fw.get('efficiency_nproc2'),
            fleet_acceptance_ge_0_8=fw.get('acceptance_ge_0_8'))

    se = run("sparse_embedding", lambda: bench_sparse_section(on_tpu))
    if se is not None:
        big = se.get('sparse_step_time_v1000000', {})
        wire = se.get('sparse_bytes_on_wire', {})
        emit({"metric": "sparse_embedding",
              "step_time": big,
              "lookup": se.get('sparse_lookup_throughput'),
              "bytes_on_wire": wire,
              "executor_parity": se.get('sparse_executor_parity')})
        summary.update(
            sparse_over_dense_v1e6=big.get('sparse_over_dense'),
            sparse_dense_over_int8_bytes=wire.get('dense_over_sparse_int8'),
            sparse_f32_over_int8_bytes=wire.get('sparse_f32_over_int8'),
            sparse_parity_ok=se.get('sparse_executor_parity',
                                    {}).get('ok'))

    s = run("telemetry_sidecar", lambda: bench_telemetry_sidecar(on_tpu))
    if s is not None:
        emit({"metric": "telemetry_sidecar", "path": s["path"],
              "telemetry_on_over_off": s["on_over_off"]})

    emit(summary)  # last line: the original ONE-JSON-line driver contract
    if failures:
        sys.exit(1)


if __name__ == '__main__':
    main()
