"""Benchmark driver: ResNet-50 fwd+bwd+update images/sec/chip (bf16 compute).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline (BASELINE.json north star): CUDA V100 ResNet-50 ≈ 383 img/s fp32
(PaddlePaddle's published reference-class number for the 1.x benchmark suite).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

V100_BASELINE_IMG_S = 383.0


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.models import ResNet50
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.dygraph.tape import dispatch_op

    on_tpu = jax.default_backend() != 'cpu'
    batch = 256 if on_tpu else 8
    img = 224 if on_tpu else 32
    iters = 20 if on_tpu else 3

    with dygraph.guard():
        model = ResNet50(class_dim=1000)
        opt = fluid.optimizer.Momentum(0.1, momentum=0.9,
                                       parameter_list=model.parameters())

        def loss_fn(m, x, y):
            logits = m(x)
            logits = dispatch_op('cast', {'x': logits}, {'dtype': 'float32'})
            l, _ = dispatch_op('softmax_with_cross_entropy',
                               {'logits': logits, 'label': y}, {})
            return dispatch_op('reduce_mean', {'x': l}, {})

        # bf16 compute with fp32 master weights (AMP) on TPU; param dtypes
        # stay fp32 across steps so the fused step compiles exactly once
        step = TrainStep(model, loss_fn, opt,
                         amp_dtype=jnp.bfloat16 if on_tpu else None)
        dtype = np.float32
        x = np.random.randn(batch, 3, img, img).astype(dtype)
        y = np.random.randint(0, 1000, (batch, 1)).astype(np.int64)
        if on_tpu:
            x = jnp.asarray(x, jnp.bfloat16)

        # warmup/compile; float() forces a device→host transfer, which is
        # the only reliable barrier on the axon remote backend
        # (block_until_ready returns before remote execution finishes)
        l = step(x, y)
        float(l)
        t0 = time.perf_counter()
        for _ in range(iters):
            l = step(x, y)
        float(l)
        dt = time.perf_counter() - t0
        img_per_sec = batch * iters / dt

    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / V100_BASELINE_IMG_S, 3),
    }))


if __name__ == '__main__':
    main()
