"""Partitioner bench: spec-resolution throughput + composition parity.

Three sections (one JSON line each, like the sibling bench tools):

- ``partition_spec_resolution`` — wall time for the Partitioner to
  resolve a PartitionSpec for EVERY persistable + activation of a real
  recipe Program (the multi-param Adam MLP bench_passes builds), zero
  tracing: this is the per-compile-cache-miss cost the Executor pays
  when lowering a partitioned program. Reported per-Program and per-var.
- ``partition_parity`` — the refactored spec paths agree with the
  retired per-module plumbing: `fsdp.fsdp_spec` ≡ partitioner fsdp
  rule over a shape battery, Megatron marker specs ≡
  `tensor_parallel.megatron_param_spec`, and the data spec composes
  over dp×fsdp. Assertion failures exit non-zero.
- ``partition_composition`` — SpmdTrainStep dp×fsdp and dp×tp smoke
  training vs a single-device reference (allclose), with the
  quantized-collective sync-call counters asserted (the PR 9 path).

  JAX_PLATFORMS=cpu python tools/bench_partition.py [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()


def emit(obj):
    print(json.dumps(obj), flush=True)          # lint: allow-print (CLI)


def _build_recipe(smoke):
    sys.path.insert(0, os.path.join(_REPO, 'tools'))
    from bench_passes import build_mlp_adam
    return build_mlp_adam(smoke=smoke)


def measure_spec_resolution(iters=20, smoke=False):
    import paddle_tpu  # noqa: F401
    from paddle_tpu import partition
    main, _startup, _make_feed, _fetch = _build_recipe(smoke)
    p = partition.Partitioner(mesh_shape={'dp': 2, 'fsdp': 4})
    ts = []
    specs = {}
    for _ in range(iters):
        t0 = time.perf_counter()
        specs = p.program_specs(main, include_activations=True)
        ts.append(time.perf_counter() - t0)
    med = statistics.median(ts)
    return {'bench': 'partition_spec_resolution',
            'ops': main.num_ops(),
            'vars_resolved': len(specs),
            'resolve_s': round(med, 6),
            'vars_per_s': round(len(specs) / med) if med else None}


def measure_parity():
    import numpy as np
    import paddle_tpu  # noqa: F401
    from paddle_tpu import partition
    from paddle_tpu.parallel import fsdp as F
    from paddle_tpu.parallel.tensor_parallel import megatron_param_spec
    checked = 0
    mesh = partition.make_mesh({'fsdp': 8})
    p = partition.Partitioner(mesh=mesh)
    rng = np.random.RandomState(0)
    shapes = [(64, 32), (32, 64), (8,), (3, 5), (1,), (16, 16, 4),
              (24, 7), (7, 24), (8, 8)]
    for s in shapes:
        assert p.fsdp_spec(s) == F.fsdp_spec(s, mesh), s
        checked += 1
    tp_mesh = partition.make_mesh({'tp': 8})
    p = partition.Partitioner(mesh=tp_mesh)
    for name in ('layer.ffn1.w', 'enc.q_proj.w', 'blk.ffn2.w',
                 'att.out_proj.w', 'plain.w'):
        arr = rng.randn(64, 32).astype('float32')
        assert tuple(p.param_spec(name, arr.shape)) == tuple(
            megatron_param_spec(name, arr)), name
        checked += 1
    p = partition.Partitioner(mesh_shape={'dp': 2, 'fsdp': 4})
    assert tuple(p.data_spec(16)) == (('dp', 'fsdp'),)
    assert p.data_axes() == ('dp', 'fsdp')
    checked += 2
    return {'bench': 'partition_parity', 'assertions': checked, 'ok': True}


def _reference_sgd(loss_fn, params, batch, lr, steps):
    import jax
    import jax.numpy as jnp
    ps = {k: jnp.asarray(v) for k, v in params.items()}
    losses = []
    for _ in range(steps):
        l, g = jax.value_and_grad(loss_fn)(ps, jnp.asarray(batch))
        ps = {k: v - lr * g[k] for k, v in ps.items()}
        losses.append(float(l))
    return losses, ps


def measure_composition(smoke=False, steps=4):
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu  # noqa: F401
    from paddle_tpu import observability as obs, partition
    from paddle_tpu.partition.spmd_step import SpmdTrainStep
    from paddle_tpu.parallel.tensor_parallel import mp_allreduce, mp_copy
    d = 16 if smoke else 64
    h = 32 if smoke else 256
    b = 16 if smoke else 64
    lr = 0.1
    rng = np.random.RandomState(0)
    W1 = (rng.randn(d, h) * 0.1).astype('float32')
    W2 = (rng.randn(h, 1) * 0.1).astype('float32')
    bias = np.zeros((1,), 'float32')
    X = rng.randn(b, d).astype('float32')
    batch = np.concatenate([X, X[:, :1]], axis=1)

    def ref_loss(ps, bt):
        x, y = bt[:, :-1], bt[:, -1:]
        hh = jnp.maximum(x @ ps['ffn1.w'], 0.0)
        return jnp.mean(((hh @ ps['ffn2.w'] + ps['b']) - y) ** 2)

    ref_losses, _ = _reference_sgd(
        ref_loss, {'ffn1.w': W1, 'ffn2.w': W2, 'b': bias}, batch, lr, steps)

    out = {'bench': 'partition_composition', 'steps': steps}
    with obs.telemetry_guard(True):
        # dp×fsdp: fc weights tile over fsdp, bias buckets over dp+fsdp
        obs.reset()
        p = partition.Partitioner(mesh_shape={'dp': 2, 'fsdp': 4})
        step = SpmdTrainStep(ref_loss, {'ffn1.w': W1, 'ffn2.w': W2,
                                        'b': bias}, partitioner=p, lr=lr)
        fsdp_losses = [float(step(batch)) for _ in range(steps)]
        m = obs.registry.to_dict()
        calls = sum(s['value'] for s in
                    m['collective_sync_calls']['samples']
                    if s['labels'].get('path') == 'spmd_step')
        np.testing.assert_allclose(fsdp_losses, ref_losses,
                                   rtol=5e-4, atol=1e-5)
        assert calls == step.sync_calls_per_step * steps
        out['dp_fsdp_max_rel_err'] = float(np.max(np.abs(
            (np.asarray(fsdp_losses) - np.asarray(ref_losses))
            / np.asarray(ref_losses))))
        out['dp_fsdp_sync_calls_per_step'] = step.sync_calls_per_step

        # dp×tp: Megatron col+row MLP via the f/g conjugate collectives
        def tp_loss(ps, bt):
            x, y = bt[:, :-1], bt[:, -1:]
            x = mp_copy(x, 'tp')
            hh = jnp.maximum(x @ ps['ffn1.w'], 0.0)
            part = hh @ ps['ffn2.w']
            return jnp.mean(((mp_allreduce(part, 'tp') + ps['b']) - y) ** 2)

        obs.reset()
        p = partition.Partitioner(mesh_shape={'dp': 2, 'tp': 4})
        step = SpmdTrainStep(tp_loss, {'ffn1.w': W1, 'ffn2.w': W2,
                                       'b': bias}, partitioner=p, lr=lr)
        tp_losses = [float(step(batch)) for _ in range(steps)]
        np.testing.assert_allclose(tp_losses, ref_losses,
                                   rtol=5e-4, atol=1e-5)
        out['dp_tp_max_rel_err'] = float(np.max(np.abs(
            (np.asarray(tp_losses) - np.asarray(ref_losses))
            / np.asarray(ref_losses))))
        out['dp_tp_sync_calls_per_step'] = step.sync_calls_per_step
    out['ok'] = True
    return out


def measure_all(smoke=False, iters=None):
    """All sections as one dict (bench.py's `partitioner` line)."""
    return {
        'partition_spec_resolution': measure_spec_resolution(
            iters=iters or (3 if smoke else 20), smoke=smoke),
        'partition_parity': measure_parity(),
        'partition_composition': measure_composition(smoke=smoke),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--smoke', action='store_true',
                    help='tiny sizes + few iters (tier-1 CI)')
    args = ap.parse_args(argv)
    iters = 3 if args.smoke else 20
    res = measure_spec_resolution(iters=iters, smoke=args.smoke)
    emit(res)
    emit(measure_parity())
    emit(measure_composition(smoke=args.smoke))
    emit({'bench': 'partition_summary',
          'resolve_s': res['resolve_s'],
          'vars_per_s': res['vars_per_s'], 'ok': True})
    return 0


if __name__ == '__main__':
    sys.exit(main())
