"""Repo-level codebase lint: AST-enforced paddle_tpu/ invariants.

Three rules, each an invariant this repo adopted in an earlier PR and
until now enforced only by review:

- ``bare-print`` — framework code never ``print()``s (PR 2: everything
  routes through log_helper so headless runs can capture it). Exempt:
  ``paddle_tpu/utils/`` (console probe CLIs). Deliberate console APIs
  carry an inline ``# lint: allow-print (<reason>)`` marker.
- ``atomic-io`` — model/param payload writes (``np.savez`` /
  ``np.save``) go through the PR 7 torn-write-proof helpers
  (io._atomic_savez or the resilience/snapshot.py commit protocol);
  a bare savez can leave a half-written artifact after ``kill -9``.
  Exempt: the two atomic-commit homes themselves.
- ``jit-compile-cache`` — modules calling ``jax.jit`` must ensure the
  persistent cross-process XLA compile cache is configured
  (core.compile_cache.setup_persistent_cache); a stray jit in a process
  that never built an Executor recompiles from scratch on every run.
  Lower-only jits (no XLA compile) carry ``# lint: allow-jit``.
- ``mesh-construction`` — ``jax.sharding.Mesh`` objects are built ONLY
  inside ``paddle_tpu/partition/`` (PR 11: the unified SPMD partitioner
  owns the device mesh; hand-rolled per-module meshes are exactly the
  plumbing it retired). Everything else resolves meshes through
  ``partition.get_partitioner()`` / the ``partition.make_mesh`` builders.

Suppression: ``# lint: allow-<rule>`` on the violating line or the line
directly above it. Run:

    python tools/lint_codebase.py [--root REPO] [--json]

Exit 0 = clean, 1 = violations. tier-1 runs this via
tests/framework/test_lint_codebase.py.
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import List, NamedTuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rule name → dirs/files (relative to paddle_tpu/) exempt from it
EXEMPT = {
    'bare-print': ('utils/',),
    'atomic-io': ('io.py', 'resilience/snapshot.py'),
    'jit-compile-cache': (),
    'mesh-construction': ('partition/',),
}


class Violation(NamedTuple):
    rule: str
    path: str          # repo-relative
    line: int
    message: str

    def format(self):
        return f'{self.path}:{self.line}: [{self.rule}] {self.message}'


def _suppressed(lines, lineno, rule):
    tag = {'bare-print': 'lint: allow-print',
           'atomic-io': 'lint: allow-io',
           'jit-compile-cache': 'lint: allow-jit',
           'mesh-construction': 'lint: allow-mesh'}[rule]
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and tag in lines[ln - 1]:
            return True
    return False


def _dotted(node):
    """'np.savez' / 'jax.jit' style dotted name of a call target."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return '.'.join(reversed(parts))


_SAVE_CALLS = {'np.savez', 'np.savez_compressed', 'np.save',
               'numpy.savez', 'numpy.savez_compressed', 'numpy.save'}


def lint_file(path, rel):
    src = open(path, encoding='utf-8').read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Violation('syntax', rel, e.lineno or 0, str(e))]
    lines = src.splitlines()
    has_cache_setup = 'setup_persistent_cache' in src
    out: List[Violation] = []

    def exempt(rule):
        sub = rel.split('paddle_tpu/', 1)[1] if 'paddle_tpu/' in rel else rel
        return any(sub == e or sub.startswith(e) for e in EXEMPT[rule])

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target = _dotted(node.func)
        if target == 'print' and not exempt('bare-print') \
                and not _suppressed(lines, node.lineno, 'bare-print'):
            out.append(Violation(
                'bare-print', rel, node.lineno,
                'framework code must log via log_helper, not print() '
                '(mark deliberate console APIs with '
                '"# lint: allow-print (<reason>)")'))
        elif target in _SAVE_CALLS and not exempt('atomic-io') \
                and not _suppressed(lines, node.lineno, 'atomic-io'):
            out.append(Violation(
                'atomic-io', rel, node.lineno,
                f'{target}() writes non-atomically; route payload saves '
                f'through io._atomic_savez (PR 7 torn-write protocol)'))
        elif target == 'jax.jit' and not has_cache_setup \
                and not exempt('jit-compile-cache') \
                and not _suppressed(lines, node.lineno, 'jit-compile-cache'):
            out.append(Violation(
                'jit-compile-cache', rel, node.lineno,
                'jax.jit without core.compile_cache.setup_persistent_cache '
                'in this module bypasses the persistent XLA compile cache'))
        elif (target == 'Mesh' or target.endswith('.Mesh')) \
                and not exempt('mesh-construction') \
                and not _suppressed(lines, node.lineno, 'mesh-construction'):
            out.append(Violation(
                'mesh-construction', rel, node.lineno,
                'direct Mesh() construction outside paddle_tpu/partition/ '
                'hand-rolls mesh plumbing the unified partitioner owns; '
                'use partition.make_mesh / get_partitioner() (mark '
                'deliberate cases with "# lint: allow-mesh (<reason>)")'))
    return out


def lint_tree(root=_REPO):
    pkg = os.path.join(root, 'paddle_tpu')
    violations: List[Violation] = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != '__pycache__')
        for fn in sorted(filenames):
            if not fn.endswith('.py'):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            violations.extend(lint_file(path, rel))
    return violations


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--root', default=_REPO)
    ap.add_argument('--json', action='store_true')
    args = ap.parse_args(argv)
    violations = lint_tree(args.root)
    if args.json:
        print(json.dumps([v._asdict() for v in violations], indent=1))
    else:
        for v in violations:
            print(v.format())
        print(f'{len(violations)} violation(s) in paddle_tpu/')
    return 1 if violations else 0


if __name__ == '__main__':
    sys.exit(main())
