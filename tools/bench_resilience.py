"""Resilience bench (PERF.md §14 + §15): checkpoint stall, restart lost
work, supervised healthy-path overhead, and NaN-recovery time.

Four claims under measurement (docs/RESILIENCE.md):

1. **Async checkpointing adds < 1 step of stall.** The same compute-bound
   static training loop runs three ways from one initial state: no
   checkpointing (baseline), async checkpointing every K steps (the
   production path: non-blocking donation-protected capture + background
   writer), and BLOCKING checkpointing every K steps (the strawman: the
   loop materializes and writes synchronously). We report per-step p99 and
   the stall attributable to checkpoint steps; acceptance is
   ``async stall < 1 × baseline median step`` — and the checkpointed run's
   losses must stay BITWISE equal to the baseline's (checkpointing must
   observe the state, never perturb it).

2. **Restart lost work is bounded by the cadence.** A run that
   checkpoints every K steps and dies at step N loses N − K⌊N/K⌋ steps;
   we restore in a fresh manager and report the lost-work accounting the
   goodput tracker books from the progress heartbeat.

3. **Supervision is ~free on the healthy path.** The same loop runs bare
   vs supervised (divergence detector on, watchdog armed with per-step
   leases on the executor AND the boundary): acceptance is ≤ 2% median
   step-time overhead at full size, with BITWISE-identical losses
   (ISSUE 8; PERF.md §15).

4. **Recovery from an injected NaN is fast and exact.** `nan@step=N`
   under policy=rollback restores the last good checkpoint; we report the
   restore wall time and the resumed-from step.

Valid on CPU — both quantities are host/IO behavior, not FLOPs:

  JAX_PLATFORMS=cpu python tools/bench_resilience.py [--smoke] [--steps N]
      [--every K]

Acceptance (tier-1, tests/framework/test_bench_resilience.py): async
stall_steps < 1.0 with bitwise-identical losses, and measured lost work ==
expected from the cadence.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_mlp(smoke=False):
    """Compute-bound RNG-free MLP + SGD (bitwise parity by construction)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    width, depth, bs = (512, 4, 128) if smoke else (1024, 8, 256)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('res_x', [784], dtype='float32')
        y = L.data('res_y', [1], dtype='float32')
        h = x
        for _ in range(depth):
            h = L.fc(h, size=width, act='relu')
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=1e-3).minimize(loss)
    return main, startup, bs, loss


def _feeds(bs, steps, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    return [{'res_x': rng.randn(bs, 784).astype(np.float32),
             'res_y': rng.randn(bs, 1).astype(np.float32)}
            for _ in range(steps)]


def _p(times, q):
    s = sorted(times)
    return s[min(len(s) - 1, int(q * len(s)))]


def _loop(exe, main, loss, feeds, mgr=None, every=0, capture=None):
    """One timed loop; returns (per-step seconds, loss bytes)."""
    import numpy as np
    times, losses = [], []
    step = 0
    for feed in feeds:
        t0 = time.perf_counter()
        lv = exe.run(main, feed=feed, fetch_list=[loss])[0]
        step += 1
        if mgr is not None and every and step % every == 0:
            mgr.end_of_step(step, capture)
        times.append(time.perf_counter() - t0)
        losses.append(np.asarray(lv).tobytes())
    return times, losses


def measure_stall(smoke=False, steps=None, every=None):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import resilience
    import tempfile

    main, startup, bs, loss = build_mlp(smoke)
    steps = steps or (24 if smoke else 48)
    every = every or 6
    feeds = _feeds(bs, steps)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        snap0 = {v.name: np.asarray(scope.find(v.name))
                 for v in main.list_vars() if v.persistable}

        def restore0():
            import jax.numpy as jnp
            for n, v in snap0.items():
                scope.set(n, jnp.asarray(v))

        def capture():
            return resilience.capture_training_state(
                executor=exe, program=main, scope=scope)

        # warm BOTH compiled variants: the plain donating step AND the
        # snapshot-protected (nothing-donated) step the first checkpoint
        # boundary switches to
        exe.run(main, feed=feeds[0], fetch_list=[loss])
        handles = exe.snapshot_persistables(main, scope)
        exe.run(main, feed=feeds[0], fetch_list=[loss])
        for h in handles.values():
            np.asarray(h)
        exe.run(main, feed=feeds[0], fetch_list=[loss])

        restore0()
        base_t, base_l = _loop(exe, main, loss, feeds)

        restore0()
        with tempfile.TemporaryDirectory() as d:
            mgr = resilience.CheckpointManager(
                d, keep=2, async_save=True, install_signal_handlers=False)
            async_t, async_l = _loop(exe, main, loss, feeds, mgr, every,
                                     capture)
            mgr.wait()
            mgr.close()

        restore0()
        with tempfile.TemporaryDirectory() as d:
            mgr = resilience.CheckpointManager(
                d, keep=2, async_save=False, install_signal_handlers=False)
            block_t, block_l = _loop(exe, main, loss, feeds, mgr, every,
                                     capture)
            mgr.close()

    base_med = _p(base_t, 0.5)
    ck_steps = [i for i in range(steps) if (i + 1) % every == 0]
    async_ck_max = max(async_t[i] for i in ck_steps)
    block_ck_max = max(block_t[i] for i in ck_steps)
    async_stall = max(0.0, async_ck_max - base_med)
    block_stall = max(0.0, block_ck_max - base_med)
    return {
        'bench': 'resilience_stall',
        'steps': steps, 'ckpt_every': every,
        'state_mb': round(sum(v.nbytes for v in snap0.values()) / 2**20, 2),
        'base_median_ms': round(base_med * 1e3, 3),
        'base_p99_ms': round(_p(base_t, 0.99) * 1e3, 3),
        'async_p99_ms': round(_p(async_t, 0.99) * 1e3, 3),
        'blocking_p99_ms': round(_p(block_t, 0.99) * 1e3, 3),
        'async_ckpt_step_max_ms': round(async_ck_max * 1e3, 3),
        'blocking_ckpt_step_max_ms': round(block_ck_max * 1e3, 3),
        'async_stall_ms': round(async_stall * 1e3, 3),
        'blocking_stall_ms': round(block_stall * 1e3, 3),
        # the acceptance number: checkpoint stall in units of one step
        'async_stall_steps': round(async_stall / base_med, 3),
        'blocking_stall_steps': round(block_stall / base_med, 3),
        'stall_lt_one_step': bool(async_stall < base_med),
        'bitwise_identical': bool(base_l == async_l == block_l),
    }


def measure_restart(smoke=False):
    """Lost-work accounting: run N steps checkpointing every K, 'crash'
    (fresh manager), restore → lost = N mod K steps, booked from the
    heartbeat."""
    import numpy as np
    from paddle_tpu import resilience
    import tempfile

    n, k = 13, 5
    state = {'w': np.ones((256,), np.float32)}
    with tempfile.TemporaryDirectory() as d:
        mgr = resilience.CheckpointManager(
            d, every_n_steps=k, keep=2, install_signal_handlers=False)
        for s in range(1, n + 1):
            mgr.end_of_step(s, lambda: (state, {}))
        mgr.wait()
        # simulated preemption: a new incarnation restores
        mgr2 = resilience.CheckpointManager(
            d, every_n_steps=k, keep=2, install_signal_handlers=False)
        arrays, meta = mgr2.restore()
        got = {
            'bench': 'resilience_restart',
            'steps_run': n, 'ckpt_every': k,
            'restored_step': meta['step'],
            'lost_steps': mgr2.goodput.lost_steps,
            'expected_lost_steps': n - k * (n // k),
            'goodput': round(mgr2.goodput.goodput(), 4),
            'restarts': mgr2.goodput.restarts,
        }
        mgr.close()
        mgr2.close()
    return got


def measure_supervised(smoke=False, steps=None):
    """Healthy-path A/B: bare loop vs supervised loop (spike/NaN detector
    on, watchdog armed: executor per-run lease + supervisor boundary
    lease). Same feeds, same initial state → losses must stay bitwise."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import resilience
    from paddle_tpu.resilience import watchdog as wdg
    import tempfile

    main, startup, bs, loss = build_mlp(smoke)
    steps = steps or (24 if smoke else 48)
    feeds = _feeds(bs, steps, seed=3)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        snap0 = {v.name: np.asarray(scope.find(v.name))
                 for v in main.list_vars() if v.persistable}

        def restore0():
            import jax.numpy as jnp
            for n, v in snap0.items():
                scope.set(n, jnp.asarray(v))

        exe.run(main, feed=feeds[0], fetch_list=[loss])   # warm compile

        def supervised_loop():
            wdg.enable(floor_s=60.0, abort=False)  # arm the per-run guards
            try:
                with tempfile.TemporaryDirectory() as d:
                    mgr = resilience.CheckpointManager(
                        d, keep=2, install_signal_handlers=False)
                    sup = resilience.TrainingSupervisor(
                        policy='rollback', manager=mgr, executor=exe,
                        program=main, scope=scope)
                    times, losses = [], []
                    step = 0
                    for feed in feeds:
                        t0 = time.perf_counter()
                        lv = exe.run(main, feed=feed, fetch_list=[loss])[0]
                        step += 1
                        sup.end_of_step(step, lv)
                        times.append(time.perf_counter() - t0)
                        losses.append(np.asarray(lv).tobytes())
                    sup.close()
                    mgr.close()
                    return times, losses
            finally:
                wdg.disable()

        # host-timing drift between back-to-back identical loops is ±2% on
        # a busy CI box — alternate bare/supervised rounds and compare
        # min-of-medians so the overhead number measures the supervisor,
        # not the machine
        base_meds, sup_meds = [], []
        base_l = sup_l = None
        for _ in range(2):
            restore0()
            base_t, base_l = _loop(exe, main, loss, feeds)
            base_meds.append(_p(base_t, 0.5))
            restore0()
            sup_t, sup_l = supervised_loop()
            sup_meds.append(_p(sup_t, 0.5))

    base_med, sup_med = min(base_meds), min(sup_meds)
    overhead = (sup_med - base_med) / base_med
    return {
        'bench': 'resilience_supervised',
        'steps': steps,
        'base_median_ms': round(base_med * 1e3, 3),
        'supervised_median_ms': round(sup_med * 1e3, 3),
        'base_p99_ms': round(_p(base_t, 0.99) * 1e3, 3),
        'supervised_p99_ms': round(_p(sup_t, 0.99) * 1e3, 3),
        # the ISSUE 8 acceptance number: ≤ 0.02 at full size
        'overhead_frac': round(overhead, 4),
        'overhead_lt_2pct': bool(overhead < 0.02),
        'bitwise_identical': bool(base_l == sup_l),
    }


def measure_nan_recovery(smoke=False):
    """Injected `nan@step=N` under policy=rollback: report detection →
    restored wall time and the exactness of the resume point."""
    import os
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import resilience
    from paddle_tpu.resilience import fault
    import tempfile

    main, startup, bs, loss = build_mlp(smoke=True)   # recovery is IO-bound
    feeds = _feeds(bs, 14, seed=5)
    nan_step, every = 9, 4
    old = os.environ.get(fault.ENV_SPEC)
    os.environ[fault.ENV_SPEC] = f'nan@step={nan_step}'
    fault.reset_injector()
    try:
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)

            def capture():
                return resilience.capture_training_state(
                    executor=exe, program=main, scope=scope)

            with tempfile.TemporaryDirectory() as d:
                mgr = resilience.CheckpointManager(
                    d, every_n_steps=every, keep=2,
                    install_signal_handlers=False)
                sup = resilience.TrainingSupervisor(
                    policy='rollback', manager=mgr, executor=exe,
                    program=main, scope=scope)
                step, i, event = 0, 0, None
                while step < 12 and i < len(feeds):
                    feed = feeds[i]
                    i += 1
                    lv = exe.run(main, feed=feed, fetch_list=[loss])[0]
                    step += 1
                    t0 = time.perf_counter()
                    mgr.end_of_step(step, capture, loss=lv)
                    boundary_s = time.perf_counter() - t0
                    v = mgr.last_verdict
                    if v is not None and v.action == 'rollback':
                        event = {'detected_at': step,
                                 'resumed_from': v.resume_step,
                                 'boundary_ms': round(boundary_s * 1e3, 3),
                                 'restore_ms': round(
                                     sup.last_recovery_seconds * 1e3, 3)}
                        step = v.resume_step
                mgr.wait()
                mgr.close()
    finally:
        if old is None:
            os.environ.pop(fault.ENV_SPEC, None)
        else:
            os.environ[fault.ENV_SPEC] = old
        fault.reset_injector()

    got = {'bench': 'resilience_nan_recovery',
           'nan_step': nan_step, 'ckpt_every': every,
           'recovered': bool(event is not None and step >= 12),
           'expected_resume': every * ((nan_step - 1) // every)}
    got.update(event or {})
    return got


def measure_all(smoke=False, steps=None, every=None):
    return {'resilience_stall': measure_stall(smoke=smoke, steps=steps,
                                              every=every),
            'resilience_restart': measure_restart(smoke=smoke),
            'resilience_supervised': measure_supervised(smoke=smoke,
                                                        steps=steps),
            'resilience_nan_recovery': measure_nan_recovery(smoke=smoke)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true',
                    help='tiny shapes / CI smoke sizes')
    ap.add_argument('--steps', type=int, default=None)
    ap.add_argument('--every', type=int, default=None,
                    help='checkpoint cadence in steps')
    args = ap.parse_args()
    for res in measure_all(smoke=args.smoke, steps=args.steps,
                           every=args.every).values():
        print(json.dumps(res), flush=True)


if __name__ == '__main__':
    main()
