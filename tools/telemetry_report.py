"""Summarize a paddle_tpu telemetry run directory.

Reads the artifacts dumped by paddle_tpu.observability (metrics.json,
trace.json, steps.jsonl — see docs/OBSERVABILITY.md) and prints a run
summary: step counts, slowest eager ops, cache hit rates, input-starvation
fraction, and the compile-time breakdown.

  PADDLE_TPU_TELEMETRY=1 PADDLE_TPU_METRICS_DIR=/tmp/run python train.py
  python tools/telemetry_report.py /tmp/run [--top 10]
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _load(path):
    if path and os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return None


def _load_jsonl(path):
    rows = []
    if path and os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    return rows


def _counter(metrics, name, default=0.0):
    m = metrics.get(name)
    if not m or not m.get('samples'):
        return default
    return sum(s['value'] for s in m['samples'])


def _gauge_by_label(metrics, name, label):
    out = {}
    m = metrics.get(name)
    for s in (m or {}).get('samples', []):
        out[s['labels'].get(label)] = s['value']
    return out


def _ms(seconds):
    return f"{seconds * 1e3:.3f}ms"


def _rate(hits, misses):
    total = hits + misses
    return f"{hits / total:.1%} ({int(hits)}/{int(total)})" if total \
        else "n/a (no lookups)"


def summarize(metrics, trace, steps, top=10):
    """→ list of report lines (pure; the CLI prints them)."""
    lines = ['# paddle_tpu telemetry report', '']

    # ---- run summary ----
    events = (trace or {}).get('traceEvents', [])
    wall = 0.0
    if events:
        t0 = min(e['ts'] for e in events)
        t1 = max(e['ts'] + e.get('dur', 0.0) for e in events)
        wall = (t1 - t0) / 1e6
    exec_steps = _counter(metrics, 'executor_steps')
    ts_calls = _counter(metrics, 'train_step_calls')
    lines += ['## Run summary',
              f"executor steps:        {int(exec_steps)}",
              f"fused TrainStep calls: {int(ts_calls)}",
              f"traced wall time:      {wall:.3f}s "
              f"({len(events)} trace events, "
              f"{(trace or {}).get('otherData', {}).get('dropped_events', 0)}"
              f" dropped)",
              f"step records:          {len(steps)}",
              '']

    # ---- slowest ops (eager dispatch histograms) ----
    lines.append(f'## Slowest eager ops (top {top} by total dispatch time)')
    rows = []
    for s in (metrics.get('tape_dispatch_seconds') or {}).get('samples', []):
        if s['count']:
            rows.append((s['sum'], s))
    if rows:
        rows.sort(key=lambda r: -r[0])
        lines.append(f"{'op':<28}{'cached':>8}{'calls':>8}{'total':>12}"
                     f"{'mean':>12}{'max':>12}")
        for total, s in rows[:top]:
            lab = s['labels']
            lines.append(
                f"{lab.get('op', '?')[:28]:<28}{lab.get('cached', '?'):>8}"
                f"{s['count']:>8}{_ms(total):>12}"
                f"{_ms(total / s['count']):>12}{_ms(s['max'] or 0):>12}")
    else:
        lines.append('(no eager dispatches recorded)')
    lines.append('')

    # ---- cache hit rates ----
    ek = _gauge_by_label(metrics, 'eager_kernel_cache', 'stat')
    lines += ['## Cache hit rates',
              f"eager kernel cache:    "
              f"{_rate(ek.get('hits', 0), ek.get('misses', 0))}"
              + (f"  [size {int(ek.get('size', 0))}/"
                 f"{int(ek.get('maxsize', 0))}, "
                 f"evictions {int(ek.get('evictions', 0))}, "
                 f"bypasses {int(ek.get('bypasses', 0))}]" if ek else ''),
              f"executor step cache:   "
              f"{_rate(_counter(metrics, 'compile_cache_hits'), _counter(metrics, 'compile_cache_misses'))}",
              f"persistent XLA cache:  "
              f"{_rate(_counter(metrics, 'persistent_cache_hits'), _counter(metrics, 'persistent_cache_misses'))}",
              '']

    # ---- input starvation ----
    wait_total = _counter(metrics, 'dataloader_wait_seconds_total')
    batches = _counter(metrics, 'dataloader_batches')
    lines.append('## Input pipeline')
    if batches:
        frac = wait_total / wall if wall > 0 else float('nan')
        lines += [f"batches:               {int(batches)}",
                  f"total input wait:      {wait_total:.4f}s",
                  f"mean wait / batch:     {_ms(wait_total / batches)}",
                  f"starvation fraction:   {frac:.1%} of traced wall time"]
    else:
        lines.append('(no DataLoader batches recorded)')
    lines.append('')

    # ---- async pipeline (non-blocking fetch handles) ----
    mat = (metrics.get('fetch_materialize_seconds') or {}).get('samples', [])
    mat_n = sum(s['count'] for s in mat)
    lines.append('## Async pipeline')
    if mat_n:
        mat_s = sum(s['sum'] for s in mat)
        passthrough = _counter(metrics, 'executor_feed_passthrough_bytes')
        feed_bytes = _counter(metrics, 'executor_feed_bytes')
        inflight = (metrics.get('executor_inflight_steps') or
                    {}).get('samples', [])
        # host time NOT hidden by the pipeline = D2H materialization waits
        # + input starvation; the rest of the wall clock overlapped device
        # compute with host work — the quantity the K-in-flight window
        # exists to maximize (PERF.md §12)
        blocked = mat_s + wait_total
        lines += [f"materializations:      {int(mat_n)} "
                  f"(total wait {mat_s:.4f}s, "
                  f"mean {_ms(mat_s / mat_n)})",
                  f"in-flight window:      "
                  f"{int(inflight[0]['value']) if inflight else 0} "
                  f"at last export"]
        if feed_bytes:
            lines.append(f"zero-copy staged feeds:"
                         f" {passthrough / feed_bytes:.1%} of feed bytes "
                         f"passed through without a second device_put")
        if wall > 0:
            lines.append(f"overlap fraction:      "
                         f"{max(0.0, 1.0 - blocked / wall):.1%} of traced "
                         f"wall time (1 − (materialize+input waits)/wall)")
    else:
        lines.append('(no FetchHandle materializations recorded — '
                     'synchronous loop; set PADDLE_TPU_ASYNC=1 or '
                     'ExecutionStrategy.num_inflight_steps>1)')
    lines.append('')

    # ---- collectives (quantized + bucketed gradient sync) ----
    sync_calls = _counter(metrics, 'collective_sync_calls')
    buckets = _counter(metrics, 'collective_allreduce_buckets')
    if sync_calls or buckets:
        lines.append('## Collectives')
        if sync_calls:
            by_key = {}
            for s in (metrics.get('collective_sync_calls')
                      or {}).get('samples', []):
                k = (f"{s['labels'].get('path', '?')}"
                     f"/{s['labels'].get('dtype', '?')}")
                by_key[k] = by_key.get(k, 0) + s['value']
            lines.append(
                f"sync calls:            {int(sync_calls)} "
                f"({', '.join(f'{k}: {int(v)}' for k, v in sorted(by_key.items()))})")
            wire = _counter(metrics, 'collective_bytes_on_wire')
            f32eq = _counter(metrics, 'collective_bytes_f32_equiv')
            if wire and f32eq:
                def fmt(b):
                    return f"{b / 2**20:.1f} MiB" if b >= 2**20 \
                        else f"{b / 2**10:.1f} KiB"
                note = '' if f32eq >= wire else \
                    ' — EXPANSION: block padding dominates; tensors this ' \
                    'small should sync at f32'
                lines.append(
                    f"bytes on wire:         {fmt(wire)} vs "
                    f"{fmt(f32eq)} f32-equivalent "
                    f"({f32eq / wire:.2f}x reduction{note})")
            qerr = (metrics.get('collective_quant_rel_error')
                    or {}).get('samples', [])
            qn = sum(s['count'] for s in qerr)
            if qn:
                qs = sum(s['sum'] for s in qerr)
                qmax = max(s['max'] or 0 for s in qerr)
                lines.append(
                    f"quantization error:    mean {qs / qn:.2e} rel/absmax "
                    f"per codec pass, max {qmax:.2e} ({int(qn)} samples)")
        if buckets:
            passes = _gauge_by_label(metrics, 'ir_pass_applied_total',
                                     'pass').get('bucket_allreduce', 0)
            per = buckets / max(passes, 1)
            lines.append(
                f"bucketed all-reduce:   {per:.0f} bucket(s) per lowering "
                f"(PADDLE_TPU_ALLREDUCE_BUCKET_MB caps each)")
            if per > 1:
                lines.append(
                    f"comm overlap ceiling:  {1 - 1 / per:.1%} of gradient "
                    f"comm can overlap backward compute (all but the last "
                    f"bucket dispatch before the backward tail finishes)")
        lines.append('')

    # ---- resilience / goodput ----
    saves = _counter(metrics, 'checkpoint_saves')
    goodput = (metrics.get('goodput_ratio') or {}).get('samples', [])
    lines.append('## Resilience / goodput')
    if saves or goodput:
        ck_bytes = _counter(metrics, 'checkpoint_bytes')
        save_s = (metrics.get('checkpoint_save_seconds')
                  or {}).get('samples', [])
        stall_s = (metrics.get('checkpoint_stall_seconds')
                   or {}).get('samples', [])
        last = (metrics.get('checkpoint_last_step') or {}).get('samples', [])
        lines.append(
            f"checkpoints:           {int(saves)} committed "
            f"({ck_bytes / 2**20:.1f} MiB"
            + (f", latest step {int(last[0]['value'])}" if last else '')
            + ')')
        if save_s and save_s[0]['count']:
            s = save_s[0]
            lines.append(f"background write:      mean "
                         f"{_ms(s['sum'] / s['count'])}, "
                         f"max {_ms(s['max'] or 0)}")
        if stall_s and stall_s[0]['count']:
            s = stall_s[0]
            lines.append(
                f"step-loop stall:       mean {_ms(s['sum'] / s['count'])}, "
                f"max {_ms(s['max'] or 0)} per checkpoint (the async "
                f"writer hides the rest)")
        retries = _counter(metrics, 'checkpoint_retries')
        failures = _counter(metrics, 'checkpoint_failures')
        if retries or failures:
            lines.append(f"IO retries/failures:   {int(retries)} retried, "
                         f"{int(failures)} abandoned")
        if goodput:
            prod = (metrics.get('goodput_productive_seconds')
                    or {}).get('samples', [{'value': 0.0}])[0]['value']
            gwall = (metrics.get('goodput_wall_seconds')
                     or {}).get('samples', [{'value': 0.0}])[0]['value']
            lines.append(f"goodput:               {goodput[0]['value']:.1%} "
                         f"(productive {prod:.1f}s / wall {gwall:.1f}s)")
        restarts = _counter(metrics, 'restarts_total')
        if restarts:
            lines.append(
                f"restarts:              {int(restarts)}, lost "
                f"{int(_counter(metrics, 'restart_lost_steps'))} step(s) / "
                f"{_counter(metrics, 'restart_lost_seconds'):.2f}s of "
                f"replayed work")
        resizes = _counter(metrics, 'elastic_resizes_total')
        resize_lost = (metrics.get('goodput_resize_lost_seconds')
                       or {}).get('samples', [])
        if resizes or (resize_lost and resize_lost[0]['value']):
            lost_s = resize_lost[0]['value'] if resize_lost else 0.0
            reshards = _counter(metrics, 'elastic_reshard_restores')
            lines.append(
                f"elastic resizes:       {int(resizes)} scheduled "
                f"resize(s), {lost_s:.2f}s resize downtime (separate from "
                f"crash loss), {int(reshards)} reshard-on-restore(s)")
        preempt = _counter(metrics, 'preemption_requests')
        faults = _counter(metrics, 'fault_injections')
        if preempt or faults:
            lines.append(f"preemptions/faults:    {int(preempt)} preemption "
                         f"notice(s), {int(faults)} injected fault(s)")
    else:
        lines.append('(no checkpoints recorded — wire a '
                     'resilience.CheckpointManager into the loop; '
                     'docs/RESILIENCE.md)')
    lines.append('')

    # ---- self-healing (supervisor + watchdog, docs/RESILIENCE.md) ----
    detections = _counter(metrics, 'supervisor_detections')
    breaches = _counter(metrics, 'watchdog_breaches')
    if detections or breaches:
        lines.append('## Self-healing')
        if detections:
            by_kind = {
                (s['labels'].get('kind') or '?'): int(s['value'])
                for s in (metrics.get('supervisor_detections')
                          or {}).get('samples', [])}
            lines.append(
                f"detections:            {int(detections)} unhealthy "
                f"step(s) ({', '.join(f'{k}: {v}' for k, v in sorted(by_kind.items()))})")
            skips = _counter(metrics, 'supervisor_skipped_updates')
            rollbacks = _counter(metrics, 'supervisor_rollbacks')
            benign = _counter(metrics, 'supervisor_amp_benign_skips')
            lines.append(
                f"recoveries:            {int(skips)} update(s) dropped, "
                f"{int(rollbacks)} rollback(s), {int(benign)} benign AMP "
                f"overflow skip(s)")
            rec = (metrics.get('supervisor_recovery_seconds')
                   or {}).get('samples', [])
            if rec and rec[0]['count']:
                s = rec[0]
                lines.append(f"rollback restore:      mean "
                             f"{_ms(s['sum'] / s['count'])}, "
                             f"max {_ms(s['max'] or 0)}")
            quarantined = _counter(metrics, 'supervisor_quarantined_batches')
            if quarantined:
                lines.append(f"quarantined:           {int(quarantined)} "
                             f"batch descriptor(s) (quarantine.jsonl)")
        if breaches:
            by_lease = {
                (s['labels'].get('lease') or '?'): int(s['value'])
                for s in (metrics.get('watchdog_breaches')
                          or {}).get('samples', [])}
            lines.append(
                f"WATCHDOG BREACHES:     {int(breaches)} hang(s) "
                f"({', '.join(f'{k}: {v}' for k, v in sorted(by_lease.items()))}), "
                f"{int(_counter(metrics, 'watchdog_stack_dumps'))} stack "
                f"dump(s) written")
        lines.append('')

    # ---- serving tier (router / prefix cache / disagg, docs/SERVING.md) --
    tier_hits = _counter(metrics, 'prefix_cache_hits')
    tier_misses = _counter(metrics, 'prefix_cache_misses')
    routed = _counter(metrics, 'router_requests')
    handoffs = _counter(metrics, 'disagg_handoffs')
    autoscale = _counter(metrics, 'autoscale_decisions')
    if tier_hits or tier_misses or routed or handoffs or autoscale:
        lines.append('## Serving tier')
        if tier_hits or tier_misses:
            saved = _counter(metrics, 'prefix_cache_tokens_saved')
            resident = (metrics.get('prefix_cache_blocks_resident')
                        or {}).get('samples', [])
            lines.append(f"prefix-cache hit rate: "
                         f"{_rate(tier_hits, tier_misses)}")
            lines.append(f"prefill compute saved: {int(saved)} prompt "
                         f"token(s) served from cached KV blocks")
            if resident:
                lines.append(f"cache residency:       "
                             f"{int(resident[0]['value'])} block(s), "
                             f"{int(_counter(metrics, 'prefix_cache_evicted_blocks'))} "
                             f"evicted")
        if routed:
            completed = _counter(metrics, 'router_requests_completed')
            rerouted = _counter(metrics, 'router_requests_rerouted')
            failed = _counter(metrics, 'router_requests_failed')
            lines.append(
                f"router:                {int(routed)} request(s), "
                f"{int(completed)} completed, {int(rerouted)} rerouted "
                f"(failover), {int(failed)} failed in-flight")
            per_replica = _gauge_by_label(metrics,
                                          'router_replica_inflight',
                                          'replica')
            if per_replica:
                load = ', '.join(f'{u}: {int(v)}'
                                 for u, v in sorted(per_replica.items()))
                lines.append(f"per-replica in-flight: {load}")
        if autoscale:
            by_act = {}
            for s in (metrics.get('autoscale_decisions')
                      or {}).get('samples', []):
                key = (f"{s['labels'].get('action', '?')}/"
                       f"{s['labels'].get('trigger', '?')}")
                by_act[key] = by_act.get(key, 0) + int(s['value'])
            detail = ', '.join(f'{k}: {v}'
                               for k, v in sorted(by_act.items()))
            lines.append(f"autoscaler:            {int(autoscale)} "
                         f"decision(s) ({detail})")
            reps = (metrics.get('autoscale_replicas')
                    or {}).get('samples', [])
            routable = (metrics.get('autoscale_replicas_routable')
                        or {}).get('samples', [])
            if reps:
                lines.append(
                    f"tier size:             {int(reps[0]['value'])} "
                    f"replica(s), "
                    f"{int(routable[0]['value']) if routable else 0} "
                    f"routable")
            ttr = (metrics.get('autoscale_time_to_routable_seconds')
                   or {}).get('samples', [])
            if ttr and ttr[0]['count']:
                s = ttr[0]
                lines.append(f"cold-start admission:  mean "
                             f"{s['sum'] / s['count']:.2f}s to routable, "
                             f"max {s['max'] or 0:.2f}s "
                             f"({int(s['count'])} replica(s))")
            dr = (metrics.get('autoscale_drain_seconds')
                  or {}).get('samples', [])
            if dr and dr[0]['count']:
                s = dr[0]
                lines.append(f"drain-then-retire:     mean "
                             f"{s['sum'] / s['count']:.2f}s, "
                             f"max {s['max'] or 0:.2f}s "
                             f"({int(s['count'])} replica(s))")
        if handoffs:
            hb = _counter(metrics, 'disagg_kv_bytes')
            hf = _counter(metrics, 'disagg_handoff_failures')
            lines.append(
                f"disaggregation:        {int(handoffs)} prefill->decode "
                f"handoff(s), {int(hb)} KV byte(s) shipped, "
                f"{int(hf)} failed")
        lines.append('')

    # ---- KV cache (quantized pools + host spill tier, docs/SERVING.md) --
    kv_dtype = (metrics.get('kv_cache_dtype') or {}).get('samples', [])
    kv_hbm = (metrics.get('kv_cache_bytes_in_hbm') or {}).get('samples', [])
    spills = _counter(metrics, 'kv_cache_spill_count')
    reinjects = _counter(metrics, 'kv_cache_reinject_count')
    if kv_dtype or kv_hbm or spills or reinjects:
        lines.append('## KV cache')
        if kv_dtype:
            names = {0: 'f32', 1: 'bf16', 2: 'int8'}
            code = int(kv_dtype[0]['value'])
            lines.append(f"storage dtype:         "
                         f"{names.get(code, f'?({code})')} "
                         f"(PADDLE_TPU_KV_DTYPE)")
        if kv_hbm:
            lines.append(f"bytes in HBM:          "
                         f"{kv_hbm[0]['value'] / 2**20:.3f} MiB "
                         f"(pool pages + row scales)")
        if spills or reinjects:
            sb = _counter(metrics, 'kv_cache_bytes_spilled')
            lines.append(
                f"host spill tier:       {int(spills)} block(s) spilled "
                f"({sb / 2**20:.3f} MiB serialized), "
                f"{int(reinjects)} reinjected on radix hits")
            rs = (metrics.get('kv_cache_reinject_seconds')
                  or {}).get('samples', [])
            if rs and rs[0]['count']:
                s = rs[0]
                lines.append(f"reinject latency:      mean "
                             f"{_ms(s['sum'] / s['count'])}, "
                             f"max {_ms(s['max'] or 0)} per hit path")
        ev = (metrics.get('prefix_cache_evictions') or {}).get('samples', [])
        if ev:
            by_cause = {}
            for s in ev:
                c = s['labels'].get('cause', '?')
                by_cause[c] = by_cause.get(c, 0) + s['value']
            lines.append(
                "evictions by cause:    "
                + ', '.join(f'{c}: {int(v)}'
                            for c, v in sorted(by_cause.items())))
        lines.append('')

    # ---- fleet-wide tier observability (docs/OBSERVABILITY.md) ----
    fleet_scrapes = _counter(metrics, 'router_fleet_scrapes')
    sampled = _counter(metrics, 'trace_requests_sampled')
    ttft = (metrics.get('decode_ttft_seconds') or {}).get('samples', [])
    if fleet_scrapes or sampled or (ttft and ttft[0]['count']):
        lines.append('## Tier (fleet-wide)')
        if fleet_scrapes:
            sfails = _counter(metrics, 'router_scrape_failures')
            lines.append(f"/metrics/fleet:        {int(fleet_scrapes)} "
                         f"aggregation(s), {int(sfails)} failed replica "
                         f"scrape(s)")
        offs = _gauge_by_label(metrics, 'trace_clock_offset_seconds',
                               'replica')
        if offs:
            lines.append(
                "clock offsets:         "
                + ', '.join(f'{r}: {v * 1e3:+.1f}ms'
                            for r, v in sorted(offs.items()))
                + '  (health-handshake estimate, trace_merge.py input)')
        if sampled:
            lines.append(
                f"tracing:               {int(sampled)} sampled "
                f"request(s), "
                f"{int(_counter(metrics, 'trace_spans_recorded'))} "
                f"span(s) recorded")
        if ttft and ttft[0]['count']:
            s = ttft[0]
            lines.append(f"TTFT:                  {s['count']} "
                         f"request(s), mean {_ms(s['sum'] / s['count'])}, "
                         f"max {_ms(s['max'] or 0)}")
        lines.append('')

    # ---- straggler / SLO monitors (docs/OBSERVABILITY.md) ----
    zscores = _gauge_by_label(metrics, 'straggler_zscore', 'host')
    slo_ok = _gauge_by_label(metrics, 'slo_ok', 'slo')
    if zscores or slo_ok:
        lines.append('## Straggler / SLO')
        if zscores:
            flagged = _counter(metrics, 'straggler_flags')
            count = (metrics.get('straggler_count')
                     or {}).get('samples', [])
            lines.append(
                f"straggler monitor:     "
                f"{int(count[0]['value']) if count else 0} host(s) "
                f"currently flagged, {int(flagged)} cumulative detection(s)")
            lines.append(
                "host z-scores:         "
                + ', '.join(f'{h}: {z:+.2f}'
                            for h, z in sorted(zscores.items())))
        if slo_ok:
            burns = _gauge_by_label(metrics, 'slo_breaches', 'slo')
            for clause, ok in sorted(slo_ok.items()):
                state = 'OK' if ok else 'BREACHED'
                lines.append(
                    f"slo {clause:<18} {state} "
                    f"({int(burns.get(clause, 0))} breach evaluation(s))")
        lines.append('')

    # ---- memory plan (analysis/plan.py, docs/ANALYSIS.md) ----
    def _gauge(name):
        s = (metrics.get(name) or {}).get('samples', [])
        return s[0]['value'] if s else None

    peak = _gauge('program_peak_hbm_bytes')
    predicted = _gauge('program_plan_accounted_bytes')
    measured = _gauge('program_measured_hbm_bytes')
    if peak is not None or measured is not None:
        lines.append('## Memory plan')
        if peak is not None:
            lines.append(f"predicted peak HBM:    {peak / 2**20:.3f} MiB "
                         f"(analysis/plan.py, last lowered program)")
        if predicted is not None and measured is not None:
            delta = ((measured - predicted) / predicted
                     if predicted else float('nan'))
            lines.append(
                f"state+feed+fetch:      predicted "
                f"{predicted / 2**20:.3f} MiB vs measured "
                f"{measured / 2**20:.3f} MiB ({delta:+.1%} delta)")
        remat = _gauge('auto_remat_checkpoints')
        if remat:
            planned = _gauge('auto_remat_planned_peak_bytes') or 0
            lines.append(
                f"auto-remat:            {int(remat)} checkpoint(s) "
                f"chosen; post-remat predicted peak "
                f"{planned / 2**20:.3f} MiB "
                f"(PADDLE_TPU_HBM_BUDGET_MB)")
        plan_s = (metrics.get('program_plan_seconds')
                  or {}).get('samples', [])
        if plan_s and plan_s[0]['count']:
            s = plan_s[0]
            lines.append(f"plan time:             "
                         f"{s['count']} plan(s), mean "
                         f"{_ms(s['sum'] / s['count'])}, "
                         f"max {_ms(s['max'] or 0)} (zero tracing)")
        fails = _counter(metrics, 'program_plan_failures')
        if fails:
            lines.append(f"PLAN FAILURES:         {int(fails)} plan "
                         f"attempt(s) raised (best-effort; lowering "
                         f"proceeded)")
        lines.append('')

    # ---- compile-time breakdown ----
    lines.append('## Compile-time breakdown')
    any_compile = False
    for name, label in [
            ('executor_compile_seconds', 'executor lower+compile'),
            ('compile_cache_deserialize_seconds', 'persistent deserialize'),
            ('compile_cache_time_saved_seconds', 'compile time saved')]:
        for s in (metrics.get(name) or {}).get('samples', []):
            if s['count']:
                any_compile = True
                lines.append(f"{label + ':':<23}{s['count']} event(s), "
                             f"total {s['sum']:.3f}s, "
                             f"max {s['max'] or 0:.3f}s")
    build_durs = [e['dur'] / 1e6 for e in events
                  if e['name'] == 'train_step/build']
    if build_durs:
        any_compile = True
        lines.append(f"{'TrainStep build:':<23}{len(build_durs)} event(s), "
                     f"total {sum(build_durs):.3f}s")
    if not any_compile:
        lines.append('(no compiles recorded — fully warm run)')
    lines.append('')

    # ---- anomalies ----
    nonfinite = _counter(metrics, 'nonfinite_detections')
    if nonfinite:
        lines += ['## Anomalies',
                  f"NON-FINITE DETECTIONS: {int(nonfinite)} fetched "
                  f"variable(s) contained NaN/Inf (FLAGS_check_nan_inf)", '']
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('directory', nargs='?',
                    default=os.environ.get('PADDLE_TPU_METRICS_DIR'),
                    help='telemetry artifact dir '
                         '(default: $PADDLE_TPU_METRICS_DIR)')
    ap.add_argument('--metrics', help='explicit metrics.json path')
    ap.add_argument('--trace', help='explicit trace.json path')
    ap.add_argument('--steps', help='explicit steps.jsonl path')
    ap.add_argument('--top', type=int, default=10,
                    help='rows in the slowest-ops table')
    args = ap.parse_args(argv)

    d = args.directory
    mpath = args.metrics or (d and os.path.join(d, 'metrics.json'))
    tpath = args.trace or (d and os.path.join(d, 'trace.json'))
    spath = args.steps or (d and os.path.join(d, 'steps.jsonl'))
    mdoc = _load(mpath)
    if mdoc is None:
        print(f"telemetry_report: no metrics.json found "
              f"(looked at {mpath!r}); run with PADDLE_TPU_TELEMETRY=1 and "
              f"PADDLE_TPU_METRICS_DIR set", file=sys.stderr)
        return 2
    metrics = mdoc.get('metrics', mdoc)
    trace = _load(tpath)
    steps = _load_jsonl(spath)
    print('\n'.join(summarize(metrics, trace, steps, top=args.top)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
