"""Static-verifier overhead bench (PERF.md §17).

The verifier (paddle_tpu/analysis/) runs at program-BUILD time — once
per compile-cache miss at every IR pass boundary — never per step. This
bench prices that on the multi-param Adam MLP recipe (the same program
bench_passes.py uses):

- ``verify_frac_of_compile`` — verifier seconds as a fraction of the
  cold lower+compile cost it rides on (measured through the telemetry
  registry's ``program_verify_seconds`` vs ``executor_compile_seconds``,
  so both numbers come from the same real Executor run);
- ``pipeline_overhead`` — direct A/B of ``ir.apply_pipeline`` wall time
  with ``PADDLE_TPU_VERIFY`` off vs ``passes``;
- ``warm_step_ratio`` — warm step time at passes-level over off-level
  (must be ~1.0: the verifier never touches the step path).

Acceptance (asserted in tier-1 via test_bench_verify.py at smoke sizes):
``verify_frac_of_compile`` ≤ 0.02.

  JAX_PLATFORMS=cpu python tools/bench_verify.py [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _hist_sum(registry, name):
    d = registry.to_dict().get(name)
    if not d or not d.get('samples'):
        return 0.0
    return sum(s.get('sum', 0.0) for s in d['samples'])


def _build_recipe(smoke):
    sys.path.insert(0, os.path.join(_REPO, 'tools'))
    from bench_passes import build_mlp_adam
    return build_mlp_adam(smoke=smoke)


def _fused_bs():
    from paddle_tpu.compiler import BuildStrategy
    bs = BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    bs.fuse_all_optimizer_ops = True
    return bs


def measure_pipeline_ab(iters=5, smoke=False):
    """ir.apply_pipeline wall time, verify off vs passes (median)."""
    import paddle_tpu  # noqa: F401
    from paddle_tpu import ir
    main, _startup, make_feed, fetch = _build_recipe(smoke)
    feed = make_feed()
    kw = dict(fetch_names=[fetch.name], feed_names=sorted(feed),
              build_strategy=_fused_bs())
    out = {}
    for level in ('off', 'passes'):
        os.environ['PADDLE_TPU_VERIFY'] = level
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ir.apply_pipeline(main, **kw)
            ts.append(time.perf_counter() - t0)
        out[level] = statistics.median(ts)
    return {'bench': 'verify_pipeline_ab',
            'ops': main.num_ops(),
            'pipeline_off_s': round(out['off'], 5),
            'pipeline_on_s': round(out['passes'], 5),
            'verify_added_s': round(out['passes'] - out['off'], 5)}


def measure_compile_fraction(smoke=False, steps=10):
    """One real cold Executor build+run at PADDLE_TPU_VERIFY=passes with
    telemetry on; the verifier's share of the compile cost and the warm
    step ratio come from the same run pair."""
    os.environ['PADDLE_TPU_COMPILE_CACHE'] = '0'   # price the real compile
    import paddle_tpu as fluid
    from paddle_tpu import observability as obs

    def one_cold_run(level):
        os.environ['PADDLE_TPU_VERIFY'] = level
        main, startup, make_feed, fetch = _build_recipe(smoke)
        feed = make_feed()
        exe = fluid.Executor()
        exe.run(startup)
        t0 = time.perf_counter()
        exe.run(main, feed=feed, fetch_list=[fetch])     # cold: compiles
        cold = time.perf_counter() - t0
        warm = []
        for _ in range(steps):
            t1 = time.perf_counter()
            exe.run(main, feed=feed, fetch_list=[fetch])
            warm.append(time.perf_counter() - t1)
        # min: warm steps are sub-ms host dispatches, so scheduler noise
        # dominates any central tendency; the best observed pair is the
        # honest "does the verifier touch the step path" probe
        return cold, min(warm)

    with obs.telemetry_guard(True):
        obs.registry.reset()
        cold_off, warm_off = one_cold_run('off')
        verify_off = _hist_sum(obs.registry, 'program_verify_seconds')

        obs.registry.reset()
        cold_on, warm_on = one_cold_run('passes')
        verify_on = _hist_sum(obs.registry, 'program_verify_seconds')
        compile_on = _hist_sum(obs.registry, 'executor_compile_seconds')

    assert verify_off == 0.0, 'verifier ran at level=off'
    assert verify_on > 0.0, 'verifier never ran at level=passes'
    frac = verify_on / compile_on if compile_on else 0.0
    return {'bench': 'verify_overhead',
            'verify_seconds': round(verify_on, 5),
            'compile_seconds': round(compile_on, 4),
            'verify_frac_of_compile': round(frac, 5),
            'cold_off_s': round(cold_off, 4),
            'cold_on_s': round(cold_on, 4),
            'warm_step_ratio': round(warm_on / warm_off, 4)
            if warm_off else None}


def measure_all(iters=5, smoke=False):
    prior = os.environ.get('PADDLE_TPU_VERIFY')
    try:
        ab = measure_pipeline_ab(iters=iters, smoke=smoke)
        frac = measure_compile_fraction(smoke=smoke)
    finally:
        if prior is None:
            os.environ.pop('PADDLE_TPU_VERIFY', None)
        else:
            os.environ['PADDLE_TPU_VERIFY'] = prior
    print(json.dumps(ab))
    print(json.dumps(frac))
    return {'verify_pipeline_ab': ab, 'verify_overhead': frac}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--iters', type=int, default=5)
    ap.add_argument('--smoke', action='store_true')
    args = ap.parse_args()
    r = measure_all(iters=args.iters, smoke=args.smoke)
    frac = r['verify_overhead']['verify_frac_of_compile']
    ok = frac <= 0.02
    print(json.dumps({'bench': 'verify_acceptance',
                      'verify_frac_of_compile': frac,
                      'threshold': 0.02, 'ok': ok}))
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
