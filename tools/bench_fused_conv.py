"""On-chip microbench for the conv-efficiency levers (PERF.md §1 follow-up;
run on a real TPU when the tunnel is up):

  python tools/bench_fused_conv.py

Measures, slope method (the dispatch-robust timing PERF.md §3 established):
1. ResNet stem: plain 7×7/s2 conv vs space-to-depth 4×4/s1 re-layout.
2. Bottleneck 1×1 conv + BN + relu: XLA (conv → affine) vs the pallas
   fused-epilogue kernel.
3. Per-conv MFU of the four distinct ResNet-50 3×3 shapes (the measured
   ceiling the fused work targets).

Prints one JSON line per measurement.
"""
import functools
import json
import time

import numpy as np


def _slope_time(fn, *args, iters=(4, 16)):
    """Run iters[0] and iters[1] chained repetitions; slope removes the
    constant dispatch/transfer overhead the axon tunnel adds."""
    import jax

    def run(n):
        t0 = time.perf_counter()
        o = None
        for _ in range(n):
            o = fn(*args)
        jax.block_until_ready(o)
        return time.perf_counter() - t0

    run(2)  # warmup/compile
    t_small, t_big = run(iters[0]), run(iters[1])
    return (t_big - t_small) / (iters[1] - iters[0])


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.utils.backend_probe import probe_backend
    devices, backend = probe_backend(isolated=False)  # exits on failure
    on_tpu = backend == 'tpu'
    print(json.dumps({"bench": "backend", "backend": backend}), flush=True)
    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32

    # --- 1. stem: plain vs s2d ---
    from paddle_tpu.ops.nn_ops import conv2d
    from paddle_tpu.ops.pallas_conv import stem_space_to_depth
    bs = 128 if on_tpu else 4
    x = jnp.asarray(rng.randn(bs, 224, 224, 3), dt)
    w = jnp.asarray(rng.randn(7, 7, 3, 64) * 0.05, dt)
    plain = jax.jit(functools.partial(conv2d, stride=2, padding=3,
                                      data_format='NHWC'))
    s2d = jax.jit(functools.partial(stem_space_to_depth,
                                    data_format='NHWC'))
    t_plain = _slope_time(plain, x, w)
    t_s2d = _slope_time(s2d, x, w)
    print(json.dumps({"bench": "stem_conv", "plain_ms": t_plain * 1e3,
                      "s2d_ms": t_s2d * 1e3,
                      "speedup": t_plain / t_s2d}), flush=True)

    # --- 2. fused 1×1 conv+bn+relu: XLA vs pallas ---
    from paddle_tpu.ops.pallas_conv import fused_conv1x1_bn_act
    for (c, o, hw) in [(256, 64, 56), (512, 128, 28), (1024, 256, 14),
                       (2048, 512, 7)]:
        xx = jnp.asarray(rng.randn(bs, hw, hw, c), dt)
        ww = jnp.asarray(rng.randn(1, 1, c, o) * 0.05, dt)
        sc = jnp.asarray(rng.rand(o) + 0.5, dt)
        sh = jnp.asarray(rng.randn(o) * 0.1, dt)
        xla = jax.jit(functools.partial(fused_conv1x1_bn_act, act='relu',
                                        force_pallas=False))
        pal = jax.jit(functools.partial(fused_conv1x1_bn_act, act='relu',
                                        force_pallas=True))
        t_xla = _slope_time(xla, xx, ww, sc, sh)
        t_pal = _slope_time(pal, xx, ww, sc, sh)
        flops = 2.0 * bs * hw * hw * c * o
        print(json.dumps({
            "bench": "conv1x1_bn_relu", "shape": f"{c}->{o}@{hw}",
            "xla_ms": t_xla * 1e3, "pallas_ms": t_pal * 1e3,
            "xla_tflops": flops / t_xla / 1e12,
            "pallas_tflops": flops / t_pal / 1e12,
            "speedup": t_xla / t_pal}), flush=True)

    # --- 3. per-conv MFU of the 3×3 ResNet shapes ---
    for (c, o, hw, s) in [(64, 64, 56, 1), (128, 128, 28, 1),
                          (256, 256, 14, 1), (512, 512, 7, 1)]:
        xx = jnp.asarray(rng.randn(bs, hw, hw, c), dt)
        ww = jnp.asarray(rng.randn(3, 3, c, o) * 0.05, dt)
        f = jax.jit(functools.partial(conv2d, stride=s, padding=1,
                                      data_format='NHWC'))
        t = _slope_time(f, xx, ww)
        flops = 2.0 * bs * hw * hw * c * o * 9 / (s * s)
        print(json.dumps({"bench": "conv3x3", "shape": f"{c}@{hw}",
                          "ms": t * 1e3,
                          "tflops": flops / t / 1e12}), flush=True)


if __name__ == '__main__':
    main()
