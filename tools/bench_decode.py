"""Stateful decode-engine benchmark (PERF.md §13).

Three sections over one mixed-length generation workload (seeded prompt and
budget draws — the ragged mix is the point: uniform lengths would hide the
drain policy's idle-slot waste), one JSON line each:

1. ``decode_uncached_baseline`` — per-request whole-sequence greedy decode
   (models/causal_lm.greedy_generate at the engine's padded context): every
   token re-runs the full prefix. One compile total, but O(L²) work and no
   cross-request batching. Produces the reference token streams.
2. ``decode_engine_continuous`` — the same requests through the
   DecodeScheduler with slot-based continuous batching (admit into freed
   slots every step). Reports tokens/s, speedups, mean slot occupancy, the
   prefill-vs-decode time split, and **per-request bitwise token parity**
   against section 1 (the engine acceptance bar).
3. ``decode_engine_drain`` — identical except ``admission='drain'``
   (refill only when ALL slots finish — the wave-batching strawman).
   Acceptance (PERF.md §13): continuous ≥ 1.5× drain tokens/s on this
   workload, parity again bitwise.
4. ``decode_sampled`` — the same workload with sampling params and PINNED
   request_ids, run TWICE through the warm engine: reports sampled
   tokens/s and ``replayable`` (the two passes bitwise-identical — the
   request_id-is-the-seed contract).
5. ``decode_engine_speculative`` — a fresh engine with
   ``spec_decode=True`` (n-gram drafter) over the same greedy workload:
   parity against section 1 stays bitwise, and the batched (S, k) verify
   rounds take ≥ 1.5× fewer decode steps than lockstep (greedy tiny-LM
   streams are repetition-heavy — the n-gram drafter's cache-friendly
   case). ``speedup_vs_lockstep`` reports the wall-clock ratio.
6. ``decode_kv_quant`` — KV storage dtype A/B (f32 / bf16 / int8 pools on
   a head_dim-32 model): measured pool bytes-in-HBM (int8 acceptance:
   ≥ 3.5× smaller than f32), token-level greedy match-rate vs f32 (f32
   bitwise, int8 ≥ 0.99), and the capacity the bytes buy — slots-per-chip
   at a fixed HBM budget and effective cache blocks with the host spill
   tier (PERF.md §23).

Runs on any backend; CPU is the honest configuration (the quantity under
test is scheduling + shape discipline, not FLOPs):

  JAX_PLATFORMS=cpu python tools/bench_decode.py [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python tools/bench_decode.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_workload(requests, max_prompt, max_new_cap, seed=0):
    """Seeded mixed-length workload: ragged prompts and HEAVY-TAILED
    generation budgets (3 of 4 requests short, 1 of 4 near the cap — the
    shape of real LLM traffic, and exactly what wave batching is worst at:
    one long request pins the whole drained wave while S-1 slots idle)."""
    rng = np.random.RandomState(seed)
    work = []
    for i in range(requests):
        plen = int(rng.randint(2, max_prompt + 1))
        prompt = [int(t) for t in rng.randint(3, 120, plen)]
        if i % 4 == 3:      # deterministic tail: every 4th request is long
            max_new = int(rng.randint(2 * max_new_cap // 3,
                                      max_new_cap + 1))
        else:
            max_new = int(rng.randint(4, max(max_new_cap // 4, 5)))
        work.append((prompt, max_new))
    return work


def _hist_sum(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0, 0
    return (sum(s['sum'] for s in d['samples']),
            sum(s['count'] for s in d['samples']))


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


def measure_uncached(model, work, padded_context):
    from paddle_tpu.models.causal_lm import greedy_generate
    # warm the single fixed shape so the baseline wall is steady-state
    greedy_generate(model, work[0][0], 1, pad_len=padded_context)
    refs = []
    t0 = time.perf_counter()
    for prompt, max_new in work:
        refs.append(greedy_generate(model, prompt, max_new,
                                    pad_len=padded_context))
    wall = time.perf_counter() - t0
    tokens = sum(len(r) for r in refs)
    return {
        'bench': 'decode_uncached_baseline',
        'requests': len(work), 'tokens': tokens,
        'tokens_per_s': round(tokens / wall, 1),
        'wall_s': round(wall, 3),
    }, refs


def measure_engine(engine, work, refs, admission, bench_name=None):
    from paddle_tpu.serving.decode import DecodeScheduler
    pre0, _ = _hist_sum('decode_prefill_seconds')
    step0, nstep0 = _hist_sum('decode_step_seconds')
    occ0, nocc0 = _hist_sum('decode_slot_occupancy')
    with DecodeScheduler(engine, queue_depth=len(work) + 1,
                         admission=admission) as sched:
        t0 = time.perf_counter()
        streams = [sched.submit(p, max_new_tokens=m) for p, m in work]
        outs = [s.result(600) for s in streams]
        wall = time.perf_counter() - t0
    tokens = sum(len(o) for o in outs)
    mismatches = sum(o != r for o, r in zip(outs, refs))
    pre1, _ = _hist_sum('decode_prefill_seconds')
    step1, nstep1 = _hist_sum('decode_step_seconds')
    occ1, nocc1 = _hist_sum('decode_slot_occupancy')
    return {
        'bench': bench_name or f'decode_engine_{admission}',
        'requests': len(work), 'tokens': tokens,
        'slots': engine.slots,
        'tokens_per_s': round(tokens / wall, 1),
        'wall_s': round(wall, 3),
        'steps': nstep1 - nstep0,
        'mean_slot_occupancy': round(
            (occ1 - occ0) / max(nocc1 - nocc0, 1), 3),
        'prefill_s': round(pre1 - pre0, 3),
        'decode_s': round(step1 - step0, 3),
        'bitwise_equal': mismatches == 0,
    }


def measure_sampled(engine, work):
    """Sampled decode through the warm lockstep engine: pinned request_ids,
    the workload run TWICE — the second pass must replay the first bitwise
    (the request_id-is-the-seed contract of serving/decode/sampling.py)."""
    from paddle_tpu.serving.decode import DecodeScheduler
    params = {'temperature': 0.8, 'top_k': 32, 'top_p': 0.95}

    def run_once():
        with DecodeScheduler(engine, queue_depth=len(work) + 1) as sched:
            t0 = time.perf_counter()
            streams = [sched.submit(p, max_new_tokens=m, sampling=params,
                                    request_id=f'bench-sampled-{i}')
                       for i, (p, m) in enumerate(work)]
            outs = [s.result(600) for s in streams]
            return outs, time.perf_counter() - t0

    outs1, wall = run_once()
    outs2, _ = run_once()
    tokens = sum(len(o) for o in outs1)
    return {
        'bench': 'decode_sampled',
        'requests': len(work), 'tokens': tokens,
        'tokens_per_s': round(tokens / wall, 1),
        'wall_s': round(wall, 3),
        'sampling': params,
        'replayable': outs1 == outs2,
    }


def measure_spec(engine, work, refs):
    """Speculative decoding (n-gram drafter) over the greedy workload:
    measure_engine's numbers plus the verify-round/acceptance counters.
    Parity against the uncached refs must stay bitwise — the drafter only
    proposes; the target model's (S, k) rows decide every token."""
    rounds0 = _counter('decode_spec_rounds')
    drafted0 = _counter('decode_spec_draft_tokens')
    accepted0 = _counter('decode_spec_accepted_tokens')
    res = measure_engine(engine, work, refs, 'continuous',
                         bench_name='decode_engine_speculative')
    drafted = _counter('decode_spec_draft_tokens') - drafted0
    res['spec_k'] = engine.spec_k
    res['spec_rounds'] = int(_counter('decode_spec_rounds') - rounds0)
    res['draft_tokens'] = int(drafted)
    res['accepted_tokens'] = int(
        _counter('decode_spec_accepted_tokens') - accepted0)
    res['acceptance'] = round(res['accepted_tokens'] / max(drafted, 1), 3)
    return res


def measure_kv_quant(smoke=False, seed=0):
    """KV storage dtype A/B (PERF.md §23): the same greedy workload through
    engines at PADDLE_TPU_KV_DTYPE = f32 / bf16 / int8 on a head_dim-32
    model — f32 rows are 128 B, int8 rows 32+4 B (payload + one f32 scale),
    so the pool ratio under test is 3.56×; tiny's head_dim 16 would
    understate it (3.2×). Reports per-dtype tokens/s, measured pool
    bytes-in-HBM, token-level greedy match-rate against the f32 reference
    (the quality contract: f32 bitwise, int8 ≥ 0.99), and what the bytes
    buy: slots-per-chip at a fixed HBM budget (planner solve ÷ worst-case
    blocks per request) and effective cache blocks with the host spill
    tier on top of HBM."""
    from paddle_tpu.analysis.plan import (decode_pool_block_bytes,
                                          solve_decode_pool_blocks)
    from paddle_tpu.dygraph import guard
    from paddle_tpu.models.causal_lm import (CausalLMConfig, TransformerLM,
                                             greedy_generate)
    from paddle_tpu.serving.decode import DecodeEngine, DecodeScheduler
    requests = 8 if smoke else 16
    budget_mb, host_mb = 1024, 512
    with guard():
        cfg = CausalLMConfig(vocab_size=128, hidden_size=64,
                             num_hidden_layers=2, num_attention_heads=2,
                             intermediate_size=64,
                             max_position_embeddings=128)
        model = TransformerLM(cfg)
        model.eval()
        work = build_workload(requests, 12, 24 if smoke else 32, seed)
        per, refs, max_bps = {}, None, None
        for dtype in ('f32', 'bf16', 'int8'):
            engine = DecodeEngine(model, slots=4, block_size=8,
                                  max_blocks=256, max_prompt_len=16,
                                  max_new_tokens_cap=48, kv_dtype=dtype)
            max_bps = engine.pool.max_blocks_per_seq
            if refs is None:
                refs = [greedy_generate(model, p, m,
                                        pad_len=engine.padded_context)
                        for p, m in work]
            engine.warmup()
            with DecodeScheduler(engine, queue_depth=len(work) + 1) as sched:
                t0 = time.perf_counter()
                streams = [sched.submit(p, max_new_tokens=m)
                           for p, m in work]
                outs = [s.result(600) for s in streams]
                wall = time.perf_counter() - t0
            matched = sum(sum(a == b for a, b in zip(o, r))
                          for o, r in zip(outs, refs))
            total = sum(len(r) for r in refs)
            per[dtype] = {
                'tokens_per_s': round(sum(len(o) for o in outs) / wall, 1),
                'kv_bytes_in_hbm': int(engine.pool.bytes_in_hbm()),
                'match_rate_vs_f32': round(matched / max(total, 1), 4),
                'bitwise_equal': outs == refs,
            }
        slots_per_chip, eff = {}, {}
        for dtype in per:
            blocks = solve_decode_pool_blocks(model, budget_mb,
                                              block_size=8, kv_dtype=dtype)
            slots_per_chip[dtype] = blocks // max_bps
            block_bytes = decode_pool_block_bytes(model, 8, dtype)
            eff[dtype] = {
                'hbm_only': blocks,
                'with_host_tier': blocks + (host_mb << 20) // block_bytes,
            }
    return {
        'bench': 'decode_kv_quant',
        'requests': len(work), 'head_dim': 32, 'budget_mb': budget_mb,
        'host_mb': host_mb, 'per_dtype': per,
        'hbm_bytes_f32_over_int8': round(
            per['f32']['kv_bytes_in_hbm']
            / per['int8']['kv_bytes_in_hbm'], 2),
        'slots_per_chip': slots_per_chip,
        'effective_cache_blocks': eff,
    }


def measure_all(smoke=False, seed=0):
    from paddle_tpu.dygraph import guard
    from paddle_tpu.models.causal_lm import CausalLMConfig, TransformerLM
    from paddle_tpu.serving.decode import DecodeEngine
    requests = 12 if smoke else 32
    slots = 4 if smoke else 8
    max_prompt = 12
    max_new_cap = 32 if smoke else 48
    with guard():
        model = TransformerLM(CausalLMConfig.tiny())
        model.eval()
        engine = DecodeEngine(model, slots=slots, block_size=8,
                              max_blocks=256, max_prompt_len=16,
                              max_new_tokens_cap=64)
        work = build_workload(requests, max_prompt, max_new_cap, seed)
        baseline, refs = measure_uncached(model, work,
                                          engine.padded_context)
        engine.warmup()
        cont = measure_engine(engine, work, refs, 'continuous')
        drain = measure_engine(engine, work, refs, 'drain')
        sampled = measure_sampled(engine, work)
        spec_engine = DecodeEngine(model, slots=slots, block_size=8,
                                   max_blocks=256, max_prompt_len=16,
                                   max_new_tokens_cap=64, spec_decode=True)
        spec_engine.warmup()
        spec = measure_spec(spec_engine, work, refs)
    cont['speedup_vs_uncached'] = round(
        cont['tokens_per_s'] / baseline['tokens_per_s'], 2)
    cont['speedup_vs_drain'] = round(
        cont['tokens_per_s'] / drain['tokens_per_s'], 2)
    spec['speedup_vs_lockstep'] = round(
        spec['tokens_per_s'] / cont['tokens_per_s'], 2)
    kv_quant = measure_kv_quant(smoke=smoke, seed=seed)
    return {'uncached': baseline, 'continuous': cont, 'drain': drain,
            'sampled': sampled, 'speculative': spec, 'kv_quant': kv_quant}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true',
                    help='CI sizes: fewer/shorter generations')
    args = ap.parse_args()
    results = measure_all(smoke=args.smoke)
    for section in results.values():
        print(json.dumps(section), flush=True)
    # gate on correctness and STRUCTURE (step counts are deterministic for
    # the seeded workload); wall-clock ratios live in PERF.md §13 and stay
    # out of the exit code so a loaded CI box cannot flake the bench
    kv = results['kv_quant']
    ok = (results['continuous']['bitwise_equal']
          and results['drain']['bitwise_equal']
          and results['continuous']['steps'] < results['drain']['steps']
          and results['sampled']['replayable']
          and results['speculative']['bitwise_equal']
          and results['speculative']['steps'] * 1.5
          <= results['continuous']['steps']
          # kv-quant quality contract (docs/SERVING.md): f32 storage is
          # bitwise; int8 greedy match-rate ≥ 0.99. The byte ratio is pool
          # geometry, not wall-clock — deterministic, so gated too.
          and kv['per_dtype']['f32']['bitwise_equal']
          and kv['per_dtype']['int8']['match_rate_vs_f32'] >= 0.99
          and kv['hbm_bytes_f32_over_int8'] >= 3.5)
    sys.exit(0 if ok else 1)


if __name__ == '__main__':
    main()
