"""Static memory/cost planner CLI (paddle_tpu/analysis/plan.py).

Loads a saved inference model — or builds one of the tier-1 recipe
programs — and prints the memory plan: predicted peak HBM, the residency
breakdown (state/donation, feeds, activations-into-backward, gradients),
the top residents at the peak, and the per-op FLOP/byte cost ranking.
Milliseconds, zero tracing — nothing is compiled or executed.

    JAX_PLATFORMS=cpu python tools/plan_program.py --recipe mnist_mlp
    JAX_PLATFORMS=cpu python tools/plan_program.py --recipe bert_layer \
        --batch-size 64 --passes
    JAX_PLATFORMS=cpu python tools/plan_program.py --model-dir /m \
        --budget 2048
    JAX_PLATFORMS=cpu python tools/plan_program.py --decode-pool-mb 2048 \
        --kv-dtype int8

``--decode-pool-mb MB`` prints the decode KV pool sizing solve
(``analysis.plan.decode_pool_report``): the same arithmetic the engine
runs for ``PADDLE_TPU_DECODE_HBM_MB`` — model state subtracted from the
budget, the remainder divided by per-block KV bytes at ``--kv-dtype`` —
so the pool a budget buys is inspectable before serving starts.

``--budget MB`` gates the exit code: 1 when the predicted peak exceeds
it (CI memory regression guard), 0 otherwise. ``--passes`` plans the
post-IR-pipeline program (all fuse knobs on — what the executor actually
lowers); with ``PADDLE_TPU_HBM_BUDGET_MB`` set that includes the
``auto_remat`` rewrite, so the report shows the post-remat plan.
Exit code: 0 = within budget (or no budget), 1 = budget exceeded,
2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
_TOOLS = os.path.join(_REPO, 'tools')
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


def _decode_pool_doc(args):
    """The itemized PADDLE_TPU_DECODE_HBM_MB solve, as a plain dict."""
    from paddle_tpu.analysis.plan import decode_pool_report
    from paddle_tpu.models.causal_lm import CausalLMConfig, TransformerLM
    cfg = (CausalLMConfig.tiny() if args.decode_model == 'tiny'
           else CausalLMConfig())
    report = decode_pool_report(TransformerLM(cfg), args.decode_pool_mb,
                                block_size=args.kv_block_size,
                                kv_dtype=args.kv_dtype)
    report['model'] = args.decode_model
    return report


def _format_decode_pool(doc):
    mib = 1 << 20
    yield (f"decode pool: {doc['num_blocks']} blocks of "
           f"{doc['block_size']} tokens at kv_dtype={doc['kv_dtype']} "
           f"({doc['model']} model)")
    yield (f"  budget {doc['budget_mb']} MiB - model state "
           f"{doc['model_state_bytes'] / mib:.1f} MiB -> "
           f"{doc['pool_bytes'] / mib:.1f} MiB of KV pages")
    yield (f"  block = {doc['kv_layers']} layers x 2 (K,V) x "
           f"{doc['kv_heads']} heads x {doc['block_size']} tokens x "
           f"{doc['row_bytes']} B/row = {doc['block_bytes']} B")


def main(argv=None):
    from lint_program import RECIPES, _build_recipe, _load_model

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group()
    src.add_argument('--model-dir',
                     help='saved inference model '
                          '(fluid.io.save_inference_model layout)')
    src.add_argument('--recipe', choices=RECIPES,
                     help='build one of the tier-1 recipe programs')
    ap.add_argument('--batch-size', type=int, default=16,
                    help='value substituted for dynamic (-1) batch dims '
                         '(default 16)')
    ap.add_argument('--budget', type=float, default=None,
                    help='HBM budget in MiB; exit 1 when the predicted '
                         'peak exceeds it')
    ap.add_argument('--passes', action='store_true',
                    help='plan the post-IR-pipeline program (fuse knobs '
                         'on; includes auto_remat when '
                         'PADDLE_TPU_HBM_BUDGET_MB is set)')
    ap.add_argument('--stages', type=int, default=None,
                    help='plan the program cut into N pipeline stages '
                         '(cost-model auto-cut, analysis.stage.'
                         'solve_stage_cuts) and print the per-stage '
                         'report; --budget then gates on the staged peak')
    ap.add_argument('--pp-schedule', choices=('gpipe', '1f1b',
                                              'interleaved'),
                    default='gpipe',
                    help='pipeline schedule the staged plan models '
                         '(default gpipe)')
    ap.add_argument('--pp-microbatches', type=int, default=None,
                    help='microbatch count for the staged plan; default '
                         'solves the smallest count that fits --budget '
                         '(analysis.stage.solve_microbatches), or the '
                         'stage count without a budget')
    ap.add_argument('--no-donate', action='store_true',
                    help='plan with buffer donation off '
                         '(PADDLE_TPU_DONATE=0 semantics)')
    ap.add_argument('--top', type=int, default=10,
                    help='rows in the residents / op-cost tables')
    ap.add_argument('--json', action='store_true',
                    help='emit the machine-readable plan')
    ap.add_argument('--decode-pool-mb', type=int, default=None,
                    help='print the decode KV pool sizing solve for this '
                         'HBM budget (MiB) — the PADDLE_TPU_DECODE_HBM_MB '
                         'arithmetic, itemized')
    ap.add_argument('--kv-dtype', choices=('f32', 'bf16', 'int8'),
                    default='f32',
                    help='KV pool storage dtype for the sizing solve '
                         '(PADDLE_TPU_KV_DTYPE; default f32)')
    ap.add_argument('--kv-block-size', type=int, default=16,
                    help='KV pool block size for the sizing solve '
                         '(default 16)')
    ap.add_argument('--decode-model', choices=('tiny', 'base'),
                    default='base',
                    help='CausalLM preset whose state/geometry the sizing '
                         'solve uses (default base)')
    args = ap.parse_args(argv)
    if args.batch_size <= 0:
        ap.error('--batch-size must be > 0')
    if args.stages is not None and args.stages < 2:
        ap.error('--stages must be >= 2')
    if args.pp_microbatches is not None and args.pp_microbatches <= 0:
        ap.error('--pp-microbatches must be > 0')
    if args.pp_microbatches is not None and args.stages is None:
        ap.error('--pp-microbatches requires --stages')
    if not (args.model_dir or args.recipe or args.decode_pool_mb):
        ap.error('one of --model-dir, --recipe or --decode-pool-mb '
                 'is required')
    if args.decode_pool_mb is not None and args.decode_pool_mb <= 0:
        ap.error('--decode-pool-mb must be > 0')
    if args.kv_block_size <= 0:
        ap.error('--kv-block-size must be > 0')

    os.environ.setdefault('PADDLE_TPU_VERIFY', 'full')
    from paddle_tpu.analysis.plan import plan_program

    pool_doc = _decode_pool_doc(args) if args.decode_pool_mb else None
    if not (args.model_dir or args.recipe):
        # decode-pool-only mode: no program to plan
        if args.json:
            print(json.dumps({'decode_pool': pool_doc}, indent=1))
        else:
            print('\n'.join(_format_decode_pool(pool_doc)))
        return 0

    if args.model_dir:
        program, fetches, feeds = _load_model(args.model_dir)
        label = args.model_dir
    else:
        program, fetches, feeds = _build_recipe(args.recipe)
        label = args.recipe

    if args.passes:
        from paddle_tpu import ir
        from paddle_tpu.compiler import BuildStrategy
        bs = BuildStrategy()
        bs.fuse_elewise_add_act_ops = True
        bs.fuse_all_optimizer_ops = True
        bs.fuse_all_reduce_ops = True
        program, _ctx = ir.apply_pipeline(program, fetch_names=fetches,
                                          feed_names=feeds,
                                          build_strategy=bs)

    plan = plan_program(program, fetch_names=fetches, feed_names=feeds,
                        donate=not args.no_donate,
                        assume_dim=args.batch_size)
    budget_bytes = int(args.budget * (1 << 20)) if args.budget else None

    splan = None
    if args.stages is not None:
        from paddle_tpu.analysis.stage import (plan_staged_program,
                                               solve_microbatches,
                                               solve_stage_cuts)
        cuts, _cut_report = solve_stage_cuts(
            program, args.stages, fetch_names=fetches, feed_names=feeds,
            assume_dim=args.batch_size)
        m = args.pp_microbatches
        if m is None:
            if budget_bytes:
                m, _peak, _fits = solve_microbatches(
                    program, cuts, args.pp_schedule, budget_bytes,
                    fetch_names=fetches, feed_names=feeds,
                    assume_dim=args.batch_size)
            else:
                m = args.stages
        splan = plan_staged_program(
            program, cuts, m, schedule=args.pp_schedule,
            fetch_names=fetches, feed_names=feeds,
            donate=not args.no_donate, assume_dim=args.batch_size)

    if args.json:
        doc = plan.to_dict(top=args.top)
        doc['target'] = label
        doc['batch_size'] = args.batch_size
        if budget_bytes:
            doc['budget_bytes'] = budget_bytes
            doc['fits_budget'] = plan.peak_bytes <= budget_bytes
        if splan is not None:
            doc['staged'] = splan.to_dict()
            if budget_bytes:
                doc['staged']['fits_budget'] = \
                    splan.host_peak_bytes <= budget_bytes
        if pool_doc:
            doc['decode_pool'] = pool_doc
        print(json.dumps(doc, indent=1))
    else:
        print(f'target: {label}  (batch dims assumed {args.batch_size}, '
              f'{plan.n_ops} ops, planned in '
              f'{plan.plan_seconds * 1e3:.1f}ms)')
        print('\n'.join(plan.format_report(top=args.top,
                                           budget_bytes=budget_bytes)))
        if splan is not None:
            print('\n'.join(splan.format_report(budget_bytes=budget_bytes)))
        if pool_doc:
            print('\n'.join(_format_decode_pool(pool_doc)))
    peak = splan.host_peak_bytes if splan is not None else plan.peak_bytes
    return 1 if budget_bytes and peak > budget_bytes else 0


if __name__ == '__main__':
    sys.exit(main())
