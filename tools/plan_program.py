"""Static memory/cost planner CLI (paddle_tpu/analysis/plan.py).

Loads a saved inference model — or builds one of the tier-1 recipe
programs — and prints the memory plan: predicted peak HBM, the residency
breakdown (state/donation, feeds, activations-into-backward, gradients),
the top residents at the peak, and the per-op FLOP/byte cost ranking.
Milliseconds, zero tracing — nothing is compiled or executed.

    JAX_PLATFORMS=cpu python tools/plan_program.py --recipe mnist_mlp
    JAX_PLATFORMS=cpu python tools/plan_program.py --recipe bert_layer \
        --batch-size 64 --passes
    JAX_PLATFORMS=cpu python tools/plan_program.py --model-dir /m \
        --budget 2048

``--budget MB`` gates the exit code: 1 when the predicted peak exceeds
it (CI memory regression guard), 0 otherwise. ``--passes`` plans the
post-IR-pipeline program (all fuse knobs on — what the executor actually
lowers); with ``PADDLE_TPU_HBM_BUDGET_MB`` set that includes the
``auto_remat`` rewrite, so the report shows the post-remat plan.
Exit code: 0 = within budget (or no budget), 1 = budget exceeded,
2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
_TOOLS = os.path.join(_REPO, 'tools')
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


def main(argv=None):
    from lint_program import RECIPES, _build_recipe, _load_model

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument('--model-dir',
                     help='saved inference model '
                          '(fluid.io.save_inference_model layout)')
    src.add_argument('--recipe', choices=RECIPES,
                     help='build one of the tier-1 recipe programs')
    ap.add_argument('--batch-size', type=int, default=16,
                    help='value substituted for dynamic (-1) batch dims '
                         '(default 16)')
    ap.add_argument('--budget', type=float, default=None,
                    help='HBM budget in MiB; exit 1 when the predicted '
                         'peak exceeds it')
    ap.add_argument('--passes', action='store_true',
                    help='plan the post-IR-pipeline program (fuse knobs '
                         'on; includes auto_remat when '
                         'PADDLE_TPU_HBM_BUDGET_MB is set)')
    ap.add_argument('--no-donate', action='store_true',
                    help='plan with buffer donation off '
                         '(PADDLE_TPU_DONATE=0 semantics)')
    ap.add_argument('--top', type=int, default=10,
                    help='rows in the residents / op-cost tables')
    ap.add_argument('--json', action='store_true',
                    help='emit the machine-readable plan')
    args = ap.parse_args(argv)
    if args.batch_size <= 0:
        ap.error('--batch-size must be > 0')

    os.environ.setdefault('PADDLE_TPU_VERIFY', 'full')
    from paddle_tpu.analysis.plan import plan_program

    if args.model_dir:
        program, fetches, feeds = _load_model(args.model_dir)
        label = args.model_dir
    else:
        program, fetches, feeds = _build_recipe(args.recipe)
        label = args.recipe

    if args.passes:
        from paddle_tpu import ir
        from paddle_tpu.compiler import BuildStrategy
        bs = BuildStrategy()
        bs.fuse_elewise_add_act_ops = True
        bs.fuse_all_optimizer_ops = True
        bs.fuse_all_reduce_ops = True
        program, _ctx = ir.apply_pipeline(program, fetch_names=fetches,
                                          feed_names=feeds,
                                          build_strategy=bs)

    plan = plan_program(program, fetch_names=fetches, feed_names=feeds,
                        donate=not args.no_donate,
                        assume_dim=args.batch_size)
    budget_bytes = int(args.budget * (1 << 20)) if args.budget else None

    if args.json:
        doc = plan.to_dict(top=args.top)
        doc['target'] = label
        doc['batch_size'] = args.batch_size
        if budget_bytes:
            doc['budget_bytes'] = budget_bytes
            doc['fits_budget'] = plan.peak_bytes <= budget_bytes
        print(json.dumps(doc, indent=1))
    else:
        print(f'target: {label}  (batch dims assumed {args.batch_size}, '
              f'{plan.n_ops} ops, planned in '
              f'{plan.plan_seconds * 1e3:.1f}ms)')
        print('\n'.join(plan.format_report(top=args.top,
                                           budget_bytes=budget_bytes)))
    return 1 if budget_bytes and plan.peak_bytes > budget_bytes else 0


if __name__ == '__main__':
    sys.exit(main())
