"""Merge per-process distributed span records into ONE chrome timeline.

Each traced process streams ``spans-<pid>.jsonl`` into
``PADDLE_TPU_TRACE_DIR`` (observability/distributed.py): a first-line
``clock`` record, one ``span`` record per completed span, and — on the
router — ``offset`` records carrying the health-handshake estimate of
each replica's (replica_unix − router_unix) clock offset. This tool
folds N such files into one chrome-trace JSON:

- every process's spans are shifted onto the OFFSET RECORDER's clock
  (``aligned_start = start_unix − offset[process]``), so a replica whose
  wall clock runs 5s fast still nests correctly inside the router's
  dispatch span;
- parent links are validated: every ``parent_span_id`` must resolve to
  a recorded span — the e2e failover drill asserts zero dangling
  parents across a router + two replicas + a kill -9;
- each process becomes one chrome "process" lane (``process_name``
  metadata), spans become ``X`` events tagged trace_id/span_id.

Usage::

    python tools/trace_merge.py <trace_dir | spans-*.jsonl ...> \
        [--out merged.json] [--trace-id ID]
    python tools/trace_merge.py --smoke      # self-check, prints JSON

``--smoke`` synthesizes two processes with a KNOWN injected clock skew
and verifies the merge re-aligns them (tier-1 gate).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_span_file(path):
    """→ ``{'clock': ..., 'spans': [...], 'offsets': [...]}`` from one
    spans JSONL file; torn tails (a kill -9 mid-line) are skipped."""
    out = {'clock': None, 'spans': [], 'offsets': []}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue              # torn final line of a killed process
            if 'clock' in rec and out['clock'] is None:
                out['clock'] = rec['clock']
            elif 'span' in rec:
                out['spans'].append(rec['span'])
            elif 'offset' in rec:
                out['offsets'].append(rec['offset'])
    return out


def merge_span_files(paths, trace_id=None):
    """Merge N span files → ``(chrome_doc, summary)``.

    `summary` carries the validation verdict: span/process/trace counts,
    the offset table applied, and ``unresolved_parents`` — span ids whose
    parent was never recorded (0 on a correct propagation chain)."""
    docs = [load_span_file(p) for p in paths]

    # offset table: the recording process (router / host 0) measured
    # everyone else's clock against its own; it is itself the reference.
    offsets = {}
    for doc in docs:
        for off in doc['offsets']:
            # last write wins: offsets re-estimate every health poll
            offsets[str(off['process'])] = float(off['offset_s'])
        if doc['offsets'] and doc['clock']:
            offsets.setdefault(str(doc['clock']['process']), 0.0)

    spans = []
    processes = []                    # label order = chrome pid order
    for doc in docs:
        label = str(doc['clock']['process']) if doc['clock'] else '?'
        if label not in processes:
            processes.append(label)
        for span in doc['spans']:
            span = dict(span)
            span.setdefault('process', label)
            if trace_id is not None and span.get('trace_id') != trace_id:
                continue
            span['aligned_start'] = (span['start_unix']
                                     - offsets.get(span['process'], 0.0))
            spans.append(span)
    spans.sort(key=lambda s: s['aligned_start'])

    by_id = {s['span_id']: s for s in spans}
    unresolved = sorted({s['span_id'] for s in spans
                         if s.get('parent_span_id')
                         and s['parent_span_id'] not in by_id})

    pid_of = {label: i for i, label in enumerate(processes)}
    t0 = spans[0]['aligned_start'] if spans else 0.0
    events = [{'name': 'process_name', 'ph': 'M', 'pid': pid,
               'tid': 0, 'args': {'name': label}}
              for label, pid in sorted(pid_of.items(),
                                       key=lambda kv: kv[1])]
    for s in spans:
        args = dict(s.get('args') or {})
        args['trace_id'] = s.get('trace_id')
        args['span_id'] = s['span_id']
        if s.get('parent_span_id'):
            args['parent_span_id'] = s['parent_span_id']
        events.append({
            'name': s['name'], 'ph': 'X',
            'ts': (s['aligned_start'] - t0) * 1e6,
            'dur': max(0.0, s['dur_s']) * 1e6,
            'pid': pid_of.get(s.get('process', '?'), 0), 'tid': 0,
            'args': args})

    chrome = {'traceEvents': events,
              'otherData': {'aligned_by': 'paddle_tpu trace_merge',
                            'offsets_s': offsets,
                            'epoch_unix': t0}}
    summary = {'files': len(paths), 'processes': processes,
               'spans': len(spans),
               'traces': len({s.get('trace_id') for s in spans}),
               'offsets_s': offsets,
               'unresolved_parents': unresolved}
    return chrome, summary


def spans_for_trace(chrome, trace_id):
    """Convenience for drills: the merged X events of one trace,
    time-ordered."""
    return sorted((e for e in chrome['traceEvents']
                   if e['ph'] == 'X'
                   and e['args'].get('trace_id') == trace_id),
                  key=lambda e: e['ts'])


# ---------------------------------------------------------------------------
# --smoke: synthesize two skewed processes, verify re-alignment
# ---------------------------------------------------------------------------

_SMOKE_SKEW_S = 5.0                   # replica clock runs 5s fast


def _smoke(tmpdir):
    """Two synthetic processes: a 'router' whose dispatch span covers a
    'replica' span, with the replica's wall clock skewed +5s. Without
    offset correction the replica span lands 5s OUTSIDE its parent;
    the merge must pull it back inside."""
    base = 1700000000.0
    tid, root, disp, rspan = 'a' * 16, 'b' * 16, 'c' * 16, 'd' * 16
    router = [
        {'clock': {'pid': 1, 'process': 'router', 'unix_time': base,
                   'perf_counter': 0.0}},
        {'offset': {'process': 'replica-a', 'offset_s': _SMOKE_SKEW_S,
                    'rtt_s': 0.001, 'unix_time': base}},
        {'span': {'name': 'router/request', 'trace_id': tid,
                  'span_id': root, 'parent_span_id': None,
                  'start_unix': base, 'dur_s': 1.0, 'process': 'router'}},
        {'span': {'name': 'router/dispatch', 'trace_id': tid,
                  'span_id': disp, 'parent_span_id': root,
                  'start_unix': base + 0.1, 'dur_s': 0.8,
                  'process': 'router'}},
    ]
    replica = [
        {'clock': {'pid': 2, 'process': 'replica-a',
                   'unix_time': base + _SMOKE_SKEW_S,
                   'perf_counter': 0.0}},
        # the replica's stamps are on ITS (fast) clock: truly at
        # base+0.3 but recorded as base+skew+0.3
        {'span': {'name': 'replica/prefill', 'trace_id': tid,
                  'span_id': rspan, 'parent_span_id': disp,
                  'start_unix': base + _SMOKE_SKEW_S + 0.3, 'dur_s': 0.2,
                  'process': 'replica-a'}},
    ]
    paths = []
    for name, records in (('spans-1.jsonl', router),
                          ('spans-2.jsonl', replica)):
        p = os.path.join(tmpdir, name)
        with open(p, 'w') as f:
            for rec in records:
                f.write(json.dumps(rec) + '\n')
        paths.append(p)

    chrome, summary = merge_span_files(paths)
    ordered = spans_for_trace(chrome, tid)
    by_name = {e['name']: e for e in ordered}
    disp_ev, rep_ev = by_name['router/dispatch'], by_name['replica/prefill']
    checks = {
        'all_spans_merged': summary['spans'] == 3,
        'parents_resolve': summary['unresolved_parents'] == [],
        'offset_applied': summary['offsets_s'].get('replica-a')
        == _SMOKE_SKEW_S,
        # the realigned replica span must nest INSIDE its parent dispatch
        'replica_nested_in_dispatch':
            disp_ev['ts'] <= rep_ev['ts']
            and rep_ev['ts'] + rep_ev['dur']
            <= disp_ev['ts'] + disp_ev['dur'] + 1,   # 1 µs float slack
        'time_ordered': [e['name'] for e in ordered]
        == ['router/request', 'router/dispatch', 'replica/prefill'],
    }
    return {'ok': all(checks.values()), 'checks': checks,
            'summary': summary}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('paths', nargs='*',
                    help='a trace dir (globs spans-*.jsonl) or explicit '
                         'span files')
    ap.add_argument('--out', help='write the merged chrome trace here')
    ap.add_argument('--trace-id', help='keep only this trace')
    ap.add_argument('--smoke', action='store_true',
                    help='self-check on synthetic skewed input')
    args = ap.parse_args(argv)

    if args.smoke:
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            verdict = _smoke(td)
        print(json.dumps(verdict, indent=1))
        return 0 if verdict['ok'] else 1

    paths = []
    for p in args.paths:
        if os.path.isdir(p):
            paths.extend(sorted(glob.glob(os.path.join(p,
                                                       'spans-*.jsonl'))))
        else:
            paths.append(p)
    if not paths:
        print('trace_merge: no span files (pass a PADDLE_TPU_TRACE_DIR '
              'or spans-*.jsonl paths)', file=sys.stderr)
        return 2
    chrome, summary = merge_span_files(paths, trace_id=args.trace_id)
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(chrome, f)
        summary['out'] = args.out
    print(json.dumps(summary, indent=1))
    return 0 if not summary['unresolved_parents'] else 1


if __name__ == '__main__':
    sys.exit(main())
