"""Elastic runtime benchmark (ISSUE 19): autoscaler ramp + resize
accounting. One JSON line per section.

1. ``elastic_autoscale_ramp`` — open-loop Poisson arrivals ramped
   low → high → zero against a 1-replica tier with the REAL autoscaler
   control loop running (real windowed-series signals, real cold-replica
   launches behind the warmup gate, real drain-then-retire on the way
   down). Reports replica-count-over-time, every decision with its
   trigger, time-to-routable for the launched replicas, and the zero-drop
   acceptance: every request of the whole ramp completes with the
   reference bytes.
2. ``elastic_resize_accounting`` — the goodput contract for scheduled
   resizes vs crashes: a scheduled resize books ONLY downtime into its
   own bucket (``resizes``/``resize_lost_s``; lost_steps == 0 because the
   resize checkpoint is synchronous at the boundary), while a crash books
   cadence-predicted lost steps into the crash bucket. Smoke verifies the
   accounting math on synthetic heartbeats; the full mode's subprocess
   fleet drill lives in tests/framework/test_elastic_resize.py.

Runs on any backend; CPU is the honest configuration (control-loop and
accounting behaviour are the quantities under test):

  JAX_PLATFORMS=cpu python tools/bench_elastic.py [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

# runnable as `python tools/bench_elastic.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _hist(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return {'count': 0, 'mean': None}
    count = sum(s.get('count', 0) for s in d['samples'])
    total = sum(s.get('sum', 0.0) for s in d['samples'])
    return {'count': count,
            'mean': round(total / count, 4) if count else None}


class _Replica:
    """In-process replica stack + HTTP listener."""

    def __init__(self, model, lock, rid, warm=True):
        from paddle_tpu.serving import ServingServer
        from paddle_tpu.serving.tier.replica import build_replica_stack
        self.engine, self.scheduler, _ = build_replica_stack(
            model=model, model_lock=lock, replica_id=rid)
        if warm:
            self.engine.warmup()
        self.server = ServingServer(None, port=0,
                                    generator=self.scheduler).start()
        self.url = f'http://127.0.0.1:{self.server.port}'

    def shutdown(self, drain=True):
        self.scheduler.close(drain=drain, timeout=30)
        self.server.shutdown(drain=drain)


def bench_autoscale_ramp(smoke):
    from paddle_tpu.dygraph import guard
    from paddle_tpu.elastic.autoscaler import AutoscaleConfig, Autoscaler
    from paddle_tpu.elastic.launcher import CallableReplicaLauncher
    from paddle_tpu.models.causal_lm import greedy_generate
    from paddle_tpu.observability import distributed as _dobs
    from paddle_tpu.serving import Router
    from paddle_tpu.serving.tier.replica import build_tiny_lm

    # short signal windows so the ramp-DOWN half of the drill sees the
    # load fall off within bench time (production default: 6 x 10s)
    for name in ('queue_depth', 'occupancy', 'ttft'):
        _dobs.series(name, window_s=1.0, windows=3)

    with guard():
        lm = build_tiny_lm()
    lock = threading.RLock()
    replicas = {}
    n = [0]

    def launch():
        n[0] += 1
        rep = _Replica(lm, lock, f'auto-{n[0]}', warm=False)
        replicas[rep.url] = rep
        # cold start on a thread: the warmup gate (not the launcher)
        # holds traffic until the compile cliff is behind the replica
        threading.Thread(target=rep.engine.warmup, daemon=True).start()
        return rep.url

    def retire(url):
        replicas.pop(url).shutdown()

    seed = _Replica(lm, lock, 'auto-0', warm=True)
    replicas[seed.url] = seed
    launcher = CallableReplicaLauncher(launch, retire)
    router = Router([seed.url], health_poll_s=0.25)
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=2 if smoke else 3,
        interval_s=0.2, up_queue=1.0, up_ttft_s=60.0,
        down_occupancy=0.25, cooldown_s=1.5, down_delay_s=2.0)
    scaler = Autoscaler(router, launcher, cfg)

    prompt = [5, 9, 2, 44]
    new_tokens = 4
    ref = greedy_generate(lm, prompt, new_tokens,
                          pad_len=seed.engine.padded_context)
    results, errors = [], []
    results_lock = threading.Lock()

    def one_request():
        try:
            r = router.generate(prompt, max_new_tokens=new_tokens,
                                timeout=60)
            with results_lock:
                results.append(r)
        except Exception as e:   # noqa: BLE001 — drops are the metric
            with results_lock:
                errors.append(f'{type(e).__name__}: {e}')

    # open-loop Poisson arrivals: low -> high -> zero
    rng = np.random.default_rng(0)
    phases = ([(2.0, 1.5), (10.0, 3.0)] if smoke
              else [(2.0, 3.0), (12.0, 6.0)])
    arrivals, t = [], 0.0
    for rate, dur in phases:
        end = t + dur
        while True:
            t += rng.exponential(1.0 / rate)
            if t >= end:
                t = end
                break
            arrivals.append(t)

    count_samples = []           # (t, replicas, routable)
    stop_sampling = threading.Event()

    def sampler():
        t0 = time.monotonic()
        while not stop_sampling.wait(0.25):
            reps = list(router.replicas)
            count_samples.append(
                (round(time.monotonic() - t0, 2), len(reps),
                 sum(r.routable() for r in reps)))

    threading.Thread(target=sampler, daemon=True).start()
    workers = []
    t0 = time.monotonic()
    for at in arrivals:
        delay = t0 + at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        w = threading.Thread(target=one_request)
        w.start()
        workers.append(w)
    for w in workers:
        w.join(120)
    # idle tail: let sustained-low drain the tier back to min
    deadline = time.monotonic() + (10 if smoke else 20)
    while time.monotonic() < deadline and len(router.replicas) > 1:
        time.sleep(0.25)
    stop_sampling.set()
    decisions = [{'action': d['action'], 'trigger': d['trigger'],
                  'replicas': d['replicas']} for d in scaler.decisions]
    max_reps = max((c[1] for c in count_samples), default=1)
    final_reps = len(router.replicas)
    bitwise = all(r['tokens'] == ref for r in results)
    scaler.close()
    router.close()
    for rep in list(replicas.values()):
        try:
            rep.shutdown()
        except Exception:
            pass
    out = {
        'bench': 'elastic_autoscale_ramp',
        'requests': len(arrivals),
        'completed': len(results),
        'dropped': len(arrivals) - len(results),
        'errors': errors[:5],
        'bitwise_equal': bool(bitwise),
        'max_replicas_seen': max_reps,
        'max_replicas_cap': cfg.max_replicas,
        'final_replicas': final_reps,
        'scaled_up': any(d['action'] == 'up' for d in decisions),
        'scaled_down': any(d['action'] == 'down' for d in decisions),
        'decisions': decisions,
        'time_to_routable_s': _hist('autoscale_time_to_routable_seconds'),
        'drain_s': _hist('autoscale_drain_seconds'),
        'replica_count_timeline': count_samples[:: max(
            1, len(count_samples) // 24)],
    }
    assert out['dropped'] == 0 and not errors, (out['dropped'], errors[:3])
    assert bitwise
    assert out['scaled_up'] and max_reps > 1
    assert max_reps <= cfg.max_replicas
    assert all(d['trigger'] for d in decisions)
    return out


def bench_resize_accounting(smoke):
    """Goodput bucket separation on synthetic heartbeats: the scheduled
    resize books pure downtime (zero lost steps — its checkpoint is
    synchronous AT the boundary); a crash at the same step books exactly
    the cadence-predicted replay."""
    from paddle_tpu.resilience.goodput import GoodputTracker
    cadence, crash_step = 5, 13
    ckpt_step = (crash_step // cadence) * cadence          # 10
    predicted_lost = crash_step - ckpt_step                # 3
    base = time.time()

    crash = GoodputTracker()
    crash.record_restart(
        {'steps': ckpt_step, 'productive_s': float(ckpt_step),
         'wall_s': float(crash_step) + 1.0},
        {'steps': crash_step, 'productive_s': float(crash_step),
         'wall_s': float(crash_step) + 1.5, 'unix_time': base - 7.0})

    resize = GoodputTracker()
    resize.record_restart(
        # a scheduled resize checkpoints the exit boundary itself
        {'steps': crash_step, 'productive_s': float(crash_step),
         'wall_s': float(crash_step) + 1.0},
        {'steps': crash_step, 'productive_s': float(crash_step),
         'wall_s': float(crash_step) + 1.0, 'unix_time': base - 7.0,
         'resize_exit': True})

    out = {
        'bench': 'elastic_resize_accounting',
        'cadence': cadence,
        'crash_step': crash_step,
        'predicted_lost_steps': predicted_lost,
        'crash': {'lost_steps': crash.lost_steps,
                  'lost_s': round(crash.lost_s, 3),
                  'resizes': crash.resizes,
                  'resize_lost_s': round(crash.resize_lost_s, 3)},
        'resize': {'lost_steps': resize.lost_steps,
                   'lost_s': round(resize.lost_s, 3),
                   'resizes': resize.resizes,
                   'resize_lost_s': round(resize.resize_lost_s, 3)},
        'buckets_separate': (
            crash.lost_steps == predicted_lost and crash.resizes == 0
            and resize.lost_steps == 0 and resize.resizes == 1
            and resize.resize_lost_s > 0.0),
        'fleet_drill': 'tests/framework/test_elastic_resize.py',
    }
    assert out['buckets_separate'], out
    return out


def measure_all(smoke=False):
    out = {}
    for fn in (bench_autoscale_ramp, bench_resize_accounting):
        d = fn(smoke)
        out[d['bench']] = d
        print(json.dumps(d), flush=True)
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('--smoke', action='store_true',
                    help='short phases, max 2 replicas (tier-1 CI gate)')
    args = ap.parse_args()
    measure_all(smoke=args.smoke)


if __name__ == '__main__':
    main()
