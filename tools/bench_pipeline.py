"""Async train-loop pipeline A/B (PERF.md §12).

The scenario the pipeline targets: a HOST-BOUND reader (each batch costs
I/O-shaped host latency — disk/network/decode time, simulated here with a
sleep sized relative to the step's device time) feeding a COMPUTE-BOUND
static training step. The synchronous loop serializes the two — every
`Executor.run` ends in a blocking `np.asarray` per fetch, so a step costs
reader + compute + D2H. The async pipeline (`PADDLE_TPU_ASYNC`,
executor.py) returns non-blocking FetchHandles and keeps K=2 dispatched
steps in flight, so reader time for step N+1 overlaps device execution of
step N: steady state approaches max(reader, compute) instead of the sum.

Measures, on the SAME program/executor/feeds (one compile, shared by both
modes since async is not part of the step-cache key):

- steady-state steps/s, sync (`PADDLE_TPU_ASYNC=0`, `return_numpy=True`)
  vs async (K in flight, handles materialized at the end);
- bitwise identity of every fetched loss between the modes (the pipeline
  reorders HOST work only — the dispatched computation stream, its RNG
  folding, and the donation schedule are identical);
- the measured per-step compute and the injected reader latency, so the
  theoretical ceiling ((reader + compute) / max(reader, compute)) is
  printed next to the achieved speedup.

Valid on CPU — the quantity under test is host/device overlap, not FLOPs:

  JAX_PLATFORMS=cpu python tools/bench_pipeline.py [--smoke] [--steps N]
      [--io-scale 1.0] [--k 2]

Acceptance (tier-1, tests/framework/test_bench_pipeline.py): async ≥ 1.3×
sync steps/s at smoke sizes with bitwise-identical losses.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/bench_pipeline.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_mlp(smoke=False):
    """MNIST-shaped MLP regression under SGD — compute-bound, RNG-free
    (no dropout), so sync/async parity is bitwise by construction.
    Returns (main, startup, feeds(list), loss)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    # sized so per-step device compute dominates the executor's host-side
    # dispatch cost (a few ms) — the overlap under test needs a
    # compute-bound step, not a dispatch-bound one
    width, depth, bs = (1024, 8, 256) if smoke else (1536, 8, 384)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('pipe_x', [784], dtype='float32')
        y = L.data('pipe_y', [1], dtype='float32')
        h = x
        for _ in range(depth):
            h = L.fc(h, size=width, act='relu')
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=1e-3).minimize(loss)
    return main, startup, bs, loss


def _make_feeds(bs, steps, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    return [{'pipe_x': rng.randn(bs, 784).astype(np.float32),
             'pipe_y': rng.randn(bs, 1).astype(np.float32)}
            for _ in range(steps)]


def _snapshot_state(program, scope):
    import numpy as np
    return {v.name: np.asarray(scope.find(v.name))
            for v in program.list_vars() if v.persistable}


def _restore_state(snap, scope):
    import jax.numpy as jnp
    for n, v in snap.items():
        scope.set(n, jnp.asarray(v))


def _run_phase(exe, main, loss, feeds, io_s, mode_env):
    """One timed loop: simulated-I/O reader + Executor.run per step, all
    fetches materialized before the clock stops. Returns (seconds,
    [loss bytes])."""
    import numpy as np
    os.environ['PADDLE_TPU_ASYNC'] = mode_env
    results = []
    t0 = time.perf_counter()
    for feed in feeds:
        time.sleep(io_s)          # host-bound reader: simulated I/O latency
        results.append(exe.run(main, feed=feed, fetch_list=[loss])[0])
    got = [np.asarray(r) for r in results]     # async: drain the window
    dt = time.perf_counter() - t0
    return dt, [g.tobytes() for g in got]


def measure_pipeline(smoke=False, steps=None, io_scale=1.0, k=2):
    import numpy as np
    import paddle_tpu as fluid

    main, startup, bs, loss = build_mlp(smoke)
    steps = steps or (8 if smoke else 16)
    feeds = _make_feeds(bs, steps)
    old_env = os.environ.get('PADDLE_TPU_ASYNC')
    scope = fluid.Scope()
    try:
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            snap = _snapshot_state(main, scope)

            # warm BOTH compiled variants (async runs copy-in/copy-out, and
            # donation is part of the step-cache key, so sync and async
            # compile separately) + measure per-step device compute (sync)
            os.environ['PADDLE_TPU_ASYNC'] = '0'
            exe.run(main, feed=feeds[0], fetch_list=[loss])
            t0 = time.perf_counter()
            for _ in range(2):
                exe.run(main, feed=feeds[0], fetch_list=[loss])
            compute_s = (time.perf_counter() - t0) / 2
            io_s = max(compute_s * io_scale, 1e-3)
            os.environ['PADDLE_TPU_ASYNC'] = str(k)
            np.asarray(exe.run(main, feed=feeds[0], fetch_list=[loss])[0])

            _restore_state(snap, scope)
            sync_s, sync_losses = _run_phase(exe, main, loss, feeds, io_s,
                                             '0')
            _restore_state(snap, scope)
            async_s, async_losses = _run_phase(exe, main, loss, feeds, io_s,
                                               str(k))
    finally:
        if old_env is None:
            os.environ.pop('PADDLE_TPU_ASYNC', None)
        else:
            os.environ['PADDLE_TPU_ASYNC'] = old_env

    identical = sync_losses == async_losses
    sync_sps = steps / sync_s
    async_sps = steps / async_s
    return {'bench': 'async_pipeline',
            'steps': steps, 'k': k, 'batch': bs,
            'io_ms': round(io_s * 1e3, 3),
            'compute_ms': round(compute_s * 1e3, 3),
            'sync_steps_per_s': round(sync_sps, 3),
            'async_steps_per_s': round(async_sps, 3),
            'speedup': round(async_sps / sync_sps, 3),
            'theoretical_ceiling': round(
                (io_s + compute_s) / max(io_s, compute_s), 3),
            'bitwise_identical': bool(identical)}


def measure_staged_feeds(smoke=False):
    """Zero-copy staged-feed passthrough: a DataLoader loop under telemetry
    must show every staged byte passed through the Executor without a
    second device_put (`executor_feed_passthrough_bytes` ==
    `dataloader_staged_bytes`)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import observability as obs

    main, startup, bs, loss = build_mlp(smoke=True)
    feeds = _make_feeds(bs, 4, seed=1)
    x = main.global_block().var('pipe_x')
    y = main.global_block().var('pipe_y')
    loader = fluid.DataLoader.from_generator(feed_list=[x, y], capacity=4)
    loader.set_batch_generator(
        lambda: iter([(f['pipe_x'], f['pipe_y']) for f in feeds]))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        with obs.telemetry_guard(True):
            obs.reset()
            for batch in loader():
                exe.run(main, feed=batch, fetch_list=[loss])
            m = obs.registry.to_dict()
    staged = sum(s['value']
                 for s in m['dataloader_staged_bytes']['samples'])
    passed = sum(s['value']
                 for s in m.get('executor_feed_passthrough_bytes',
                                {'samples': []})['samples'])
    return {'bench': 'staged_feed_passthrough',
            'staged_bytes': int(staged),
            'passthrough_bytes': int(passed),
            'zero_copy': bool(staged > 0 and passed == staged)}


def measure_all(smoke=False, steps=None, io_scale=1.0, k=2):
    return {'async_pipeline': measure_pipeline(smoke=smoke, steps=steps,
                                               io_scale=io_scale, k=k),
            'staged_feeds': measure_staged_feeds(smoke=smoke)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true',
                    help='tiny shapes / CI smoke sizes')
    ap.add_argument('--steps', type=int, default=None,
                    help='timed steps per mode')
    ap.add_argument('--io-scale', type=float, default=1.0,
                    help='reader latency as a fraction of measured step '
                         'compute time')
    ap.add_argument('--k', type=int, default=2,
                    help='in-flight window depth for the async phase')
    args = ap.parse_args()
    for res in measure_all(smoke=args.smoke, steps=args.steps,
                           io_scale=args.io_scale, k=args.k).values():
        print(json.dumps(res), flush=True)


if __name__ == '__main__':
    main()
