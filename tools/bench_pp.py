"""Pipeline-parallel schedule bench (PERF.md "Pipeline parallelism").

Two measurements, both on the executor's real lowering path (the same
`PipelineOptimizer` stamp → scan/1F1B lowering a training script hits):

1. ``measure_schedules`` — an activation-heavy deep MLP cut into 2
   stages, GPipe vs 1F1B at the same cut and microbatch count:

   - bitwise loss parity across the schedules (they are the same
     arithmetic — 1F1B only reorders the backward);
   - PREDICTED host peak from the staged planner
     (`analysis.stage.plan_staged_program`) — GPipe keeps all m
     microbatches of residuals in flight, 1F1B one wave;
   - MEASURED XLA temp bytes of the compiled step
     (`jit(...).lower(...).compile().memory_analysis()`), so the
     planner's prediction is checked against the compiler, not assumed;
   - steps/s for both schedules.

2. ``measure_autocut`` — the bert_layer recipe: every manual single-cut
   candidate (`analysis.stage.stage_cut_candidates`) is scored through
   the staged planner and the cost-model auto-cut
   (`solve_stage_cuts`) must land within 5% of the best manual cut.

Valid on CPU — parity, planner-vs-XLA agreement, and cut quality are
host-independent claims; steps/s is reported for trend only (a CPU host
pipelines nothing, so 1F1B ≈ GPipe throughput here — the schedule's win
is the peak-residency column).

  JAX_PLATFORMS=cpu python tools/bench_pp.py [--smoke] [--steps N]

Acceptance (tier-1, tests/framework/test_bench_pp.py): bitwise parity,
1F1B predicted AND measured peak <= GPipe, auto-cut within 5%.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/bench_pp.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
_TOOLS = os.path.join(_REPO, 'tools')
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


def build_pp_mlp(smoke=False):
    """Activation-heavy deep MLP under a 2-stage PipelineOptimizer
    (auto-cut, schedule stamped gpipe — the env knob flips it without a
    rebuild). Returns (main, startup, bs, loss)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    width, depth, bs = (128, 8, 32) if smoke else (512, 12, 128)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('pp_x', [width], dtype='float32')
        y = L.data('pp_y', [1], dtype='float32')
        h = x
        for _ in range(depth):
            h = L.fc(h, size=width, act='relu')
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        opt = fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(learning_rate=1e-3),
            num_stages=2, num_microbatches=4, schedule='gpipe')
        opt.minimize(loss)
    return main, startup, bs, loss


def _pipeline_stamp(program):
    """The marker's stamped pipeline plan (cut_vars/m/schedule)."""
    for op in reversed(program.global_block().ops):
        pipe = op.attrs.get('pipeline')
        if pipe:
            return pipe
    raise ValueError('no pipeline stamp on the program')


def _measured_temp_bytes(exe, program, feed, fetch_names, scope):
    """XLA's temp-buffer bytes for the step the executor just compiled:
    re-lower the same (program, feeds, fetches) through the executor's
    own `_lower` and ask the compiled artifact, donation included."""
    import jax
    import numpy as np
    from paddle_tpu import ir
    from paddle_tpu.core.random import default_generator
    from paddle_tpu.executor import _lower

    feed_vals = {n: np.asarray(v) for n, v in feed.items()}
    state_names = sorted(v.name for v in program.list_vars()
                         if v.persistable
                         and scope.find(v.name) is not None)
    opt_program, _ = ir.apply_pipeline(
        program, fetch_names=fetch_names, feed_names=list(feed_vals))
    step = _lower(opt_program, list(feed_vals), fetch_names, state_names,
                  feed_shapes={n: v.shape for n, v in feed_vals.items()})
    dstate = {n: scope.find(n) for n in state_names}
    key = default_generator.base_key()
    compiled = jax.jit(step, donate_argnums=(0,)).lower(
        dstate, {}, feed_vals, key).compile()
    return int(compiled.memory_analysis().temp_size_in_bytes)


def measure_schedules(smoke=False, steps=None):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.analysis.stage import plan_staged_program
    from paddle_tpu.core.random import default_generator

    main, startup, bs, loss = build_pp_mlp(smoke)
    steps = steps or (4 if smoke else 8)
    stamp = _pipeline_stamp(main)
    cuts, m = list(stamp['cut_vars']), int(stamp['num_microbatches'])
    rng = np.random.RandomState(0)
    feeds = [{'pp_x': rng.randn(bs, main.global_block().var('pp_x')
                                .shape[-1]).astype(np.float32),
              'pp_y': rng.randn(bs, 1).astype(np.float32)}
             for _ in range(steps)]
    fetch = [loss.name]

    old_env = os.environ.get('PADDLE_TPU_PP_SCHEDULE')
    out = {'bench': 'pipeline_schedules', 'steps': steps, 'batch': bs,
           'microbatches': m, 'cut_vars': cuts, 'schedules': {}}
    losses = {}
    try:
        for sched in ('gpipe', '1f1b'):
            os.environ['PADDLE_TPU_PP_SCHEDULE'] = sched
            splan = plan_staged_program(
                main, cuts, m, schedule=sched, fetch_names=fetch,
                feed_names=['pp_x', 'pp_y'],
                feed_shapes={'pp_x': feeds[0]['pp_x'].shape,
                             'pp_y': feeds[0]['pp_y'].shape})
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                default_generator.seed(42)
                exe = fluid.Executor()
                exe.run(startup)
                exe.run(main, feed=feeds[0], fetch_list=fetch)  # compile
                measured = _measured_temp_bytes(exe, main, feeds[0],
                                                fetch, scope)
                # re-seed state so both schedules see identical params
                exe.run(startup)
                default_generator.seed(42)
                got, t0 = [], time.perf_counter()
                for feed in feeds:
                    got.append(np.asarray(
                        exe.run(main, feed=feed, fetch_list=fetch)[0]))
                dt = time.perf_counter() - t0
            losses[sched] = [g.tobytes() for g in got]
            out['schedules'][sched] = {
                'predicted_host_peak_bytes': int(splan.host_peak_bytes),
                'measured_temp_bytes': measured,
                'steps_per_s': round(steps / dt, 3),
            }
    finally:
        if old_env is None:
            os.environ.pop('PADDLE_TPU_PP_SCHEDULE', None)
        else:
            os.environ['PADDLE_TPU_PP_SCHEDULE'] = old_env

    g, f = out['schedules']['gpipe'], out['schedules']['1f1b']
    out['bitwise_identical'] = losses['gpipe'] == losses['1f1b']
    out['predicted_1f1b_le_gpipe'] = (f['predicted_host_peak_bytes']
                                      <= g['predicted_host_peak_bytes'])
    out['measured_1f1b_le_gpipe'] = (f['measured_temp_bytes']
                                     <= g['measured_temp_bytes'])
    return out


def measure_autocut(smoke=False, tolerance=0.05):
    """Auto-cut vs every manual single cut on the bert_layer recipe,
    scored by the staged planner's max per-stage cost (flops+bytes)."""
    from lint_program import _build_recipe
    from paddle_tpu.analysis.stage import (plan_staged_program,
                                           solve_stage_cuts,
                                           stage_cut_candidates)

    program, fetches, feeds = _build_recipe('bert_layer')
    bs = 8 if smoke else 16

    def cut_cost(cuts):
        splan = plan_staged_program(program, cuts, 2, schedule='1f1b',
                                    fetch_names=fetches, feed_names=feeds,
                                    assume_dim=bs)
        return max(r.flops + r.bytes for r in splan.stages)

    cands = stage_cut_candidates(program, fetch_names=fetches,
                                 feed_names=feeds, assume_dim=bs)
    manual = {c: cut_cost([c]) for c in cands}
    best_var = min(manual, key=manual.get)
    auto_cuts, report = solve_stage_cuts(program, 2, fetch_names=fetches,
                                         feed_names=feeds, assume_dim=bs)
    auto_cost = cut_cost(auto_cuts)
    return {'bench': 'pipeline_autocut', 'recipe': 'bert_layer',
            'candidates': len(cands),
            'auto_cut': auto_cuts, 'auto_cost': int(auto_cost),
            'best_manual_cut': best_var,
            'best_manual_cost': int(manual[best_var]),
            'balance': round(report['balance'], 4),
            'within_tolerance': bool(
                auto_cost <= manual[best_var] * (1 + tolerance))}


def measure_all(smoke=False, steps=None):
    return {'schedules': measure_schedules(smoke=smoke, steps=steps),
            'autocut': measure_autocut(smoke=smoke)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true',
                    help='tiny shapes / CI smoke sizes')
    ap.add_argument('--steps', type=int, default=None,
                    help='timed steps per schedule')
    args = ap.parse_args()
    for res in measure_all(smoke=args.smoke, steps=args.steps).values():
        print(json.dumps(res), flush=True)


if __name__ == '__main__':
    main()
