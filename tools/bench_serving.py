"""Serving-path load benchmark (PERF.md §11).

Load generator over the micro-batching serving stack (paddle_tpu/serving/):
N client threads each fire single-row requests at a :class:`MicroBatcher`
and wait for their result before firing the next — the classic closed-loop
model, so measured latency includes queueing — plus an OPEN-LOOP Poisson
section whose arrivals don't wait for completions (closed-loop load
coordinates with the server and understates tail latency — 'coordinated
omission'). Four sections, one JSON line each:

1. ``serving_serial_baseline`` — the pre-subsystem path: one
   ``Predictor.run`` per request, serially. This is what every request paid
   before the batcher existed.
2. ``serving_batcher`` — the same request stream through the dynamic
   micro-batcher (bucket ladder + padding + one device call per batch).
   Reports throughput, p50/p99 latency, mean coalesced batch rows, mean
   padding-waste ratio, and **bitwise parity** of every response against the
   serial baseline outputs. Acceptance (PERF.md §11): ≥ 5× the serial
   throughput at max_batch_size=16 on CPU.
3. ``serving_open_loop`` — seeded Poisson arrivals at ~3× the serial rate:
   offered vs achieved throughput, completion-stamped p50/p99 (via
   ``PredictionFuture.add_done_callback``), typed rejections.
4. ``serving_overload`` — backpressure: a burst larger than the bounded
   queue against a deliberately slow engine must produce typed
   ``Overloaded`` rejections (no hangs, no crashes) and leave the admitted
   requests answered.

Runs on any backend; CPU is the honest configuration (the quantity under
test is dispatch amortization, not FLOPs):

  JAX_PLATFORMS=cpu python tools/bench_serving.py [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

# runnable as `python tools/bench_serving.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

FEATURES = 64
CLASSES = 10


def build_model(dirname):
    """Save a small MLP inference model; returns its directory."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[FEATURES], dtype='float32')
        h = layers.fc(x, 128, act='relu')
        h = layers.fc(h, 128, act='relu')
        out = layers.fc(h, CLASSES, act='softmax')
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        fluid.io.save_inference_model(dirname, ['x'], [out], exe, main)
    return dirname


def _pctl(latencies, q):
    return round(float(np.percentile(np.asarray(latencies) * 1e3, q)), 3)


def measure_serial(model_dir, X, requests):
    """One Predictor.run per request, serially — the baseline every request
    paid before the serving subsystem. Returns (section dict, row outputs)."""
    from paddle_tpu.inference import Predictor
    pred = Predictor(model_dir)
    pred.run([X[:1]])                       # compile the bucket-1 shape
    lat, outs = [], []
    t0 = time.perf_counter()
    for i in range(requests):
        row = X[i % len(X):i % len(X) + 1]
        t1 = time.perf_counter()
        out, = pred.run([row])
        lat.append(time.perf_counter() - t1)
        if i < len(X):
            outs.append(out)
    wall = time.perf_counter() - t0
    return {
        'bench': 'serving_serial_baseline',
        'requests': requests,
        'throughput_req_s': round(requests / wall, 1),
        'p50_ms': _pctl(lat, 50), 'p99_ms': _pctl(lat, 99),
    }, outs


def _hist_stats(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0, 0
    s = sum(x['sum'] for x in d['samples'])
    c = sum(x['count'] for x in d['samples'])
    return s, c


def measure_batcher(model_dir, X, refs, clients, requests_per_client,
                    max_batch_size, batch_timeout_ms):
    """Closed-loop clients through the micro-batcher; parity-checked against
    the serial baseline outputs."""
    from paddle_tpu import serving
    engine = serving.InferenceEngine(model_dir, max_batch_size=max_batch_size)
    engine.warmup()
    rows0, nb0 = _hist_stats('serving_batch_rows')
    waste0, nw0 = _hist_stats('serving_padding_waste_ratio')
    lat, mismatches = [], [0]
    lat_lock = threading.Lock()

    def client(cid):
        my_lat = []
        bad = 0
        for i in range(requests_per_client):
            ridx = (cid * requests_per_client + i) % len(X)
            t1 = time.perf_counter()
            out, = batcher.predict({'x': X[ridx:ridx + 1]})
            my_lat.append(time.perf_counter() - t1)
            if not np.array_equal(out, refs[ridx]):
                bad += 1
        with lat_lock:
            lat.extend(my_lat)
            mismatches[0] += bad

    with serving.MicroBatcher(engine, batch_timeout_ms=batch_timeout_ms,
                              queue_depth=4 * clients) as batcher:
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0

    total = clients * requests_per_client
    rows1, nb1 = _hist_stats('serving_batch_rows')
    waste1, nw1 = _hist_stats('serving_padding_waste_ratio')
    batches = max(nb1 - nb0, 1)
    return {
        'bench': 'serving_batcher',
        'clients': clients, 'requests': total,
        'max_batch_size': max_batch_size,
        'batch_timeout_ms': batch_timeout_ms,
        'throughput_req_s': round(total / wall, 1),
        'p50_ms': _pctl(lat, 50), 'p99_ms': _pctl(lat, 99),
        'batches': batches,
        'mean_batch_rows': round((rows1 - rows0) / batches, 2),
        'mean_padding_waste': round(
            (waste1 - waste0) / max(nw1 - nw0, 1), 3),
        'bitwise_equal': mismatches[0] == 0,
    }


class _SlowEngine:
    """Engine proxy whose device call takes a fixed wall time — makes the
    overload section deterministic (a fast engine drains any burst)."""

    def __init__(self, engine, delay_s):
        self._engine = engine
        self._delay = delay_s
        self.max_batch_size = engine.max_batch_size

    def validate(self, inputs):
        return self._engine.validate(inputs)

    def run_batch(self, feed, nrows=None):
        time.sleep(self._delay)
        return self._engine.run_batch(feed, nrows)


def measure_open_loop(model_dir, X, rate_rps, requests, max_batch_size=16,
                      batch_timeout_ms=2, timeout_ms=None):
    """Open-loop Poisson load (the tail-latency-honest model the ROADMAP
    asked for): arrivals follow a seeded exponential inter-arrival process
    at ``rate_rps`` REGARDLESS of completions, so queueing delay shows up
    in the latency distribution instead of throttling the offered load
    (closed-loop clients hide it — 'coordinated omission'). Latency is
    stamped at completion via PredictionFuture.add_done_callback, not when
    the caller polls. Reports offered vs achieved rate, p50/p99, and typed
    rejections."""
    import random
    from paddle_tpu import serving
    engine = serving.InferenceEngine(model_dir, max_batch_size=max_batch_size)
    engine.warmup()
    rng = random.Random(0)
    lat, lat_lock = [], threading.Lock()
    rejected = [0]
    failed = [0]
    pending = []

    def on_done(submit_t, fut):
        dt = time.perf_counter() - submit_t
        with lat_lock:
            lat.append(dt)

    with serving.MicroBatcher(engine, batch_timeout_ms=batch_timeout_ms,
                              queue_depth=max(2 * max_batch_size, 32)) \
            as batcher:
        t0 = time.perf_counter()
        next_arrival = t0
        for i in range(requests):
            now = time.perf_counter()
            if next_arrival > now:
                time.sleep(next_arrival - now)
            ridx = i % len(X)
            submit_t = time.perf_counter()
            try:
                fut = batcher.submit({'x': X[ridx:ridx + 1]}, timeout_ms)
                fut.add_done_callback(
                    lambda f, s=submit_t: on_done(s, f))
                pending.append(fut)
            except serving.Overloaded:
                rejected[0] += 1
            except serving.ServingError:
                failed[0] += 1
            next_arrival += rng.expovariate(rate_rps)
        for f in pending:
            try:
                f.result(timeout=60)
            except serving.ServingError:
                failed[0] += 1
        wall = time.perf_counter() - t0
    answered = len(lat) - failed[0]
    return {
        'bench': 'serving_open_loop',
        'offered_rate_req_s': rate_rps,
        'requests': requests,
        'achieved_req_s': round(answered / wall, 1),
        'answered': answered,
        'rejected_overload': rejected[0],
        'failed': failed[0],
        'p50_ms': _pctl(lat, 50) if lat else None,
        'p99_ms': _pctl(lat, 99) if lat else None,
    }


def measure_overload(model_dir, X, queue_depth, burst):
    """Burst > queue_depth against a slow engine: typed rejections, no
    hangs, admitted requests all answered."""
    from paddle_tpu import serving
    engine = serving.InferenceEngine(model_dir, max_batch_size=4)
    engine.warmup()
    slow = _SlowEngine(engine, delay_s=0.05)
    rejected, futures = 0, []
    with serving.MicroBatcher(slow, batch_timeout_ms=1,
                              queue_depth=queue_depth) as batcher:
        for i in range(burst):
            try:
                futures.append(batcher.submit({'x': X[i % len(X):
                                                      i % len(X) + 1]}))
            except serving.Overloaded:
                rejected += 1
        answered = 0
        for f in futures:
            f.result(timeout=30)
            answered += 1
    from paddle_tpu.observability import registry
    prom = registry.prometheus_text()
    return {
        'bench': 'serving_overload',
        'burst': burst, 'queue_depth': queue_depth,
        'rejected': rejected, 'answered': answered,
        'rejections_in_prometheus':
            'paddle_tpu_serving_requests_rejected_overload' in prom,
    }


def measure_all(smoke=False, model_dir=None):
    """All three sections; returns {'serial': ..., 'batcher': ...,
    'overload': ...}. ``smoke``: CI sizes (seconds, not minutes)."""
    tmp = None
    if model_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix='bench_serving_')
        model_dir = build_model(os.path.join(tmp.name, 'model'))
    # closed-loop sizing: clients must exceed 2× the row budget or batches
    # never fill and every round waits out the whole batch window (the
    # measured-throughput cliff documented in docs/SERVING.md)
    clients = 48 if smoke else 64
    per_client = 25 if smoke else 100
    max_batch = 16
    rng = np.random.RandomState(0)
    X = rng.randn(64, FEATURES).astype(np.float32)
    try:
        serial, refs = measure_serial(
            model_dir, X, requests=200 if smoke else 1000)
        batcher = measure_batcher(model_dir, X, refs, clients, per_client,
                                  max_batch_size=max_batch,
                                  batch_timeout_ms=2)
        batcher['speedup_vs_serial'] = round(
            batcher['throughput_req_s'] / serial['throughput_req_s'], 2)
        # open-loop Poisson arrivals at ~3x the serial rate: comfortably
        # inside the batcher's capacity (~5x serial) so the p99 reflects
        # batching delay, not saturation collapse
        open_loop = measure_open_loop(
            model_dir, X, rate_rps=3.0 * serial['throughput_req_s'],
            requests=300 if smoke else 2000, max_batch_size=max_batch)
        overload = measure_overload(model_dir, X, queue_depth=8,
                                    burst=64 if smoke else 256)
    finally:
        if tmp is not None:
            tmp.cleanup()
    return {'serial': serial, 'batcher': batcher, 'open_loop': open_loop,
            'overload': overload}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true',
                    help='CI sizes: fewer clients/requests')
    ap.add_argument('--model-dir', default=None,
                    help='serve an existing saved model instead of the '
                         'built-in MLP')
    args = ap.parse_args()
    results = measure_all(smoke=args.smoke, model_dir=args.model_dir)
    for section in results.values():
        print(json.dumps(section), flush=True)
    ok = (results['batcher']['bitwise_equal']
          and results['overload']['rejected'] > 0
          and results['overload']['answered'] > 0)
    sys.exit(0 if ok else 1)


if __name__ == '__main__':
    main()
