"""Memory-planner bench (PERF.md §20).

Two sections, one JSON line each:

- ``plan_latency`` — ``analysis.plan_program`` wall time on the
  multi-param Adam MLP recipe vs the cold Executor lower+compile it
  informs. Acceptance (asserted in tier-1 via test_bench_plan.py at
  smoke sizes): plan ≤ 1% of the cold lower+compile — the planner is
  zero-tracing by construction, this prices the claim.
- ``plan_remat`` — the memory-vs-throughput tradeoff on an
  activation-heavy MLP: predicted peak without remat, the simulated
  ``PADDLE_TPU_HBM_BUDGET_MB`` the unplanned program exceeds, the
  post-``auto_remat`` predicted peak (must fit), steps/s with and
  without remat (recompute costs one extra forward pass), and bitwise
  loss parity remat-on vs remat-off.

  JAX_PLATFORMS=cpu python tools/bench_plan.py [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _fresh_names():
    import paddle_tpu as fluid
    from paddle_tpu.core import unique_name
    unique_name.generator = unique_name.UniqueNameGenerator()
    fluid.framework.manual_seed(0)


def measure_plan_latency(smoke=False, iters=7):
    """plan_program wall time vs one real cold Executor compile."""
    os.environ['PADDLE_TPU_COMPILE_CACHE'] = '0'   # price the real compile
    sys.path.insert(0, os.path.join(_REPO, 'tools'))
    from bench_passes import build_mlp_adam
    import paddle_tpu as fluid
    from paddle_tpu.analysis.plan import plan_program

    _fresh_names()
    main, startup, make_feed, fetch = build_mlp_adam(smoke=smoke)
    feed = make_feed()
    shapes = {k: v.shape for k, v in feed.items()}

    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        plan = plan_program(main, fetch_names=[fetch.name],
                            feed_names=sorted(feed), feed_shapes=shapes)
        ts.append(time.perf_counter() - t0)
    plan_s = statistics.median(ts)

    exe = fluid.Executor()
    exe.run(startup)
    t0 = time.perf_counter()
    exe.run(main, feed=feed, fetch_list=[fetch])       # cold: compiles
    cold_s = time.perf_counter() - t0
    return {'bench': 'plan_latency',
            'ops': main.num_ops(),
            'plan_ms': round(plan_s * 1e3, 3),
            'cold_compile_s': round(cold_s, 4),
            'plan_frac_of_compile': round(plan_s / cold_s, 5),
            'predicted_peak_mib': round(plan.peak_bytes / 2**20, 3)}


def _build_remat_model(smoke):
    """Activation-heavy MLP under SGD: wide batch × depth so the
    residuals-into-backward term dominates state — the workload shape
    remat exists for."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    width, depth, bs = (32, 6, 64) if smoke else (256, 8, 512)
    _fresh_names()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [width], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = x
        for _ in range(depth):
            h = L.fc(h, size=width, act='relu')
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(bs, width).astype(np.float32),
            'y': rng.randn(bs, 1).astype(np.float32)}
    return main, startup, feed, loss


def measure_remat_tradeoff(smoke=False, steps=6):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.analysis.plan import plan_program, select_checkpoints

    def run(budget_mb):
        if budget_mb is None:
            os.environ.pop('PADDLE_TPU_HBM_BUDGET_MB', None)
        else:
            os.environ['PADDLE_TPU_HBM_BUDGET_MB'] = repr(budget_mb)
        try:
            main, startup, feed, loss = _build_remat_model(smoke)
            exe = fluid.Executor()
            exe.run(startup)
            losses = [exe.run(main, feed=feed, fetch_list=[loss])[0]]
            t0 = time.perf_counter()
            for _ in range(steps):
                losses.append(exe.run(main, feed=feed,
                                      fetch_list=[loss])[0])
            dt = time.perf_counter() - t0
            return losses, steps / dt
        finally:
            os.environ.pop('PADDLE_TPU_HBM_BUDGET_MB', None)

    main, _startup, feed, loss = _build_remat_model(smoke)
    shapes = {k: v.shape for k, v in feed.items()}
    kw = dict(fetch_names=[loss.name], feed_names=sorted(feed),
              feed_shapes=shapes)
    no_remat = plan_program(main, **kw)
    # best-achievable peak under an impossible budget → the remat floor;
    # the simulated budget sits halfway between floor and no-remat peak,
    # so the unplanned program EXCEEDS it and auto_remat can FIT it
    names, floor_peak = select_checkpoints(main, 0, **kw)
    budget = (floor_peak + no_remat.peak_bytes) // 2
    budget_mb = budget / float(1 << 20)
    chosen, remat_peak = select_checkpoints(main, budget, **kw)

    base_losses, base_sps = run(None)
    remat_losses, remat_sps = run(budget_mb)
    bitwise = all(np.array_equal(a, b)
                  for a, b in zip(base_losses, remat_losses))
    return {'bench': 'plan_remat',
            'no_remat_peak_mib': round(no_remat.peak_bytes / 2**20, 3),
            'budget_mib': round(budget_mb, 3),
            'remat_peak_mib': round(remat_peak / 2**20, 3),
            'checkpoints': len(chosen),
            'fits_budget': remat_peak <= budget,
            'exceeds_without_remat': no_remat.peak_bytes > budget,
            'steps_per_s_base': round(base_sps, 2),
            'steps_per_s_remat': round(remat_sps, 2),
            'remat_steps_ratio': round(remat_sps / base_sps, 3)
            if base_sps else None,
            'bitwise_identical': bool(bitwise)}


def measure_all(smoke=False, iters=7):
    prior = os.environ.get('PADDLE_TPU_HBM_BUDGET_MB')
    try:
        lat = measure_plan_latency(smoke=smoke, iters=iters)
        remat = measure_remat_tradeoff(smoke=smoke)
    finally:
        if prior is None:
            os.environ.pop('PADDLE_TPU_HBM_BUDGET_MB', None)
        else:
            os.environ['PADDLE_TPU_HBM_BUDGET_MB'] = prior
    print(json.dumps(lat))
    print(json.dumps(remat))
    return {'plan_latency': lat, 'plan_remat': remat}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true')
    ap.add_argument('--iters', type=int, default=7)
    args = ap.parse_args()
    r = measure_all(smoke=args.smoke, iters=args.iters)
    frac = r['plan_latency']['plan_frac_of_compile']
    ok = (frac <= 0.01 and r['plan_remat']['fits_budget']
          and r['plan_remat']['exceeds_without_remat']
          and r['plan_remat']['bitwise_identical'])
    print(json.dumps({'bench': 'plan_acceptance',
                      'plan_frac_of_compile': frac,
                      'threshold': 0.01,
                      'remat_fits': r['plan_remat']['fits_budget'],
                      'bitwise': r['plan_remat']['bitwise_identical'],
                      'ok': ok}))
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
