"""Dygraph dispatch-overhead microbench (PERF.md §9).

Times one training step of a ResNet bottleneck block and a BERT transformer
layer three ways:

1. **eager, kernel cache off** — the pre-overhaul tape: every op call
   re-traces jax.vjp through its functional (the Python-dispatch slow path
   the reference pays 1,500+ LoC of C++ Tracer to avoid);
2. **eager, kernel cache on** — the per-op jitted-kernel cache
   (dygraph/tape.py): op dispatch is an LRU hit onto a compiled kernel;
3. **fused TrainStep** — forward+vjp+update as ONE donated XLA program
   (the production path; the remaining eager/fused gap is the cost of
   op-granular dispatch itself).

Slope-method timing per PERF.md §3 (marginal time between an N-iter and a
3N-iter run cancels fixed costs). One JSON line per measurement. Runs on any
backend; sized for CPU by default:

  JAX_PLATFORMS=cpu python tools/bench_dispatch.py [--iters 5] [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/bench_dispatch.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _slope(fn, iters):
    """Marginal seconds/step between iters and 3*iters chained runs."""
    import jax

    def run(n):
        t0 = time.perf_counter()
        r = None
        for _ in range(n):
            r = fn()
        jax.block_until_ready(r.value if hasattr(r, 'value') else r)
        return time.perf_counter() - t0

    run(1)   # warmup: compiles + populates kernel/step caches
    t1, t3 = run(iters), run(3 * iters)
    return max((t3 - t1) / (2 * iters), 1e-9)


def _mean_sq(out):
    from paddle_tpu.dygraph.tape import dispatch_op
    return dispatch_op('reduce_mean', {'x': out * out}, {})


def _eager_step_fn(make_model, make_inputs):
    """Eager tape training step: forward, backward() tape walk, fused
    optimizer update — op-granular dispatch throughout."""
    import paddle_tpu as fluid
    model = make_model()
    opt = fluid.optimizer.SGD(0.01, parameter_list=model.parameters())
    inputs = make_inputs()

    def step():
        loss = _mean_sq(model(*inputs))
        loss.backward()
        opt.minimize(loss)
        opt.clear_gradients()
        return loss

    return step


def _fused_step_fn(make_model, make_inputs):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.dygraph.jit import TrainStep
    model = make_model()
    opt = fluid.optimizer.SGD(0.01, parameter_list=model.parameters())

    def loss_fn(m, *batch):
        return _mean_sq(m(*batch))

    step = TrainStep(model, loss_fn, opt)
    batch = [np.asarray(t.value) for t in make_inputs()]
    return lambda: step(*batch)


def bench_block(name, make_model, make_inputs, iters):
    """→ dict with the three slope timings (ms) + derived ratios."""
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.tape import kernel_cache

    with dygraph.guard():
        with dygraph.eager_kernel_cache_guard(False):
            t_uncached = _slope(_eager_step_fn(make_model, make_inputs),
                                iters)
        with dygraph.eager_kernel_cache_guard(True):
            kernel_cache.clear()
            t_cached = _slope(_eager_step_fn(make_model, make_inputs), iters)
            stats = kernel_cache.stats()
        t_fused = _slope(_fused_step_fn(make_model, make_inputs), iters)

    return {
        'bench': f'dispatch_{name}',
        'eager_uncached_ms': round(t_uncached * 1e3, 3),
        'eager_cached_ms': round(t_cached * 1e3, 3),
        'train_step_ms': round(t_fused * 1e3, 3),
        # ≥ 2x on the ResNet block is the overhaul's acceptance bar
        'cache_speedup': round(t_uncached / t_cached, 2),
        'eager_cached_vs_fused': round(t_cached / t_fused, 2),
        'cache_hits': stats['hits'], 'cache_misses': stats['misses'],
    }


def _resnet_block(smoke):
    import numpy as np
    from paddle_tpu.models.resnet import BottleneckBlock
    from paddle_tpu import dygraph
    bs, hw = (2, 8) if smoke else (4, 16)
    rng = np.random.RandomState(0)

    def make_model():
        return BottleneckBlock(64, 16, stride=1, shortcut=True)

    def make_inputs():
        return [dygraph.to_variable(
            rng.randn(bs, 64, hw, hw).astype(np.float32))]

    return make_model, make_inputs


def _bert_layer(smoke):
    import numpy as np
    from paddle_tpu.models.bert import BertConfig, TransformerLayer
    from paddle_tpu import dygraph
    bs, seq = (1, 8) if smoke else (2, 16)
    cfg = BertConfig(hidden_size=64, num_attention_heads=2,
                     intermediate_size=128, hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    rng = np.random.RandomState(0)

    def make_model():
        return TransformerLayer(cfg)

    def make_inputs():
        return [dygraph.to_variable(
            rng.randn(bs, seq, 64).astype(np.float32))]

    return make_model, make_inputs


def measure_all(iters=5, smoke=False):
    """Both blocks; returns {'resnet_block': {...}, 'bert_layer': {...}}."""
    out = {}
    for name, builder in [('resnet_block', _resnet_block),
                          ('bert_layer', _bert_layer)]:
        make_model, make_inputs = builder(smoke)
        out[name] = bench_block(name, make_model, make_inputs, iters)
    return out


def measure_telemetry_overhead(iters=4, smoke=True):
    """Eager cached-step slope time with telemetry off vs ON (the resnet
    bottleneck block). The ≤3% telemetry-DISABLED budget is enforced
    structurally (the disabled dispatch path does no telemetry work — see
    tests/framework/test_observability.py); this records the measured cost
    of actually enabling it, for the bench sidecar."""
    from paddle_tpu import dygraph, observability as obs
    from paddle_tpu.dygraph.tape import kernel_cache
    make_model, make_inputs = _resnet_block(smoke)
    with dygraph.guard():
        with obs.telemetry_guard(False):
            kernel_cache.clear()
            t_off = _slope(_eager_step_fn(make_model, make_inputs), iters)
        with obs.telemetry_guard(True):
            kernel_cache.clear()
            t_on = _slope(_eager_step_fn(make_model, make_inputs), iters)
    return {'bench': 'telemetry_overhead',
            'eager_cached_ms_telemetry_off': round(t_off * 1e3, 3),
            'eager_cached_ms_telemetry_on': round(t_on * 1e3, 3),
            'on_over_off': round(t_on / t_off, 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--iters', type=int, default=5,
                    help='slope base iteration count (runs N then 3N)')
    ap.add_argument('--smoke', action='store_true',
                    help='tiny shapes / CI smoke sizes')
    ap.add_argument('--telemetry-ab', action='store_true',
                    help='also measure the eager step with telemetry '
                         'enabled vs disabled')
    args = ap.parse_args()
    for res in measure_all(iters=args.iters, smoke=args.smoke).values():
        print(json.dumps(res), flush=True)
    if args.telemetry_ab:
        print(json.dumps(measure_telemetry_overhead(
            iters=args.iters, smoke=args.smoke)), flush=True)


if __name__ == '__main__':
    main()
