"""Serving-tier benchmark (PERF.md §19): router scaling, prefix-cache
wins, disaggregated handoff, and a failover drill. One JSON line per
section.

1. ``serving_tier_scaling`` — open-loop Poisson arrivals (completion-
   stamped p50/p99, the tail-latency-honest load model from
   bench_serving) through the HTTP router against 1 replica, then the
   same arrival schedule against 2. On a 1-core CI host the replicas
   time-share the CPU, so the 2-replica p99 ratio measures ROUTING
   OVERHEAD (≈1.2× here), not scaling; on real hardware each replica owns
   its device and the ratio becomes tail-latency relief. Correctness
   (all completed, bitwise) gates; latency is reported.
2. ``serving_tier_prefix_cache`` — the motivating workload: one shared
   system prompt + per-user suffixes, cache off vs on. Reports hit rate,
   prefill-compute-saved (tokens served from cached blocks), wall
   speedup, and bitwise parity. The acceptance demands hit rate AND
   tokens-saved > 0 on this workload — the always-on metrics prove it.
3. ``serving_tier_disagg`` — disaggregated prefill/decode vs colocated:
   bitwise parity, handoff count/bytes, and decode-step stall relief
   (max inter-token gap on a stream active while long prompts prefill).
4. ``serving_tier_failover`` — drill: one replica dies abruptly mid-run;
   every non-in-flight request completes through the survivor
   (the kill -9 subprocess version lives in
   tests/framework/test_router_failover.py).

Runs on any backend; CPU is the honest configuration (scheduling + routing
are the quantities under test):

  JAX_PLATFORMS=cpu python tools/bench_router.py [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

# runnable as `python tools/bench_router.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


def _percentile(xs, q):
    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs else 0.0


class _Replica:
    """In-process replica stack + HTTP listener."""

    def __init__(self, model, lock, rid, **kw):
        from paddle_tpu.serving import ServingServer
        from paddle_tpu.serving.tier.replica import build_replica_stack
        self.engine, self.scheduler, self.worker = build_replica_stack(
            model=model, model_lock=lock, replica_id=rid, slots=4,
            queue_depth=256, **kw)
        self.engine.warmup()
        self.server = ServingServer(None, port=0,
                                    generator=self.scheduler).start()
        self.url = f'http://127.0.0.1:{self.server.port}'

    def shutdown(self, drain=True):
        self.scheduler.close(drain=drain, timeout=30)
        self.server.shutdown(drain=drain)
        if self.worker is not None:
            self.worker.close()


def _poisson_run(router, work, rate_per_s, refs, seed=0):
    """Open-loop arrivals: each request fires at its scheduled time on its
    own thread; latency is submit -> final event (completion-stamped)."""
    rng = np.random.RandomState(seed)
    gaps = rng.exponential(1.0 / rate_per_s, len(work))
    lat = [None] * len(work)
    ok = [False] * len(work)

    def fire(i, prompt, max_new):
        t0 = time.perf_counter()
        try:
            fin = router.generate(prompt, max_new_tokens=max_new, timeout=120)
            ok[i] = fin['tokens'] == refs[i]
        except Exception:
            ok[i] = False
        lat[i] = time.perf_counter() - t0

    threads = []
    t_next = time.perf_counter()
    for i, (prompt, max_new) in enumerate(work):
        t_next += gaps[i]
        delay = t_next - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=fire, args=(i, prompt, max_new))
        th.start()
        threads.append(th)
    for th in threads:
        th.join(180)
    done = [l for l in lat if l is not None]
    return {
        'completed': sum(1 for l in lat if l is not None),
        'bitwise_equal': all(ok),
        'p50_ms': round(_percentile(done, 50) * 1e3, 1),
        'p99_ms': round(_percentile(done, 99) * 1e3, 1),
    }


def measure_scaling(model, lock, work, refs, smoke):
    from paddle_tpu.serving.tier import Router
    # calibrate the arrival rate off one sequential request
    calib = _Replica(model, lock, 'calib')
    with Router([calib.url], health_poll_s=5) as router:
        t0 = time.perf_counter()
        for p, m in work[:3]:
            router.generate(p, max_new_tokens=m, timeout=120)
        service_s = (time.perf_counter() - t0) / 3
    rate = 0.8 / max(service_s, 1e-3)        # ~80% of 1-replica capacity

    def run(n_replicas):
        reps = [calib] if n_replicas == 1 else \
            [calib, _Replica(model, lock, 'scale-2')]
        with Router([r.url for r in reps], health_poll_s=5) as router:
            out = _poisson_run(router, work, rate, refs)
        for r in reps[1:]:
            r.shutdown()
        out.update(replicas=n_replicas, arrival_rate_per_s=round(rate, 2))
        return out

    one = run(1)
    two = run(2)
    calib.shutdown()
    return {'bench': 'serving_tier_scaling', 'requests': len(work),
            'one_replica': one, 'two_replicas': two,
            # on a 1-core host in-process replicas time-share the CPU (and
            # the model lock), so this ratio measures ROUTING OVERHEAD, not
            # scaling — on N devices each replica owns its accelerator and
            # the ratio becomes the tail-latency relief (PERF.md §19, the
            # same honesty note as bench_fleet's weak scaling)
            'p99_ratio_two_vs_one': round(
                two['p99_ms'] / max(one['p99_ms'], 1e-9), 2),
            'cpu_count': os.cpu_count()}


def build_shared_prompt_work(requests, seed=0):
    """The prefix-cache workload: ONE 12-token system prompt shared by all
    requests, 1-3 token user suffixes — the shape of real assistant
    traffic, and the redundant-prefill worst case."""
    rng = np.random.RandomState(seed)
    system = [int(t) for t in rng.randint(3, 120, 12)]
    work = []
    for _ in range(requests):
        suffix = [int(t) for t in rng.randint(3, 120, rng.randint(1, 4))]
        work.append((system + suffix, int(rng.randint(2, 6))))
    return work


def measure_prefix_cache(model, work, refs):
    from paddle_tpu.serving.decode import DecodeEngine, DecodeScheduler

    def run(enabled):
        eng = DecodeEngine(model, slots=4, block_size=4, max_blocks=256,
                           max_prompt_len=16, max_new_tokens_cap=8,
                           prefix_cache=enabled)
        eng.warmup()
        h0, m0, s0 = (_counter('prefix_cache_hits'),
                      _counter('prefix_cache_misses'),
                      _counter('prefix_cache_tokens_saved'))
        with DecodeScheduler(eng, queue_depth=len(work) + 1) as sched:
            t0 = time.perf_counter()
            streams = [sched.submit(p, max_new_tokens=m) for p, m in work]
            outs = [s.result(300) for s in streams]
            wall = time.perf_counter() - t0
        hits = _counter('prefix_cache_hits') - h0
        misses = _counter('prefix_cache_misses') - m0
        return {
            'wall_s': round(wall, 3),
            'bitwise_equal': outs == refs,
            'hit_rate': round(hits / max(hits + misses, 1), 3),
            'prefill_tokens_saved': int(
                _counter('prefix_cache_tokens_saved') - s0),
        }

    off = run(False)
    on = run(True)
    return {'bench': 'serving_tier_prefix_cache', 'requests': len(work),
            'cache_off': off, 'cache_on': on,
            'speedup': round(off['wall_s'] / max(on['wall_s'], 1e-9), 2)}


def measure_disagg(model, work, refs):
    from paddle_tpu.serving.tier.replica import build_replica_stack
    h0, b0 = _counter('disagg_handoffs'), _counter('disagg_kv_bytes')
    eng, sched, worker = build_replica_stack(
        model=model, disagg=True, slots=4, queue_depth=len(work) + 1,
        max_new_tokens_cap=8)
    try:
        streams = [sched.submit(p, max_new_tokens=m) for p, m in work]
        outs = [s.result(300) for s in streams]
    finally:
        sched.close()
        worker.close()
    return {'bench': 'serving_tier_disagg', 'requests': len(work),
            'bitwise_equal': outs == refs,
            'handoffs': int(_counter('disagg_handoffs') - h0),
            'kv_bytes': int(_counter('disagg_kv_bytes') - b0)}


def measure_failover(model, lock, work, refs):
    """Abruptly stop one of two replicas mid-run; every request completes
    (in-flight ones on the dying replica transparently reroute when
    nothing streamed yet — the first-event rule)."""
    from paddle_tpu.serving.tier import Router
    reps = [_Replica(model, lock, f'fo-{i}') for i in range(2)]
    results, dropped = [None] * len(work), []
    r0 = _counter('router_requests_rerouted')
    with Router([r.url for r in reps], health_poll_s=0.3) as router:
        def fire(i, prompt, max_new):
            try:
                # non-streamed: idempotent retry makes even in-flight
                # requests on the dying replica survivable — zero drops
                fin = router.generate_nonstream(prompt,
                                                max_new_tokens=max_new,
                                                timeout=120)
                results[i] = fin['tokens'] == refs[i]
            except Exception as e:
                dropped.append((i, str(e)))

        threads = [threading.Thread(target=fire, args=(i, p, m))
                   for i, (p, m) in enumerate(work)]
        for t in threads[:len(threads) // 2]:
            t.start()
        reps[0].shutdown(drain=False)          # dies abruptly mid-run
        for t in threads[len(threads) // 2:]:
            t.start()
        for t in threads:
            t.join(180)
    reps[1].shutdown()
    # in-flight streams on the dying replica legitimately die; everything
    # else must complete — with stream=False generates, the router retries
    # all of them (nothing was streamed), so ALL must complete
    return {'bench': 'serving_tier_failover', 'requests': len(work),
            'completed': sum(r is not None for r in results),
            'bitwise_equal': all(r for r in results if r is not None),
            'dropped': len(dropped),
            'rerouted': int(_counter('router_requests_rerouted') - r0)}


def measure_trace_overhead(model, lock, work, refs):
    """Tracing A/B (PERF.md §22): the SAME serial request sweep through
    the router with ``PADDLE_TPU_TRACE_SAMPLE=0`` (production default)
    vs ``=1`` plus span records on. The untraced path must do zero span
    work — asserted structurally (``spans_off == 0``) — so the measured
    off-vs-on p50 gap is the full cost of tracing a request, a hard
    upper bound on what the disabled path can cost."""
    import tempfile
    from paddle_tpu.observability import distributed as _dobs
    from paddle_tpu.observability.trace_context import (ENV_TRACE_DIR,
                                                        ENV_TRACE_SAMPLE)
    from paddle_tpu.serving.tier import Router
    rep = _Replica(model, lock, 'trace-ab')
    saved = {k: os.environ.get(k)
             for k in (ENV_TRACE_SAMPLE, ENV_TRACE_DIR)}
    p50, spans, ok = {}, {}, {}
    try:
        with tempfile.TemporaryDirectory() as td, \
                Router([rep.url], health_poll_s=0.3) as router:
            for mode, env in (('off', {ENV_TRACE_SAMPLE: '0'}),
                              ('on', {ENV_TRACE_SAMPLE: '1',
                                      ENV_TRACE_DIR: td})):
                os.environ.update(env)
                for prompt, max_new in work[:2]:     # warm the HTTP path
                    router.generate(prompt, max_new_tokens=max_new,
                                    timeout=120)
                s0 = _counter('trace_spans_recorded')
                lat, good = [], True
                for i, (prompt, max_new) in enumerate(work):
                    t0 = time.perf_counter()
                    fin = router.generate(prompt, max_new_tokens=max_new,
                                          timeout=120)
                    lat.append(time.perf_counter() - t0)
                    good = good and fin['tokens'] == refs[i]
                p50[mode] = _percentile(lat, 50)
                spans[mode] = int(_counter('trace_spans_recorded') - s0)
                ok[mode] = good
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _dobs.reset_distributed()     # drop the recorder bound to td
        rep.shutdown()
    return {'bench': 'serving_tier_trace_overhead',
            'requests': len(work),
            'p50_off_ms': round(p50['off'] * 1e3, 2),
            'p50_on_ms': round(p50['on'] * 1e3, 2),
            'on_over_off': round(p50['on'] / max(p50['off'], 1e-9), 3),
            'spans_off': spans['off'], 'spans_on': spans['on'],
            'bitwise_equal': ok['off'] and ok['on']}


def measure_all(smoke=False, seed=0):
    import threading as _t
    from paddle_tpu.dygraph import guard
    from paddle_tpu.models.causal_lm import greedy_generate
    from paddle_tpu.serving.tier.replica import build_tiny_lm
    requests = 12 if smoke else 32
    with guard():
        model = build_tiny_lm()
        lock = _t.RLock()
        pad = -(-(16 + 16) // 4) * 4           # replica-geometry padded ctx
        work = build_shared_prompt_work(requests, seed)
        refs = [greedy_generate(model, p, m, pad_len=pad) for p, m in work]
        # scaling + failover use short fixed work (HTTP-path wall time)
        short_work = work[:max(requests // 2, 6)]
        short_refs = refs[:len(short_work)]
        scaling = measure_scaling(model, lock, short_work, short_refs, smoke)
        cache = measure_prefix_cache(model, work, refs)
        disagg = measure_disagg(model, work[:requests // 2],
                                refs[:requests // 2])
        failover = measure_failover(model, lock, short_work, short_refs)
    return {'scaling': scaling, 'prefix_cache': cache, 'disagg': disagg,
            'failover': failover}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true',
                    help='CI sizes: fewer/shorter generations')
    ap.add_argument('--trace-ab', action='store_true',
                    help='also measure request p50 with trace sampling '
                         'off vs on (PERF.md §22)')
    args = ap.parse_args()
    if args.trace_ab:
        import threading as _t
        from paddle_tpu.dygraph import guard
        from paddle_tpu.models.causal_lm import greedy_generate
        from paddle_tpu.serving.tier.replica import build_tiny_lm
        n = 8 if args.smoke else 24
        with guard():
            model = build_tiny_lm()
            work = build_shared_prompt_work(n)
            pad = -(-(16 + 16) // 4) * 4
            refs = [greedy_generate(model, p, m, pad_len=pad)
                    for p, m in work]
            res = measure_trace_overhead(model, _t.RLock(), work, refs)
        print(json.dumps(res), flush=True)
        sys.exit(0 if (res['bitwise_equal'] and res['spans_off'] == 0
                       and res['spans_on'] > 0) else 1)
    results = measure_all(smoke=args.smoke)
    for section in results.values():
        print(json.dumps(section), flush=True)
    # gate on correctness and structure; wall-clock ratios live in PERF.md
    # §19 and stay out of the exit code so a loaded CI box cannot flake
    ok = (results['scaling']['one_replica']['bitwise_equal']
          and results['scaling']['two_replicas']['bitwise_equal']
          and results['prefix_cache']['cache_on']['bitwise_equal']
          and results['prefix_cache']['cache_off']['bitwise_equal']
          and results['prefix_cache']['cache_on']['hit_rate'] > 0
          and results['prefix_cache']['cache_on']['prefill_tokens_saved'] > 0
          and results['disagg']['bitwise_equal']
          and results['disagg']['handoffs'] > 0
          and results['failover']['dropped'] == 0
          and results['failover']['completed'] == results['failover']['requests']
          and results['failover']['bitwise_equal'])
    sys.exit(0 if ok else 1)


if __name__ == '__main__':
    main()
