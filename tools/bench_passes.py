"""IR pass-pipeline microbench (PERF.md §10).

For three static-graph training programs — a multi-param Adam MLP, a
ResNet bottleneck block (conv+BN+momentum), and a BERT-style transformer
layer (attention+layer_norm+adam) — measures, pass pipeline OFF vs ON
(with the BuildStrategy fuse knobs live):

- global-block op count the tracer walks,
- total jaxpr equation count of the lowered step (nested jaxprs included),
- trace+lower wall seconds (pipeline run + `_lower` + jax.jit().lower(),
  i.e. everything before XLA's backend compile),
- `executor_compile_seconds` through the real Executor path under
  telemetry, for the end-to-end number PR 2's metric records.

One JSON line per model. Runs on any backend; sized for CPU:

  JAX_PLATFORMS=cpu python tools/bench_passes.py [--iters 3] [--smoke]

The multi-param Adam model is the acceptance bench: with
`fuse_all_optimizer_ops=True` the eqn count must drop ≥30% (asserted in
tier-1 by tests/framework/test_bench_passes.py at smoke sizes).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/bench_passes.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# model builders (shared with tests/framework/test_ir_passes.py)
# ---------------------------------------------------------------------------

def build_mlp_adam(smoke=False, layers_n=None):
    """Deep MLP under Adam: #params scales with depth, so the per-param
    update-op tail dominates the traced program — the fuse_all_optimizer_ops
    showcase. Returns (main, startup, make_feed, fetch_var)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    # "multi-param" must mean it even at smoke sizes: below ~12 layers the
    # update ops are too small a fraction of the program for the bundle
    # rewrite to clear its own reshape/slice overhead
    width = 16 if smoke else 64
    depth = layers_n if layers_n is not None else (16 if smoke else 24)
    bs = 4 if smoke else 32
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [width], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = x
        for _ in range(depth):
            h = L.fc(h, size=width, act='relu')
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    rng = np.random.RandomState(0)

    def make_feed():
        return {'x': rng.randn(bs, width).astype(np.float32),
                'y': rng.randn(bs, 1).astype(np.float32)}

    return main, startup, make_feed, loss


def build_resnet_block(smoke=False):
    """Static ResNet bottleneck (1×1 → 3×3 → 1×1 convs, BN, relu,
    shortcut) under Momentum — conv/BN trace cost + fused momentum tail."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    ch, hw, bs = (8, 6, 2) if smoke else (32, 12, 4)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [ch, hw, hw], dtype='float32')
        y = L.data('y', [1], dtype='float32')

        def conv_bn(inp, ch_out, k, act=None):
            c = L.conv2d(inp, ch_out, k, padding=(k - 1) // 2,
                         bias_attr=False)
            return L.batch_norm(c, act=act)

        h = conv_bn(x, ch // 2, 1, act='relu')
        h = conv_bn(h, ch // 2, 3, act='relu')
        h = conv_bn(h, ch, 1)
        h = L.relu(L.elementwise_add(h, x))
        pool = L.reduce_mean(h, dim=[2, 3])
        pred = L.fc(pool, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.Momentum(learning_rate=1e-2,
                                 momentum=0.9).minimize(loss)
    rng = np.random.RandomState(0)

    def make_feed():
        return {'x': rng.randn(bs, ch, hw, hw).astype(np.float32),
                'y': rng.randn(bs, 1).astype(np.float32)}

    return main, startup, make_feed, loss


def build_bert_layer(smoke=False):
    """Static transformer layer: QKV projections, scaled-dot attention,
    residual + layer_norm, GELU FFN — fc-heavy, so add+act fusion and the
    Adam tail both engage."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    hid, seq, heads, bs = (16, 4, 2, 1) if smoke else (64, 16, 4, 2)
    dh = hid // heads
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [seq, hid], dtype='float32')
        y = L.data('y', [1], dtype='float32')

        def proj(inp, act=None):
            return L.fc(inp, size=hid, num_flatten_dims=2, act=act)

        q, k, v = proj(x), proj(x), proj(x)

        def split_heads(t):
            t = L.reshape(t, shape=[0, seq, heads, dh])
            return L.transpose(t, perm=[0, 2, 1, 3])

        qh, kh, vh = split_heads(q), split_heads(k), split_heads(v)
        scores = L.scale(L.matmul(qh, kh, transpose_y=True),
                         scale=1.0 / np.sqrt(dh))
        ctxv = L.matmul(L.softmax(scores), vh)
        ctxv = L.reshape(L.transpose(ctxv, perm=[0, 2, 1, 3]),
                         shape=[0, seq, hid])
        attn_out = proj(ctxv)
        h = L.layer_norm(L.elementwise_add(attn_out, x), begin_norm_axis=2)
        ffn = L.fc(h, size=hid * 2, num_flatten_dims=2, act='gelu')
        ffn = L.fc(ffn, size=hid, num_flatten_dims=2)
        h2 = L.layer_norm(L.elementwise_add(ffn, h), begin_norm_axis=2)
        pred = L.fc(L.reduce_mean(h2, dim=[1]), size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    rng = np.random.RandomState(0)

    def make_feed():
        return {'x': rng.randn(bs, seq, hid).astype(np.float32),
                'y': rng.randn(bs, 1).astype(np.float32)}

    return main, startup, make_feed, loss


MODELS = {'mlp_adam': build_mlp_adam, 'resnet_block': build_resnet_block,
          'bert_layer': build_bert_layer}


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------

def _fused_build_strategy():
    from paddle_tpu.compiler import BuildStrategy
    bs = BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    bs.fuse_all_optimizer_ops = True
    return bs


def count_eqns(jaxpr):
    """Total equations including nested (pjit/cond/scan/remat) jaxprs."""
    total = 0
    for eqn in jaxpr.eqns:
        total += 1
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                total += count_eqns(sub)
    return total


def _sub_jaxprs(v):
    import jax
    if isinstance(v, jax.core.Jaxpr):
        return [v]
    if isinstance(v, jax.core.ClosedJaxpr):
        return [v.jaxpr]
    if isinstance(v, (list, tuple)):
        return [s for x in v for s in _sub_jaxprs(x)]
    return []


def _lowered_step(program, feed_vals, fetch_name, state, passes_on):
    """(step fn, optimized program) after optionally running the pipeline —
    the pass cost itself is part of the measured trace+lower time."""
    from paddle_tpu import ir
    from paddle_tpu.executor import _lower
    if passes_on:
        program, _ = ir.apply_pipeline(
            program, fetch_names=[fetch_name], feed_names=list(feed_vals),
            build_strategy=_fused_build_strategy())
    step = _lower(program, sorted(feed_vals), [fetch_name],
                  sorted(state))
    return step, program


def measure_model(name, builder, iters=3, smoke=False):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, startup, make_feed, loss = builder(smoke)
    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.global_scope()
    state = {v.name: jnp.asarray(scope.find(v.name))
             for v in main.list_vars() if v.persistable}
    feed_vals = {k: jnp.asarray(v) for k, v in make_feed().items()}
    key = jax.random.PRNGKey(0)

    out = {'bench': f'passes_{name}'}
    for tag, on in (('off', False), ('on', True)):
        step, prog = _lowered_step(main, feed_vals, loss.name, state, on)
        jaxpr = jax.make_jaxpr(step)({}, state, feed_vals, key)
        t0 = time.perf_counter()
        for _ in range(iters):
            step_i, _ = _lowered_step(main, feed_vals, loss.name, state, on)
            jax.jit(step_i, donate_argnums=(0,)).lower(
                {}, state, feed_vals, key)
        dt = (time.perf_counter() - t0) / iters
        out[f'ops_{tag}'] = len(prog.global_block().ops)
        out[f'eqns_{tag}'] = count_eqns(jaxpr.jaxpr)
        out[f'trace_lower_ms_{tag}'] = round(dt * 1e3, 3)
    out['eqn_reduction'] = round(1 - out['eqns_on'] / out['eqns_off'], 4)
    out['op_reduction'] = round(1 - out['ops_on'] / out['ops_off'], 4)
    out['trace_lower_speedup'] = round(
        out['trace_lower_ms_off'] / max(out['trace_lower_ms_on'], 1e-9), 3)
    return out


def measure_executor_compile(iters=2, smoke=True):
    """executor_compile_seconds (PR 2 telemetry) for the mlp_adam program,
    pipeline off vs on through the REAL Executor.run path, in both compile
    regimes:

    - cold: persistent XLA cache disabled — trace + lower + full backend
      compile (the one-time-EVER cost per program, amortized across
      processes by PR 1's persistent cache);
    - warm: persistent cache pre-populated — trace + lower + executable
      deserialize, i.e. what EVERY cold process start pays in production.
      The pass pipeline targets exactly this number: the trace is the one
      cost the compile cache cannot amortize.

    Identical feed shapes per off/on pair; a fresh Executor (fresh jit
    closure) per run forces a real retrace."""
    import tempfile
    import numpy as np
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.compiler import CompiledProgram

    main, startup, make_feed, loss = build_mlp_adam(smoke)
    fluid.Executor().run(startup)
    base_feed = make_feed()

    def run_once(passes_on, batch, cache_dir):
        feed = {k: np.repeat(v, batch, axis=0) for k, v in base_feed.items()}
        old_env = os.environ.get('PADDLE_TPU_PASSES')
        os.environ['PADDLE_TPU_PASSES'] = '1' if passes_on else '0'
        # drive jax's cache config directly: Executor.setup_persistent_cache
        # configures it at most once per process, which would leave earlier
        # experiments' settings live and taint the A/B
        old_dir = jax.config.jax_compilation_cache_dir
        old_min = jax.config.jax_persistent_cache_min_compile_time_secs
        old_sz = jax.config.jax_persistent_cache_min_entry_size_bytes
        old_en = jax.config.jax_enable_compilation_cache
        # jax materializes its cache object once and then ignores config
        # changes; drop it so THIS run's dir/enable settings take effect
        # (private API — best-effort, the enable flag still guards cold)
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
        jax.config.update('jax_enable_compilation_cache',
                          cache_dir is not None)
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        if cache_dir is not None:
            jax.config.update(
                'jax_persistent_cache_min_compile_time_secs', 0.0)
            jax.config.update(
                'jax_persistent_cache_min_entry_size_bytes', -1)
        try:
            with obs.telemetry_guard(True):
                obs.reset()
                exe = fluid.Executor()
                cp = CompiledProgram(main,
                                     build_strategy=_fused_build_strategy())
                exe.run(cp, feed=feed, fetch_list=[loss])
                hist = obs.registry.to_dict()['executor_compile_seconds']
                return sum(s['sum'] for s in hist['samples'])
        finally:
            jax.config.update('jax_enable_compilation_cache', old_en)
            jax.config.update('jax_compilation_cache_dir', old_dir)
            jax.config.update(
                'jax_persistent_cache_min_compile_time_secs', old_min)
            jax.config.update(
                'jax_persistent_cache_min_entry_size_bytes', old_sz)
            if old_env is None:
                os.environ.pop('PADDLE_TPU_PASSES', None)
            else:
                os.environ['PADDLE_TPU_PASSES'] = old_env

    cold_off = [run_once(False, 1 + i, None) for i in range(iters)]
    cold_on = [run_once(True, 1 + i, None) for i in range(iters)]
    warm_dir = tempfile.mkdtemp(prefix='bench_passes_xla_cache_')
    warm_off, warm_on = [], []
    for i in range(iters):
        batch = 1 + iters + i
        run_once(False, batch, warm_dir)            # populate
        warm_off.append(run_once(False, batch, warm_dir))
        run_once(True, batch, warm_dir)
        warm_on.append(run_once(True, batch, warm_dir))
    return {'bench': 'passes_executor_compile',
            'cold_compile_s_off': round(min(cold_off), 4),
            'cold_compile_s_on': round(min(cold_on), 4),
            'cold_compile_speedup': round(
                min(cold_off) / max(min(cold_on), 1e-9), 3),
            'warm_compile_s_off': round(min(warm_off), 4),
            'warm_compile_s_on': round(min(warm_on), 4),
            'warm_compile_speedup': round(
                min(warm_off) / max(min(warm_on), 1e-9), 3)}


def _hermetic_compile_cache():
    """Point the persistent XLA cache at a fresh temp dir BEFORE any
    Executor configures jax (the first configuration wins for the whole
    process): entries a developer's ~/.cache accumulated must not serve
    this bench's 'cold' compiles."""
    import tempfile
    os.environ.setdefault(
        'PADDLE_TPU_COMPILE_CACHE_DIR',
        tempfile.mkdtemp(prefix='bench_passes_xla_cache_'))


def measure_all(iters=3, smoke=False):
    _hermetic_compile_cache()
    out = {}
    for name, builder in MODELS.items():
        out[name] = measure_model(name, builder, iters=iters, smoke=smoke)
    out['executor_compile'] = measure_executor_compile(
        iters=max(2, iters // 2), smoke=smoke)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--iters', type=int, default=3,
                    help='trace+lower timing repetitions')
    ap.add_argument('--smoke', action='store_true',
                    help='tiny shapes / CI smoke sizes')
    args = ap.parse_args()
    for res in measure_all(iters=args.iters, smoke=args.smoke).values():
        print(json.dumps(res), flush=True)


if __name__ == '__main__':
    main()
