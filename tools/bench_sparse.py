"""Sparse embedding bench: rows-only grads vs dense scatter, bytes on wire.

Sections (one JSON line each, like the sibling bench tools):

- ``sparse_lookup_throughput`` — gather throughput (lookups/sec) for a
  V×D table at nnz ids/step (the PERF.md §21 lookups/sec line).
- ``sparse_step_time`` — the headline: one embedding train step
  (forward gather → grad → SGD update), dense-scatter legacy vs
  rows-only coalesce+scatter-apply, at V ∈ {1e4, 1e6}, nnz≈4k. The
  dense path moves O(V·D) HBM per step, the sparse path O(nnz·D).
  Acceptance (full size): sparse ≥ 5× dense at V=1e6.
- ``sparse_bytes_on_wire`` — DP gradient-sync bytes for the same table:
  dense f32 all-reduce vs the COO push (int32 rows + vals at
  f32/bf16/int8-with-row-scales). Acceptance: sparse-int8 ≥ 100×
  smaller than dense, and ≥ 3.5× smaller than f32 rows.
- ``sparse_executor_parity`` — end-to-end static Programs (embedding
  MLP, SGD) sparse vs dense: steps/s both ways and final-loss parity
  (allclose), through the REAL Executor lowering.

  JAX_PLATFORMS=cpu python tools/bench_sparse.py [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault('JAX_PLATFORMS', 'cpu')


def emit(obj):
    print(json.dumps(obj), flush=True)          # lint: allow-print (CLI)


def _median_time(fn, iters):
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def measure_lookup_throughput(vocab, dim, nnz, iters=30):
    import numpy as np
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, vocab, (nnz,)).astype(np.int32))
    look = jax.jit(lambda w_, i_: jnp.take(w_, i_, axis=0))
    look(w, ids).block_until_ready()
    t = _median_time(lambda: look(w, ids).block_until_ready(), iters)
    return {'bench': 'sparse_lookup_throughput', 'vocab': vocab, 'dim': dim,
            'nnz': nnz, 'lookups_per_sec': round(nnz / t, 1),
            'lookup_ms': round(t * 1e3, 4)}


def measure_step_time(vocab, dim, nnz, iters=20, accept_ratio=None):
    """One embedding train step, dense-scatter vs rows-only. Both paths
    are jitted with the table donated; the loss (sum of gathered rows ×
    a target) makes the cotangent per-occurrence dense, the worst case
    for coalescing."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import sparse_ops as sp
    rng = np.random.RandomState(1)
    w0 = rng.randn(vocab, dim).astype(np.float32)
    ids = jnp.asarray(rng.randint(0, vocab, (nnz,)).astype(np.int32))
    tgt = jnp.asarray(rng.randn(nnz, dim).astype(np.float32))
    lr = jnp.float32(0.05)
    bucket = sp.nnz_bucket(nnz)

    def dense_step(w, ids_, tgt_):
        def loss(w_):
            return jnp.sum(jnp.take(w_, ids_, axis=0) * tgt_)
        g = jax.grad(loss)(w)                    # dense V×D scatter-add
        return w - lr * g                        # O(V·D) update

    def sparse_step(w, ids_, tgt_):
        # per-occurrence cotangent of the same loss is tgt_ itself —
        # coalesce + scatter-apply, no V×D tensor anywhere
        rows, vals = sp.coalesce_rows(ids_, tgt_, vocab, bucket=bucket)
        return sp.sparse_sgd(w, rows, vals, lr)

    d_fn = jax.jit(dense_step, donate_argnums=(0,))
    s_fn = jax.jit(sparse_step, donate_argnums=(0,))

    def run(fn):
        w = jnp.asarray(w0)
        w = fn(w, ids, tgt)
        w.block_until_ready()                    # warm/compile
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            w = fn(w, ids, tgt)
            w.block_until_ready()
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts), np.asarray(w)

    td, wd = run(d_fn)
    ts_, ws = run(s_fn)
    # same ids/targets each iter → identical final tables up to f32
    # reduction order in the duplicate-id sum
    parity = bool(np.allclose(wd, ws, atol=1e-4))
    ratio = td / ts_ if ts_ > 0 else float('inf')
    out = {'bench': 'sparse_step_time', 'vocab': vocab, 'dim': dim,
           'nnz': nnz, 'bucket': bucket,
           'dense_step_ms': round(td * 1e3, 3),
           'sparse_step_ms': round(ts_ * 1e3, 3),
           'sparse_over_dense': round(ratio, 2), 'parity': parity}
    if accept_ratio is not None:
        out['acceptance_ge'] = accept_ratio
        out['ok'] = parity and ratio >= accept_ratio
        if not out['ok']:
            raise AssertionError(
                f'sparse step {ratio:.2f}x dense (need >= {accept_ratio}) '
                f'or parity failed ({parity}) at V={vocab}')
    return out


def measure_bytes_on_wire(vocab, dim, nnz, replicas=8):
    from paddle_tpu.ops import sparse_ops as sp
    from paddle_tpu.parallel import quant_collectives as qc
    bucket = sp.nnz_bucket(nnz)
    dense = qc.wire_bytes(vocab * dim, 'f32', replicas)
    rows_f32 = qc.sparse_wire_bytes(bucket, dim, 'f32', replicas)
    rows_bf16 = qc.sparse_wire_bytes(bucket, dim, 'bf16', replicas)
    rows_int8 = qc.sparse_wire_bytes(bucket, dim, 'int8', replicas)
    out = {'bench': 'sparse_bytes_on_wire', 'vocab': vocab, 'dim': dim,
           'nnz': nnz, 'bucket': bucket, 'replicas': replicas,
           'dense_f32_bytes': dense, 'sparse_f32_bytes': rows_f32,
           'sparse_bf16_bytes': rows_bf16, 'sparse_int8_bytes': rows_int8,
           'dense_over_sparse_int8': round(dense / rows_int8, 1),
           'sparse_f32_over_int8': round(rows_f32 / rows_int8, 2)}
    out['ok'] = (out['dense_over_sparse_int8'] >= 100.0
                 and out['sparse_f32_over_int8'] >= 3.5)
    if not out['ok']:
        raise AssertionError(f'bytes-on-wire acceptance failed: {out}')
    return out


def _exec_recipe(vocab, dim, fields, is_sparse, steps, batch):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    from paddle_tpu.core.random import default_generator
    import paddle_tpu.core.scope as sm
    from paddle_tpu.core.scope import Scope
    default_generator.seed(11)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [fields], dtype='int64')
        label = L.data('label', [1], dtype='float32')
        emb = L.embedding(ids, size=[vocab, dim], is_sparse=is_sparse)
        h = L.fc(emb, size=32, act='relu')
        out = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(out, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    old = sm._global_scope
    sm._global_scope = Scope()
    try:
        exe.run(startup)
        rng = np.random.RandomState(7)
        feeds = [{'ids': rng.randint(0, vocab, (batch, fields))
                  .astype(np.int64),
                  'label': rng.rand(batch, 1).astype(np.float32)}
                 for _ in range(steps)]
        exe.run(main, feed=feeds[0], fetch_list=[loss])   # compile
        losses, t0 = [], time.perf_counter()
        for f in feeds:
            l, = exe.run(main, feed=f, fetch_list=[loss])
            losses.append(float(l))
        wall = time.perf_counter() - t0
        return losses, wall
    finally:
        sm._global_scope = old


def measure_executor_parity(vocab, dim, fields, steps, batch):
    import numpy as np
    ld, wd = _exec_recipe(vocab, dim, fields, False, steps, batch)
    ls, ws = _exec_recipe(vocab, dim, fields, True, steps, batch)
    parity = bool(np.allclose(ld, ls, atol=1e-4))
    out = {'bench': 'sparse_executor_parity', 'vocab': vocab,
           'fields': fields, 'steps': steps,
           'dense_steps_per_s': round(steps / wd, 2),
           'sparse_steps_per_s': round(steps / ws, 2),
           'loss_allclose': parity, 'final_loss': round(ls[-1], 6),
           'ok': parity}
    if not parity:
        raise AssertionError(
            f'sparse-vs-dense executor loss mismatch: {ld[-3:]} vs '
            f'{ls[-3:]}')
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true',
                    help='small sizes + relaxed acceptance (tier-1 CI)')
    args = ap.parse_args()
    import paddle_tpu  # noqa: F401  (registers ops)

    if args.smoke:
        # V must dwarf nnz for the O(V·D)-vs-O(nnz·D) asymmetry to show
        # over the coalesce's fixed cost — 100k:512 keeps the smoke fast
        # AND honest (10k:512 measures the sort, not the scatter)
        dim, nnz = 32, 512
        emit(measure_lookup_throughput(10_000, dim, nnz, iters=10))
        emit(measure_step_time(100_000, dim, nnz, iters=8,
                               accept_ratio=2.0))
        emit(measure_bytes_on_wire(1_000_000, 64, 4096))
        emit(measure_executor_parity(2_000, 16, 8, steps=6, batch=16))
    else:
        dim, nnz = 64, 4096
        emit(measure_lookup_throughput(1_000_000, dim, nnz))
        emit(measure_step_time(10_000, dim, nnz, iters=20))
        emit(measure_step_time(1_000_000, dim, nnz, iters=20,
                               accept_ratio=5.0))
        emit(measure_bytes_on_wire(1_000_000, dim, nnz))
        emit(measure_executor_parity(50_000, 16, 16, steps=20, batch=64))


if __name__ == '__main__':
    main()
