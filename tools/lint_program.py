"""Static Program linter CLI (paddle_tpu/analysis/).

Loads a saved inference model — or builds one of the tier-1 recipe
programs — and prints the full verifier diagnostic report: shape/dtype
inference findings, dataflow errors (read-before-write, dangling vars),
dead code, collective consistency, and donation hazards, each with the
op and its Python construction site.

    JAX_PLATFORMS=cpu python tools/lint_program.py --recipe mnist_mlp
    JAX_PLATFORMS=cpu python tools/lint_program.py --model-dir /path/to/model
    JAX_PLATFORMS=cpu python tools/lint_program.py --recipe bert_layer \
        --passes --json

``--passes`` additionally runs the IR pass pipeline (all fuse knobs on)
and re-verifies the rewritten program — the same post-condition the
executor applies at ``PADDLE_TPU_VERIFY=passes``.

Exit code: 0 = nothing at/above ``--fail-on`` (default ``error``),
1 = findings, 2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

RECIPES = ('mnist_mlp', 'mlp_adam', 'resnet_block', 'bert_layer',
           'fleet_dp', 'seq2seq_decode')


def _build_recipe(name):
    """(main_program, fetch_names, feed_names) for one tier-1 recipe."""
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    sys.path.insert(0, os.path.join(_REPO, 'tools'))
    from bench_passes import (build_bert_layer, build_mlp_adam,
                              build_resnet_block)

    if name == 'mnist_mlp':
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = L.data('img', [64], dtype='float32')
            label = L.data('label', [1], dtype='int64')
            h = L.fc(img, size=32, act='relu')
            h = L.fc(h, size=32, act='relu')
            logits = L.fc(h, size=10)
            loss = L.reduce_mean(
                L.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        return main, [loss.name], ['img', 'label']
    if name in ('mlp_adam', 'resnet_block', 'bert_layer'):
        builder = {'mlp_adam': build_mlp_adam,
                   'resnet_block': build_resnet_block,
                   'bert_layer': build_bert_layer}[name]
        main, _startup, make_feed, fetch = builder(smoke=True)
        feed = make_feed() if callable(make_feed) else make_feed
        return main, [fetch.name], sorted(feed)
    if name == 'fleet_dp':
        from paddle_tpu.parallel import DistributedStrategy, fleet
        fleet.init()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = L.data('x', shape=[32], dtype='float32')
            y = L.data('y', shape=[1], dtype='int64')
            h = L.fc(x, size=32, act='relu')
            logits = L.fc(h, size=10)
            loss = L.reduce_mean(
                L.softmax_with_cross_entropy(logits, y))
            fleet.distributed_optimizer(
                fluid.optimizer.SGD(0.1),
                strategy=DistributedStrategy()).minimize(loss)
        return main, [loss.name], ['x', 'y']
    if name == 'seq2seq_decode':
        main, fetches, feeds = _build_seq2seq()
        return main, fetches, feeds
    raise SystemExit(f'unknown recipe {name!r}; choose from {RECIPES}')


def _build_seq2seq():
    """Static greedy-decode-style program: embedding + fixed-trip RNN
    loop over a while op — the control-flow shape the decode path emits."""
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [8], dtype='int64')
        emb = L.embedding(ids, size=[100, 16])
        h = L.fc(emb, size=16, act='tanh')
        logits = L.fc(h, size=100)
        probs = L.softmax(logits)
    return main, [probs.name], ['ids']


def _load_model(dirname):
    import paddle_tpu as fluid
    exe = fluid.Executor()
    program, feed_names, fetch_targets = fluid.io.load_inference_model(
        dirname, exe)
    return program, [t.name for t in fetch_targets], list(feed_names)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument('--model-dir',
                     help='saved inference model (fluid.io.'
                          'save_inference_model layout)')
    src.add_argument('--recipe', choices=RECIPES,
                     help='build one of the tier-1 recipe programs')
    ap.add_argument('--passes', action='store_true',
                    help='also run the IR pass pipeline (fuse knobs on) '
                         'and re-verify the rewritten program')
    ap.add_argument('--plan', action='store_true',
                    help='append the static memory plan (peak HBM, top '
                         'residents, op cost ranking — '
                         'tools/plan_program.py report)')
    ap.add_argument('--batch-size', type=int, default=16,
                    help='dynamic-dim substitution for --plan '
                         '(default 16)')
    ap.add_argument('--json', action='store_true',
                    help='emit machine-readable diagnostics')
    ap.add_argument('--fail-on', choices=('info', 'warning', 'error'),
                    default='error',
                    help='exit 1 when diagnostics at/above this severity '
                         'exist (default: error)')
    args = ap.parse_args(argv)

    # site capture must be on while the recipe builds its ops
    os.environ.setdefault('PADDLE_TPU_VERIFY', 'full')
    from paddle_tpu import analysis

    if args.model_dir:
        program, fetches, feeds = _load_model(args.model_dir)
        label = args.model_dir
    else:
        program, fetches, feeds = _build_recipe(args.recipe)
        label = args.recipe

    reports = [('pre-lower', analysis.verify_program(
        program, fetch_names=fetches, feed_names=feeds, stage='pre'))]
    if args.passes:
        from paddle_tpu import ir
        from paddle_tpu.compiler import BuildStrategy
        bs = BuildStrategy()
        bs.fuse_elewise_add_act_ops = True
        bs.fuse_all_optimizer_ops = True
        bs.fuse_all_reduce_ops = True
        opt, _ctx = ir.apply_pipeline(program, fetch_names=fetches,
                                      feed_names=feeds, build_strategy=bs)
        reports.append(('post-pipeline', analysis.verify_program(
            opt, fetch_names=fetches, feed_names=feeds,
            stage='post-pipeline')))

    plan = None
    if args.plan:
        from paddle_tpu.analysis.plan import plan_program
        plan = plan_program(program, fetch_names=fetches,
                            feed_names=feeds,
                            assume_dim=args.batch_size)

    all_diags = [d for _, ds in reports for d in ds]
    if args.json:
        doc = {
            'target': label,
            'stages': {stage: [d.to_dict() for d in ds]
                       for stage, ds in reports},
            'max_severity': analysis.max_severity(all_diags),
        }
        if plan is not None:
            doc['plan'] = plan.to_dict()
        print(json.dumps(doc, indent=1))
    else:
        for stage, ds in reports:
            print(analysis.format_report(
                ds, f'{label} [{stage}]: {len(ds)} finding(s)'))
        if plan is not None:
            print('\n'.join(plan.format_report()))
    return 1 if analysis.severity_at_least(all_diags, args.fail_on) else 0


if __name__ == '__main__':
    sys.exit(main())
