"""Fleet weak-scaling bench (PERF.md §18): real ``jax.distributed`` CPU
workers through the REAL product spine — fleet env bootstrap, executor
with global-array feeds, partitioner mesh, per-host input sharding.

Weak scaling: per-host batch is FIXED, so the global batch (and the total
work) grows with the fleet. The reported **scaling efficiency** is

    efficiency(n) = global_samples_per_s(n) / global_samples_per_s(1)

i.e. throughput delivered per unit of hardware, normalized to the 1-host
run. On a real pod every host owns its cores and this is the classic
weak-scaling curve; on THIS bench host all workers timeshare one machine,
so the same formula prices exactly what the fleet runtime adds — gloo
collectives, lockstep synchronization, bring-up, dispatch — against the
perfect-timesharing ideal (1.0). The compute-bound recipe (wide MLP, big
per-host batch) keeps the comm/compute ratio representative of the pod
regime; acceptance is ≥ 0.8 at nproc=2.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_fleet.py [--smoke] [--nprocs 1,2,4]

Each fleet size spawns via ``fleet_runtime.local_fleet`` (one process per
trainer, one device each, gloo collectives, full PADDLE_* env)."""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# worker: one trainer of the fleet (invoked by local_fleet with env wired)
# ---------------------------------------------------------------------------

def worker(result_path, hidden, depth, batch_per_host, iters):
    import numpy as np
    from paddle_tpu.fleet_runtime import bootstrap
    bootstrap()
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import layers as L
    from paddle_tpu.parallel import DistributedStrategy, fleet

    n = jax.process_count()
    rank = jax.process_index()
    global_batch = batch_per_host * n

    fluid.seed(7)
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = L.data('bx', [hidden], dtype='float32')
        y = L.data('by', [1], dtype='float32')
        h = x
        for _ in range(depth):
            h = L.fc(h, size=hidden, act='relu')
        pred = L.fc(h, size=1)
        loss = L.mean(L.square_error_cost(pred, y))
        fleet.init()
        fleet.distributed_optimizer(
            fluid.optimizer.Momentum(0.01, momentum=0.9),
            strategy=DistributedStrategy()).minimize(loss)

    exe = fluid.Executor()
    exe.run(start)
    rng = np.random.RandomState(0)
    X = rng.randn(global_batch, hidden).astype('float32')
    Y = rng.randn(global_batch, 1).astype('float32')
    feed = {'bx': X[rank::n], 'by': Y[rank::n]}   # this host's rows

    for _ in range(3):                             # compile + warm
        float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss])[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        lv = exe.run(main, feed=feed, fetch_list=[loss])[0]
    final = float(np.asarray(lv))
    dt = (time.perf_counter() - t0) / iters
    if rank == 0:
        with open(result_path, 'w') as f:
            json.dump({'nproc': n, 'steps_per_s': round(1.0 / dt, 3),
                       'samples_per_s': round(global_batch / dt, 1),
                       'global_batch': global_batch,
                       'final_loss': final}, f)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def measure_fleet(nprocs=(1, 2, 4), smoke=False, iters=None):
    import tempfile
    from paddle_tpu.fleet_runtime import local_fleet
    # full sizes put the step firmly in the compute-bound pod regime
    # (~0.2s of per-host compute vs ~50ms of per-step collective-launch
    # latency on this 1-core bench host) — the regime the ≥0.8
    # acceptance is defined over. Smoke shrinks compute ~6× for CI and
    # reports the same lines without the acceptance bar.
    hidden = 256 if smoke else 512
    depth = 4 if smoke else 8
    batch = 2048
    iters = iters or (4 if smoke else 8)
    results = {}
    out = []
    with tempfile.TemporaryDirectory() as td:
        for n in nprocs:
            res = os.path.join(td, f'r{n}.json')
            fl = local_fleet(
                n, os.path.abspath(__file__),
                args=['--worker', res, '--hidden', hidden, '--depth',
                      depth, '--batch-per-host', batch, '--iters', iters],
                env={'PYTHONPATH': _REPO, 'PADDLE_TPU_VERIFY': 'off',
                     # honest per-worker compute on a shared machine:
                     # single-threaded XLA per process, no thread thrash
                     'XLA_FLAGS': '--xla_cpu_multi_thread_eigen=false'},
                cwd=_REPO)
            rcs = fl.wait(timeout=900)
            if any(rc != 0 for rc in rcs):
                raise SystemExit(f'fleet nproc={n} failed: rc={rcs}')
            with open(res) as f:
                r = json.load(f)
            results[n] = r
            rec = {'bench': 'fleet_weak_scaling', **r}
            out.append(rec)
            print(json.dumps(rec), flush=True)
    base = results[min(results)]
    eff = {str(n): round(r['samples_per_s'] / base['samples_per_s'], 3)
           for n, r in results.items()}
    summary = {
        'bench': 'fleet_weak_scaling_summary',
        'hidden': hidden, 'depth': depth, 'batch_per_host': batch,
        'iters': iters, 'host_cores': os.cpu_count(),
        'steps_per_s': {str(n): r['steps_per_s']
                        for n, r in results.items()},
        'samples_per_s': {str(n): r['samples_per_s']
                          for n, r in results.items()},
        'efficiency': eff,
        'efficiency_nproc2': eff.get('2'),
        'acceptance_ge_0_8': (eff.get('2') is None
                              or eff['2'] >= 0.8),
    }
    print(json.dumps(summary), flush=True)
    return {'fleet_weak_scaling': summary, 'runs': out}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true',
                    help='tiny sizes / CI smoke (nprocs 1,2)')
    ap.add_argument('--nprocs', default=None,
                    help='comma list of fleet sizes (default 1,2,4; '
                         'smoke 1,2)')
    ap.add_argument('--iters', type=int, default=None)
    # worker protocol (spawned by local_fleet; env carries the fleet spec)
    ap.add_argument('--worker', default=None, help=argparse.SUPPRESS)
    ap.add_argument('--hidden', type=int, default=512,
                    help=argparse.SUPPRESS)
    ap.add_argument('--depth', type=int, default=3, help=argparse.SUPPRESS)
    ap.add_argument('--batch-per-host', type=int, default=128,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.worker:
        worker(args.worker, args.hidden, args.depth, args.batch_per_host,
               args.iters or 10)
        return
    nprocs = (tuple(int(x) for x in args.nprocs.split(','))
              if args.nprocs else ((1, 2) if args.smoke else (1, 2, 4)))
    measure_fleet(nprocs=nprocs, smoke=args.smoke, iters=args.iters)


if __name__ == '__main__':
    main()
