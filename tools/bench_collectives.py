"""Quantized + bucketed gradient-collective bench (PERF.md §16).

Four sections, each printed as one JSON line (partial-evidence protocol):

- ``collectives_bytes`` — telemetry-counted bytes-on-wire for one
  gradient-volume sync at f32 / bf16 / int8 on the 8-device CPU mesh,
  plus the measured max elementwise error of the quantized all-reduce vs
  the exact ``lax.psum``. THE acceptance: int8 reduction ≥ 3.5×.
- ``collectives_steps`` — steps/s of an explicit-gradient-sync DP train
  step (shard_map, grads reduced with ``qallreduce_mean``) per comm
  dtype. On CPU the codec is host arithmetic with no real interconnect to
  save, so int8 is NOT expected to win here — bytes is the column that
  transfers to TPU; this column proves the quantized step is a working
  train step and prices the codec.
- ``collectives_convergence`` — the MNIST-MLP recipe trained twice on
  identical data/init, grads synced at f32 vs int8; final-loss parity
  within tolerance is the EQuARX "negligible quality loss" claim.
- ``collectives_bucketing`` — the fleet static path: per-grad
  ``c_allreduce_sum`` ops bucketed by ir/bucket_allreduce.py under a
  small cap; losses must be BITWISE identical pass-on/off and the bucket
  count must match the cap arithmetic.

Runs on any backend; sized for CPU::

  JAX_PLATFORMS=cpu python tools/bench_collectives.py [--smoke] [--iters N]

Multi-process mode (real cross-process reduce through the dygraph
DataParallel bundle path)::

  python tools/bench_collectives.py --nproc 2

spawns the workers, initializes ``jax.distributed`` over localhost, and
verifies the bundled quantized all-reduce sums per-process gradients
exactly (f32) / within the codec bound (int8). Not part of ``--smoke``
(tier-1 stays single-process).
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

# runnable as `python tools/bench_collectives.py` from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

NDEV = 8


def _force_devices():
    flags = os.environ.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count={NDEV}').strip()


# ---------------------------------------------------------------------------
# explicit-sync DP train step (shard_map + qallreduce over 'dp')
# ---------------------------------------------------------------------------

def _init_mlp(rng, in_dim, hidden, out_dim):
    import numpy as np
    s1 = (2.0 / in_dim) ** 0.5
    s2 = (2.0 / hidden) ** 0.5
    return {'w1': (rng.randn(in_dim, hidden) * s1).astype(np.float32),
            'b1': np.zeros(hidden, np.float32),
            'w2': (rng.randn(hidden, out_dim) * s2).astype(np.float32),
            'b2': np.zeros(out_dim, np.float32)}


def make_dp_step(mesh, params, lr, comm_dtype, axis='dp'):
    """Jitted data-parallel step: batch sharded over `axis`, params
    replicated, per-shard grads explicitly synced with qallreduce_mean at
    `comm_dtype` (exact pmean at f32). Returns (step_fn, n_elems)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.core import compat
    from paddle_tpu.parallel import quant_collectives as qc

    def loss_fn(p, x, y):
        h = jnp.maximum(x @ p['w1'] + p['b1'], 0.0)
        logits = h @ p['w2'] + p['b2']
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y, axis=1))

    def body(p, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        grads = {k: compat.pcast(
            qc.qallreduce_mean(g, axis, comm_dtype=comm_dtype),
            axis, to='varying') for k, g in grads.items()}
        new_p = {k: v - lr * grads[k] for k, v in p.items()}
        return new_p, lax.pmean(loss, axis)

    pspec = {k: P() for k in params}
    fn = compat.shard_map(body, mesh=mesh,
                          in_specs=(pspec, P(axis), P(axis)),
                          out_specs=(pspec, P()))
    n_elems = sum(int(v.size) for v in params.values())
    return jax.jit(fn, donate_argnums=(0,)), n_elems


def _mnist_like(rng, n, in_dim=784, classes=10):
    """Prototype-digit corpus (the test_mnist_convergence recipe shape):
    per-class fixed prototypes + pixel noise, learnable by an MLP."""
    import numpy as np
    protos = rng.randint(0, 256, (classes, in_dim))
    labels = rng.randint(0, classes, n)
    imgs = np.clip(protos[labels] + rng.randint(-80, 80, (n, in_dim)),
                   0, 255).astype(np.float32) / 255.0
    return imgs, labels.astype(np.int32)[:, None]


# ---------------------------------------------------------------------------
# sections
# ---------------------------------------------------------------------------

def measure_bytes(smoke=False):
    """Telemetry-counted wire bytes per comm dtype + quantized-vs-exact
    error for one gradient-volume all-reduce on the dp mesh."""
    import numpy as np
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from paddle_tpu import observability as obs
    from paddle_tpu.core import compat
    from paddle_tpu.parallel import quant_collectives as qc
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({'dp': NDEV})
    elems = (1 << 16) if smoke else (1 << 20)
    rng = np.random.RandomState(0)
    X = rng.randn(NDEV, elems).astype('float32')
    want = np.asarray(
        compat.shard_map(lambda v: lax.psum(v[0], 'dp')[None], mesh=mesh,
                         in_specs=P('dp'), out_specs=P('dp'))(
            jnp.asarray(X)))[0]

    out = {'bench': 'collectives_bytes', 'grad_elems': elems,
           'devices': NDEV}
    with obs.telemetry_guard(True):
        for comm in ('f32', 'bf16', 'int8'):
            obs.reset()
            got = np.asarray(
                compat.shard_map(
                    lambda v: qc.qallreduce_sum(v[0], 'dp',
                                                comm_dtype=comm)[None],
                    mesh=mesh, in_specs=P('dp'), out_specs=P('dp'))(
                    jnp.asarray(X)))[0]
            qc.record_collective('bench', elems, comm, NDEV)
            m = obs.registry.to_dict()
            wire = sum(s['value']
                       for s in m['collective_bytes_on_wire']['samples'])
            f32eq = sum(s['value']
                        for s in m['collective_bytes_f32_equiv']['samples'])
            err = float(np.abs(got - want).max())
            rel = err / float(np.abs(want).max())
            out[f'wire_bytes_{comm}'] = int(wire)
            out[f'reduction_{comm}'] = round(f32eq / wire, 3)
            out[f'max_rel_err_{comm}'] = float(f'{rel:.3e}')
            if comm == 'f32':
                out['f32_exact'] = bool(np.array_equal(got, want))
    out['bytes_reduction_int8'] = out['reduction_int8']
    out['acceptance_ge_3_5x'] = out['reduction_int8'] >= 3.5
    return out


def measure_steps(iters=30, smoke=False):
    """steps/s of the explicit-sync DP step per comm dtype (CPU prices the
    codec; the interconnect win needs real ICI — documented)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({'dp': NDEV})
    hidden = 64 if smoke else 512
    bs = NDEV * (8 if smoke else 32)
    iters = max(4, iters // 4) if smoke else iters
    rng = np.random.RandomState(0)
    X, Y = _mnist_like(rng, bs)
    data_sh = NamedSharding(mesh, P('dp'))
    Xd = jax.device_put(jnp.asarray(X), data_sh)
    Yd = jax.device_put(jnp.asarray(Y), data_sh)

    out = {'bench': 'collectives_steps', 'devices': NDEV, 'hidden': hidden,
           'batch': bs, 'iters': iters}
    for comm in ('f32', 'bf16', 'int8'):
        params = {k: jnp.asarray(v) for k, v in
                  _init_mlp(np.random.RandomState(1), 784, hidden,
                            10).items()}
        step, _ = make_dp_step(mesh, params, 0.1, comm)
        params, loss = step(params, Xd, Yd)          # compile
        float(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            params, loss = step(params, Xd, Yd)
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        out[f'steps_per_s_{comm}'] = round(1.0 / dt, 2)
        out[f'final_loss_{comm}'] = float(loss)
    out['int8_vs_f32'] = round(out['steps_per_s_int8']
                               / out['steps_per_s_f32'], 3)
    return out


def measure_convergence(smoke=False):
    """MNIST-recipe final-loss parity: identical data/init, grads synced
    at f32 vs int8 (the EQuARX quality claim, loss-gated)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({'dp': NDEV})
    n, epochs = (512, 4) if smoke else (2048, 3)
    bs = 64
    hidden = 64 if smoke else 128
    rng = np.random.RandomState(0)
    X, Y = _mnist_like(rng, n)
    data_sh = NamedSharding(mesh, P('dp'))

    losses = {}
    for comm in ('f32', 'int8'):
        params = {k: jnp.asarray(v) for k, v in
                  _init_mlp(np.random.RandomState(1), 784, hidden,
                            10).items()}
        step, _ = make_dp_step(mesh, params, 0.1, comm)
        hist = []
        for _ in range(epochs):
            for i in range(0, n - bs + 1, bs):
                xb = jax.device_put(jnp.asarray(X[i:i + bs]), data_sh)
                yb = jax.device_put(jnp.asarray(Y[i:i + bs]), data_sh)
                params, loss = step(params, xb, yb)
                hist.append(float(loss))
        losses[comm] = hist
    f32_final = float(np.mean(losses['f32'][-4:]))
    int8_final = float(np.mean(losses['int8'][-4:]))
    first = float(losses['f32'][0])
    # parity: the quantized run lands within 10% of the f32 run's total
    # loss DECREASE, or within 15% of its final value — the first term
    # gates a converged run tightly, the second keeps a steep early curve
    # (smoke sizes) from flagging sub-step timing noise as divergence
    gap = abs(int8_final - f32_final)
    tol = max(0.1 * (first - f32_final), 0.15 * f32_final, 1e-6)
    return {'bench': 'collectives_convergence', 'steps': len(losses['f32']),
            'first_loss': round(first, 4),
            'final_loss_f32': round(f32_final, 4),
            'final_loss_int8': round(int8_final, 4),
            'final_gap': round(gap, 4), 'tolerance': round(tol, 4),
            'parity': bool(gap <= tol),
            'both_converged': bool(f32_final < 0.5 * first
                                   and int8_final < 0.5 * first)}


def measure_bucketing(smoke=False):
    """Static fleet path: bucket pass on/off bitwise parity + bucket-count
    arithmetic under a small PADDLE_TPU_ALLREDUCE_BUCKET_MB cap."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import ir, layers
    from paddle_tpu.compiler import BuildStrategy, CompiledProgram
    from paddle_tpu.parallel import DistributedStrategy, fleet

    depth = 4 if smoke else 8
    width = 64
    fleet.init()
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[width], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        h = x
        for _ in range(depth):
            h = layers.fc(h, size=width, act='relu')
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.05),
            strategy=DistributedStrategy()).minimize(loss)
    n_ar = len([o for o in main.global_block().ops
                if o.type == 'c_allreduce_sum'])

    rng = np.random.RandomState(0)
    X = rng.randn(16, width).astype('float32')
    Yv = rng.randn(16, 1).astype('float32')

    old = os.environ.get('PADDLE_TPU_ALLREDUCE_BUCKET_MB')
    # cap sized to force >1 bucket: each fc layer grad is width*width*4 B
    os.environ['PADDLE_TPU_ALLREDUCE_BUCKET_MB'] = str(
        2 * width * width * 4 / 2 ** 20)
    try:
        runs = {}
        for tag, on in (('off', False), ('on', True)):
            bs = BuildStrategy()
            bs.fuse_all_reduce_ops = on
            exe = fluid.Executor()
            with fluid.scope_guard(fluid.Scope()):
                exe.run(start)
                cp = CompiledProgram(main, build_strategy=bs)
                runs[tag] = [
                    np.asarray(exe.run(cp, feed={'x': X, 'y': Yv},
                                       fetch_list=[loss])[0])
                    for _ in range(6)]
        bitwise = all(np.array_equal(a, b)
                      for a, b in zip(runs['off'], runs['on']))
        bs = BuildStrategy()
        bs.fuse_all_reduce_ops = True
        opt, ctx = ir.apply_pipeline(main, fetch_names=[loss.name],
                                     build_strategy=bs)
        stats = ctx.stats.get('bucket_allreduce', {})
    finally:
        if old is None:
            os.environ.pop('PADDLE_TPU_ALLREDUCE_BUCKET_MB', None)
        else:
            os.environ['PADDLE_TPU_ALLREDUCE_BUCKET_MB'] = old
    return {'bench': 'collectives_bucketing', 'allreduce_ops': n_ar,
            'buckets': stats.get('buckets', 0),
            'bucketed_ops': stats.get('bucketed_ops', 0),
            'bitwise_identical': bool(bitwise)}


def measure_all(iters=30, smoke=False):
    return {'collectives_bytes': measure_bytes(smoke=smoke),
            'collectives_steps': measure_steps(iters=iters, smoke=smoke),
            'collectives_convergence': measure_convergence(smoke=smoke),
            'collectives_bucketing': measure_bucketing(smoke=smoke)}


# ---------------------------------------------------------------------------
# multi-process mode (real cross-process bundle reduce)
# ---------------------------------------------------------------------------

def _worker(rank, nproc, port, comm):
    import jax
    try:
        # cross-process computations on the CPU backend need the gloo
        # collectives implementation (no-op on jax builds without it)
        jax.config.update('jax_cpu_collectives_implementation', 'gloo')
    except Exception:
        pass
    jax.distributed.initialize(coordinator_address=f'localhost:{port}',
                               num_processes=nproc, process_id=rank)
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.parallel import DataParallel
    from paddle_tpu.dygraph.nn import Linear

    with dygraph.guard():
        model = Linear(16, 4)
        dp = DataParallel(model)
        rngs = [np.random.RandomState(100 + r) for r in range(nproc)]
        grads = {}
        for p in model.parameters():
            per_rank = [r.randn(*np.shape(p.value)).astype('float32')
                        for r in rngs]
            p.grad = jnp.asarray(per_rank[rank])
            grads[id(p)] = np.sum(per_rank, axis=0)
        os.environ['PADDLE_TPU_COMM_DTYPE'] = comm
        t0 = time.perf_counter()
        dp.apply_collective_grads()
        dt = time.perf_counter() - t0
        max_err = max(float(np.abs(np.asarray(p.grad) - grads[id(p)]).max())
                      for p in model.parameters())
        tol = 0.0 if comm == 'f32' else 0.5
        ok = max_err <= tol
    if rank == 0:
        print(json.dumps({'bench': 'collectives_multiproc', 'nproc': nproc,
                          'comm_dtype': comm, 'max_err': max_err,
                          'reduce_seconds': round(dt, 4), 'ok': ok}),
              flush=True)
    sys.exit(0 if ok else 1)


def _spawn_multiproc(nproc, comm):
    with socket.socket() as s:
        s.bind(('localhost', 0))
        port = s.getsockname()[1]
    procs = []
    for r in range(nproc):
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        env.pop('XLA_FLAGS', None)       # one device per process
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), '--worker-rank',
             str(r), '--nproc', str(nproc), '--port', str(port),
             '--comm', comm],
            env=env, cwd=_REPO))
    rc = [p.wait(timeout=300) for p in procs]
    if any(rc):
        raise SystemExit(f'multiproc workers failed: rc={rc}')


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--iters', type=int, default=30)
    ap.add_argument('--smoke', action='store_true',
                    help='tiny shapes / CI smoke sizes')
    ap.add_argument('--nproc', type=int, default=0,
                    help='spawn N jax.distributed processes and verify the '
                         'cross-process bundled reduce instead of the '
                         'single-process sections')
    ap.add_argument('--comm', default='int8',
                    help='comm dtype for --nproc mode')
    ap.add_argument('--worker-rank', type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument('--port', type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.worker_rank is not None:
        _worker(args.worker_rank, args.nproc, args.port, args.comm)
        return
    if args.nproc:
        _spawn_multiproc(args.nproc, args.comm)
        return
    _force_devices()
    for res in measure_all(iters=args.iters, smoke=args.smoke).values():
        print(json.dumps(res), flush=True)


if __name__ == '__main__':
    main()
