"""Seq2seq with the contrib decoder API: teacher-forced training via
TrainingDecoder, inference via BeamSearchDecoder — the reference's
machine-translation recipe (ref: contrib/decoder/beam_search_decoder.py)
on a toy cyclic language.

Run: python examples/train_seq2seq_decoder.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import paddle_tpu as fluid                                    # noqa: E402
from paddle_tpu import contrib                                # noqa: E402

V, D, H, B, T = 8, 6, 16, 16, 5
W = 2          # beam width


def cyclic_batch():
    """Deterministic language: next token = (tok + 1) % V."""
    starts = np.full((B,), 2, 'int64')
    seq = np.stack([(starts + t) % V for t in range(T + 1)], 1)
    return seq[:, :-1], seq[:, 1:]


def gru_ish_updater(c):
    w = c.get_input('w')
    h = c.get_state('h')
    new_h = fluid.layers.fc(
        fluid.layers.concat([w, h], axis=1), H, act='tanh',
        param_attr=fluid.ParamAttr(name='dec_w'), bias_attr=False)
    c.set_state('h', new_h)


def main():
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        src = fluid.data('src', [B, T], 'int64')
        trg = fluid.data('trg', [B, T], 'int64')
        emb = fluid.layers.embedding(
            src, size=[V, D], param_attr=fluid.ParamAttr(name='emb_w'))
        h0 = fluid.layers.fill_constant([B, H], 'float32', 0.0)
        cell = contrib.StateCell(inputs={'w': None},
                                 states={'h': contrib.InitState(init=h0)},
                                 out_state='h')
        cell.state_updater(gru_ish_updater)
        decoder = contrib.TrainingDecoder(cell)
        with decoder.block():
            w = decoder.step_input(emb)
            cell.compute_state(inputs={'w': w})
            cell.update_states()
            decoder.output(cell.get_state('h'))
        hidden = decoder()
        logits = fluid.layers.fc(
            hidden, V, num_flatten_dims=2,
            param_attr=fluid.ParamAttr(name='out_w'),
            bias_attr=fluid.ParamAttr(name='out_b'))
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(
                logits, fluid.layers.unsqueeze(trg, axes=[2])))
        fluid.optimizer.Adam(0.02).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)
    X, Y = cyclic_batch()
    for step in range(120):
        val, = exe.run(main_prog, feed={'src': X, 'trg': Y},
                       fetch_list=[loss])
        if step % 30 == 0 or step == 119:
            print(f'step {step:3d}  loss {float(val):.4f}')

    # --- beam-search inference with the same state updater ---
    infer, infer_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(infer, infer_startup):
        bh0 = fluid.data('bh0', [2, H], 'float32')
        init_ids = fluid.data('bids', [2, 1], 'int64')
        init_scores = fluid.data('bscores', [2, 1], 'float32')
        c2 = contrib.StateCell(inputs={'w': None},
                               states={'h': contrib.InitState(init=bh0)},
                               out_state='h')
        c2.state_updater(gru_ish_updater)
        bsd = contrib.BeamSearchDecoder(
            c2, init_ids, init_scores, target_dict_dim=V, word_dim=D,
            topk_size=V, max_len=T, beam_size=W, end_id=V + 100)
        bsd.decode()
        ids, scores = bsd()
    # the infer startup would re-init the shared 'dec_w' — snapshot the
    # trained value and restore it (the load_params idiom, inlined)
    trained_dec_w = np.asarray(fluid.global_scope().find('dec_w'))
    exe.run(infer_startup)
    fluid.global_scope().set('dec_w', trained_dec_w)
    out_ids, out_scores = exe.run(
        infer, feed={'bh0': np.zeros((2, H), 'float32'),
                     'bids': np.full((2, 1), 2, 'int64'),
                     'bscores': np.zeros((2, 1), 'float32')},
        fetch_list=[ids, scores])
    # (the search shares the trained recurrence; its own embedding/output
    # projection are decode()-built — as in the reference — so this
    # demonstrates the machinery, not a trained translator)
    print('beam 0 decode from token 2:', out_ids[0, 0].tolist(),
          f'(score {float(out_scores[0, 0]):.2f})')


if __name__ == '__main__':
    main()
