"""BERT pretraining with fleet collective data parallelism.

Usage: python examples/train_bert_fleet.py [--steps N]
Uses all local devices as the 'dp' mesh axis (8 virtual CPU devices under
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""
import argparse
import os
import sys

# runnable from anywhere: put the repo root on sys.path
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph.jit import TrainStep
from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                    pretrain_loss)
from paddle_tpu.parallel import fleet
from paddle_tpu.parallel.mesh import data_sharding


def main():
    import jax
    import jax.numpy as jnp
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=10)
    args = ap.parse_args()
    on_tpu = jax.default_backend() != 'cpu'

    fleet.init(mesh_shape={'dp': len(jax.devices())})
    cfg = BertConfig.base() if on_tpu else BertConfig.tiny()
    batch = 64 if on_tpu else 8
    seq = 128 if on_tpu else 32

    with dygraph.guard():
        model = BertForPretraining(cfg)
        opt = fluid.optimizer.Adam(1e-4, parameter_list=model.parameters())
        step = TrainStep(model, pretrain_loss, opt,
                         data_sharding=data_sharding(),
                         amp_dtype=jnp.bfloat16 if on_tpu else None)
        rng = np.random.RandomState(0)
        for i in range(args.steps):
            ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype('int64')
            tt = np.zeros((batch, seq), np.int64)
            mlm = np.where(rng.rand(batch, seq) < 0.15,
                           rng.randint(0, cfg.vocab_size, (batch, seq)),
                           -1).astype(np.int64)
            nsp = rng.randint(0, 2, (batch, 1)).astype(np.int64)
            l = step(ids, tt, mlm, nsp)
            print(f"step {i}: loss {float(l):.4f}", flush=True)


if __name__ == '__main__':
    main()
