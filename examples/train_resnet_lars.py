"""ResNet large-batch training with LARS — the TPU-v3-pod recipe of
"Scale MLPerf-0.6 models on Google TPU-v3 Pods" (arXiv 1909.09756 §2):
LARS with per-layer trust ratios, linear LR warmup into polynomial decay,
weight decay excluded for biases and batch-norm scale/shift, sync-BN over
the data axes, and per-host input sharding when run as a fleet.

Single host::

    python examples/train_resnet_lars.py [--steps N] [--batch B]

As a local test fleet (2 real jax.distributed CPU workers)::

    python examples/train_resnet_lars.py --nproc 2
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
import time

import numpy as np


def is_bn_or_bias(param):
    """The standard LARS exclusion set: biases and norm scale/shift train
    WITHOUT weight decay in their trust-ratio denominators."""
    name = getattr(param, 'name', str(param))
    return any(m in name for m in ('.b_0', 'bias', 'bn', 'batch_norm',
                                   '.w_1', 'scale', 'offset'))


def main():
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import layers as L

    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=30)
    ap.add_argument('--batch', type=int, default=None,
                    help='GLOBAL batch (split across hosts)')
    ap.add_argument('--nproc', type=int, default=0,
                    help='spawn N local jax.distributed CPU workers')
    args = ap.parse_args()

    if args.nproc:
        # re-exec self as a local fleet (fleet_runtime.local_fleet wires
        # PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID / endpoints)
        from paddle_tpu.fleet_runtime import local_fleet
        fl = local_fleet(args.nproc, os.path.abspath(__file__),
                         args=['--steps', args.steps]
                         + (['--batch', args.batch] if args.batch else []))
        rcs = fl.wait()
        sys.exit(max(rc if rc is not None else 1 for rc in rcs))

    from paddle_tpu.fleet_runtime import bootstrap
    bootstrap()                       # no-op single-host; fleet env wires up
    on_tpu = jax.default_backend() != 'cpu'
    hosts = jax.process_count()
    global_batch = args.batch or (256 if on_tpu else 16)
    img = 64 if on_tpu else 16

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = L.data('image', shape=[3, img, img], dtype='float32')
        y = L.data('label', shape=[1], dtype='int64')
        h = L.conv2d(x, num_filters=16, filter_size=3, padding=1)
        # sync-BN: batch statistics reduced over the partitioner's data
        # axes, so per-host stats equal the single-host global-batch stats
        h = L.batch_norm(h, act='relu', sync_stats=True)
        h = L.pool2d(h, pool_size=2, pool_type='max', pool_stride=2)
        h = L.conv2d(h, num_filters=32, filter_size=3, padding=1)
        h = L.batch_norm(h, act='relu', sync_stats=True)
        h = L.pool2d(h, pool_size=2, pool_type='avg',
                     global_pooling=True)
        logits = L.fc(h, size=10)
        loss = L.mean(L.softmax_with_cross_entropy(logits, y))

        # the large-batch schedule: linear warmup into polynomial decay
        base_lr = 0.1 * (global_batch / 256.0)     # linear scaling rule
        lr = L.linear_lr_warmup(
            L.polynomial_decay(base_lr, decay_steps=max(args.steps, 10),
                               end_learning_rate=1e-4, power=2.0),
            warmup_steps=max(args.steps // 10, 2),
            start_lr=0.0, end_lr=base_lr)
        opt = fluid.optimizer.LarsMomentumOptimizer(
            lr, momentum=0.9, lars_coeff=0.001, lars_weight_decay=5e-4,
            exclude_from_weight_decay_fn=is_bn_or_bias)
        from paddle_tpu.parallel import DistributedStrategy, fleet
        fleet.init()
        fleet.distributed_optimizer(opt,
                                    strategy=DistributedStrategy()) \
            .minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)

    blk = main_prog.global_block()
    loader = fluid.DataLoader.from_generator(
        feed_list=[blk.var('image'), blk.var('label')], capacity=4)
    # each host reads only its process_index-strided rows of every batch
    loader.shard_for_fleet()

    def batches():
        rng = np.random.RandomState(0)
        for _ in range(args.steps):
            yield (rng.randn(global_batch, 3, img, img).astype('float32'),
                   rng.randint(0, 10, (global_batch, 1)).astype('int64'))

    loader.set_batch_generator(batches)

    t0, last = time.perf_counter(), None
    n = 0
    for batch in loader():
        last = float(np.asarray(
            exe.run(main_prog, feed=batch, fetch_list=[loss])[0]))
        n += 1
    dt = time.perf_counter() - t0
    if jax.process_index() == 0:
        print(f'host 0/{hosts}: {n} steps, final loss {last:.4f}, '
              f'{n / dt:.2f} steps/s (global batch {global_batch})')


if __name__ == '__main__':
    main()
