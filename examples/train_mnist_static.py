"""MNIST LeNet, static graph (the reference's canonical first script).

Usage: python examples/train_mnist_static.py [--epochs N]
Runs on whatever backend jax selects (TPU chip or CPU)."""
import argparse
import os
import sys

# runnable from anywhere: put the repo root on sys.path
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, nets
from paddle_tpu.datasets import mnist_train


def build():
    img = layers.data('img', [1, 28, 28])
    label = layers.data('label', [1], dtype='int64')
    conv1 = nets.simple_img_conv_pool(img, 20, 5, 2, 2, act='relu')
    conv2 = nets.simple_img_conv_pool(conv1, 50, 5, 2, 2, act='relu')
    pred = layers.fc(conv2, size=10, act='softmax')
    loss = layers.reduce_mean(layers.cross_entropy(pred, label))
    acc = layers.accuracy(pred, label)
    return loss, acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=1)
    ap.add_argument('--batch', type=int, default=128)
    ap.add_argument('--steps', type=int, default=None,
                    help='cap steps per epoch (smoke runs)')
    args = ap.parse_args()

    loss, acc = build()
    fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    from paddle_tpu import reader as R
    train = R.batch(R.shuffle(mnist_train(), 1024), args.batch,
                    drop_last=True)
    for epoch in range(args.epochs):
        for i, batch in enumerate(train()):
            if args.steps and i >= args.steps:
                break
            imgs = np.stack([b[0].reshape(1, 28, 28) for b in batch])
            labels = np.stack([[b[1]] for b in batch]).astype(np.int64)
            l, a = exe.run(feed={'img': imgs, 'label': labels},
                           fetch_list=[loss, acc])
            if i % 50 == 0:
                print(f"epoch {epoch} step {i}: loss "
                      f"{float(np.ravel(l)[0]):.4f} acc "
                      f"{float(np.ravel(a)[0]):.3f}", flush=True)
    print("done")


if __name__ == '__main__':
    main()
