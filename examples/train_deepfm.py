"""DeepFM CTR training over sparse id features — the recsys workload the
Fluid parameter-server half served (ROADMAP item 5, docs/SPARSE.md).

Both embedding tables run ``is_sparse=True``: every step backprops a
rows-only padded-COO gradient (O(nnz·D), bucket-ladder compile-stable)
and the optimizer scatter-applies only the touched rows. Under a fleet,
gradient sync pushes the COO pairs through the quantized sparse
all-gather (int8 rows + per-row f32 scales at ``PADDLE_TPU_COMM_DTYPE=
int8``) instead of all-reducing the dense tables.

Single host::

    python examples/train_deepfm.py [--steps N] [--batch B] [--vocab V]

As a local test fleet (2 real jax.distributed CPU workers, per-host
batch shards + sparse grad push)::

    python examples/train_deepfm.py --nproc 2
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=30)
    ap.add_argument('--batch', type=int, default=64,
                    help='GLOBAL batch (split across hosts)')
    ap.add_argument('--vocab', type=int, default=100_000)
    ap.add_argument('--fields', type=int, default=16)
    ap.add_argument('--dim', type=int, default=16)
    ap.add_argument('--dense', action='store_true',
                    help='legacy dense-gradient tables (A/B baseline)')
    ap.add_argument('--nproc', type=int, default=0,
                    help='spawn N local jax.distributed CPU workers')
    args = ap.parse_args()

    if args.nproc:
        from paddle_tpu.fleet_runtime import local_fleet
        fl = local_fleet(args.nproc, os.path.abspath(__file__),
                         args=['--steps', args.steps, '--batch', args.batch,
                               '--vocab', args.vocab,
                               '--fields', args.fields, '--dim', args.dim]
                         + (['--dense'] if args.dense else []))
        rcs = fl.wait()
        sys.exit(max(rc if rc is not None else 1 for rc in rcs))

    import jax
    from paddle_tpu.fleet_runtime import bootstrap
    bootstrap()                      # no-op single-host; fleet env wires up
    import paddle_tpu as fluid
    import paddle_tpu.dygraph as dygraph
    from paddle_tpu.dygraph.tape import dispatch_op, Tensor
    from paddle_tpu.models.nlp_rec import DeepFM

    hosts = jax.process_count()
    rank = jax.process_index()
    local_batch = args.batch // hosts

    with dygraph.guard():
        from paddle_tpu.core.random import default_generator
        default_generator.seed(2024)    # every host builds the same weights
        model = DeepFM(args.fields, args.vocab, embedding_size=args.dim,
                       is_sparse=not args.dense)
        if hosts > 1:
            from paddle_tpu.dygraph.parallel import DataParallel
            model = DataParallel(model)
        opt = fluid.optimizer.Adagrad(
            0.05, parameter_list=model.parameters())

        rng = np.random.RandomState(7)   # same stream on every host
        t0, last = time.perf_counter(), None
        for step in range(args.steps):
            ids = rng.randint(0, args.vocab,
                              (args.batch, args.fields)).astype(np.int64)
            vals = rng.rand(args.batch, args.fields).astype(np.float32)
            label = (rng.rand(args.batch, 1) < 0.5).astype(np.float32)
            sl = slice(rank * local_batch, (rank + 1) * local_batch)
            logits = model(dygraph.to_variable(ids[sl]),
                           dygraph.to_variable(vals[sl]))
            loss = dispatch_op('reduce_mean', {'x': dispatch_op(
                'sigmoid_cross_entropy_with_logits',
                {'x': logits,
                 'label': Tensor(label[sl], stop_gradient=True)}, {})}, {})
            if hosts > 1:
                loss = model.scale_loss(loss)
            loss.backward()
            if hosts > 1:
                model.apply_collective_grads()   # sparse COO push + bundles
            opt.minimize(loss)
            opt.clear_gradients()
            last = float(loss.numpy()) * (hosts if hosts > 1 else 1)
        dt = time.perf_counter() - t0

    if rank == 0:
        mode = 'dense' if args.dense else 'sparse'
        print(f'host 0/{hosts}: {args.steps} steps ({mode} tables, '
              f'V={args.vocab}), final loss {last:.4f}, '
              f'{args.steps / dt:.2f} steps/s '
              f'(global batch {args.batch})')


if __name__ == '__main__':
    main()
