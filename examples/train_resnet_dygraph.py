"""ResNet-50 dygraph training with the fused TrainStep (the bench path).

Usage: python examples/train_resnet_dygraph.py [--steps N] [--batch B]
Synthetic data; NHWC + bf16 on TPU."""
import argparse
import os
import sys

# runnable from anywhere: put the repo root on sys.path
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
import time

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph.jit import TrainStep
from paddle_tpu.dygraph.tape import dispatch_op
from paddle_tpu.models import ResNet50


def main():
    import jax
    import jax.numpy as jnp
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=20)
    ap.add_argument('--batch', type=int, default=None)
    args = ap.parse_args()
    on_tpu = jax.default_backend() != 'cpu'
    batch = args.batch or (128 if on_tpu else 4)
    img = 224 if on_tpu else 32
    fmt = 'NHWC' if on_tpu else 'NCHW'

    with dygraph.guard():
        model = ResNet50(class_dim=1000, data_format=fmt)
        opt = fluid.optimizer.Momentum(0.1, momentum=0.9,
                                       parameter_list=model.parameters())

        def loss_fn(m, x, y):
            logits = dispatch_op('cast', {'x': m(x)}, {'dtype': 'float32'})
            l, _ = dispatch_op('softmax_with_cross_entropy',
                               {'logits': logits, 'label': y}, {})
            return dispatch_op('reduce_mean', {'x': l}, {})

        step = TrainStep(model, loss_fn, opt,
                         amp_dtype=jnp.bfloat16 if on_tpu else None)
        shape = (batch, img, img, 3) if fmt == 'NHWC' else (batch, 3, img, img)
        x = np.random.randn(*shape).astype(np.float32)
        y = np.random.randint(0, 1000, (batch, 1)).astype(np.int64)
        if on_tpu:
            # keep the synthetic batch device-resident (a real input
            # pipeline overlaps transfers via the DataLoader ring)
            x = jnp.asarray(x, jnp.bfloat16)
        l = step(x, y)                        # compile
        float(l)
        t0 = time.perf_counter()
        for i in range(args.steps):
            l = step(x, y)
        print(f"loss {float(l):.4f}  "
              f"{batch * max(args.steps, 1) / (time.perf_counter() - t0):.1f}"
              f" img/s")


if __name__ == '__main__':
    main()
