"""Model compression walkthrough: train a teacher, distill a smaller
student while pruning it, all through the slim Compressor pipeline.

Run: JAX_PLATFORMS=cpu python examples/compress_distill_prune.py
"""
import os
import sys

# runnable from anywhere: put the repo root on sys.path
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np

os.environ.setdefault('XLA_FLAGS',
                      '--xla_force_host_platform_device_count=8')
import jax  # noqa: E402
if os.environ.get('JAX_PLATFORMS') == 'cpu':
    # axon sessions pin jax_platforms via sitecustomize, overriding the env
    # var — re-pin so JAX_PLATFORMS=cpu really selects the CPU backend
    jax.config.update('jax_platforms', 'cpu')

import paddle_tpu as fluid               # noqa: E402
import paddle_tpu.layers as L            # noqa: E402
from paddle_tpu.contrib import slim      # noqa: E402

BATCH, DIM, CLASSES = 32, 16, 4


def make_batch(rng):
    x = rng.randn(BATCH, DIM).astype('float32')
    y = np.abs(x[:, :CLASSES]).argmax(1)[:, None].astype('int64')
    return x, y


def reader(n, seed):
    rng = np.random.RandomState(seed)

    def r():
        for _ in range(n):
            x, y = make_batch(rng)
            yield {'img': x, 'label': y}
    return r


def build(prefix, width):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data('img', [BATCH, DIM], 'float32')
        y = fluid.data('label', [BATCH, 1], 'int64')
        feat = L.fc(x, size=width, act='relu',
                    param_attr=fluid.ParamAttr(name=prefix + '_w1'))
        logits = L.fc(feat, size=CLASSES,
                      param_attr=fluid.ParamAttr(name=prefix + '_w2'))
        loss = L.reduce_mean(L.softmax_with_cross_entropy(logits, y))
    return prog, startup, feat, logits, loss


def main():
    exe = fluid.Executor(fluid.CPUPlace())

    # 1) teacher: wide net, trained normally
    t_prog, t_start, _, t_logits, t_loss = build('teacher', 64)
    with fluid.program_guard(t_prog, t_start):
        fluid.optimizer.Adam(5e-3).minimize(t_loss)
    exe.run(t_start)
    rng = np.random.RandomState(0)
    for i in range(200):
        x, y = make_batch(rng)
        l, = exe.run(t_prog, feed={'img': x, 'label': y},
                     fetch_list=[t_loss])
    print(f'teacher final loss {float(np.asarray(l)):.4f}')

    # 2) student: half width, distilled + pruned by the Compressor
    s_prog, s_start, _, s_logits, s_loss = build('student', 32)
    exe.run(s_start)
    # soft-label distillation on the logits (same class count either side);
    # the pruning strategy joins at epoch 1 so distillation warms up first
    comp = slim.Compressor(
        place=fluid.CPUPlace(), scope=fluid.global_scope(),
        train_program=slim.GraphWrapper(s_prog,
                                        out_nodes={'loss': s_loss.name}),
        train_reader=reader(30, seed=1),
        teacher_programs=[slim.GraphWrapper(t_prog.clone(for_test=True))],
        distiller_optimizer=fluid.optimizer.Adam(5e-3), epoch=4)
    comp.add_strategy(slim.DistillationStrategy(
        distillers=[slim.SoftLabelDistiller(
            s_logits.name, t_logits.name, teacher_temperature=2.0)],
        start_epoch=0, end_epoch=4))
    comp.add_strategy(slim.UniformPruneStrategy(
        pruner=slim.StructurePruner({'*': 1}, {'*': 'l1_norm'}),
        start_epoch=1, end_epoch=4, target_ratio=0.25,
        params=['student_w1']))
    comp.run()

    w = np.asarray(fluid.global_scope().find('student_w1'))
    pruned_cols = int(np.all(w == 0, axis=0).sum())
    print(f'student trained with distillation; pruned '
          f'{pruned_cols}/{w.shape[1]} filter columns')

    # 3) eval student accuracy on held-out batches
    infer = s_prog.clone(for_test=True)
    rng_ev = np.random.RandomState(9)
    correct = total = 0
    for _ in range(20):
        x, y = make_batch(rng_ev)
        lg, = exe.run(infer, feed={'img': x, 'label': y},
                      fetch_list=[s_logits])
        correct += (np.asarray(lg).argmax(1) == y[:, 0]).sum()
        total += len(y)
    print(f'student accuracy: {correct / total:.3f}')


if __name__ == '__main__':
    main()
