"""Static-graph IR: Program / Block / Operator / Variable.

Parity with reference python/paddle/fluid/framework.py (Program, Block,
Operator, Variable, program_guard, default_main_program) — redesigned for TPU:
the Program is a lightweight op-list IR that the Executor lowers to ONE pure
jax function and jit-compiles (see executor.py). There is no per-op kernel
dispatch at runtime; XLA fuses the entire step. Ops reference registered
functional implementations (ops/registry.py) instead of C++ OpKernels.
"""
from __future__ import annotations

import contextlib
import copy
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from .core import unique_name
from .core.dtypes import convert_dtype
from .core.scope import global_scope

# dummy size substituted for -1 dims during jax.eval_shape-based inference;
# inferred dims equal to it are mapped back to -1 for display.
_DYNAMIC_DIM_SENTINEL = 1999

BACKWARD_OP_TYPE = '__backward__'

# ---------------------------------------------------------------------------
# op construction-site capture (paddle_tpu/analysis/): with PADDLE_TPU_VERIFY
# ≠ off, every Operator records the first non-framework file:line of the
# stack that appended it, so verifier diagnostics and trace-time errors can
# name the model code that built the op instead of an executor internal.
# ---------------------------------------------------------------------------

_PKG_DIR = os.path.dirname(os.path.abspath(__file__)) + os.sep


def _sites_enabled():
    # tolerant read on purpose: analysis.verify_level() owns the strict
    # parse; an unknown value here must not break program construction
    return os.environ.get('PADDLE_TPU_VERIFY', 'off').strip().lower() \
        not in ('', 'off')


def _capture_site():
    """file:line of the nearest stack frame outside paddle_tpu/ — the user
    call that (transitively) appended the op. A plain frame walk, no
    traceback object, so the cost is a few attribute reads per op at
    program BUILD time only."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR) and '<frozen' not in fn:
            return f'{fn}:{f.f_lineno}'
        f = f.f_back
    return None


_dygraph_tracer_ = None  # set by dygraph.base when in imperative mode


def in_dygraph_mode():
    return _dygraph_tracer_ is not None


class Variable:
    """A named tensor in a Block. Mirrors fluid.framework.Variable."""

    def __init__(self, block, name, shape=None, dtype='float32',
                 persistable=False, stop_gradient=False, is_data=False,
                 lod_level=0, trainable=False, **kwargs):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.lod_level = lod_level
        self.trainable = trainable

    # ---- info ----
    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def numel(self):
        n = 1
        for s in self.shape:
            n *= s
        return n

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, persistable={self.persistable})")

    __str__ = __repr__

    def numpy(self):
        """Fetch the current value from the global scope (persistables only)."""
        val = global_scope().find(self.name)
        if val is None:
            raise ValueError(
                f"Variable {self.name} has no value in scope; run the startup "
                f"program or fetch it via Executor.run.")
        return np.asarray(val)

    def set_value(self, value):
        from .core.dtypes import to_jax_dtype, check_int32_bounds
        import jax.numpy as jnp
        if self.dtype == 'int64':
            check_int32_bounds(value, self.name)
        global_scope().set(self.name, jnp.asarray(value, to_jax_dtype(self.dtype)))

    # math ops are monkey-patched in layers/math_op_patch.py


class Parameter(Variable):
    """A trainable persistable Variable. Mirrors fluid.framework.Parameter."""

    def __init__(self, block, name, shape, dtype='float32', trainable=True,
                 regularizer=None, learning_rate=1.0, do_model_average=False,
                 **kwargs):
        super().__init__(block, name, shape=shape, dtype=dtype,
                         persistable=True, stop_gradient=not trainable,
                         trainable=trainable)
        self.regularizer = regularizer
        self.optimize_attr = {'learning_rate': learning_rate}
        self.do_model_average = do_model_average


class Operator:
    """One node of the Program IR.

    Mirrors fluid.framework.Operator, but instead of an OpDesc dispatched to a
    C++ kernel, `type` names a registered jax functional (ops/registry.py);
    inputs/outputs are slot-name → [var names].
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {
            k: ([v] if isinstance(v, str) else list(v))
            for k, v in (inputs or {}).items()}
        self.outputs: Dict[str, List[str]] = {
            k: ([v] if isinstance(v, str) else list(v))
            for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        self._site = _capture_site() if _sites_enabled() else None
        if _DEVICE_GUARD is not None and 'op_device' not in self.attrs:
            self.attrs['op_device'] = _DEVICE_GUARD

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name):
        return self.attrs[name]

    def _set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def has_attr(self, name):
        return name in self.attrs

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"{{{self.type}: {ins} -> {outs}}}"


class Block:
    """A list of ops + dict of vars. Mirrors fluid.framework.Block."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    # ---- vars ----
    def create_var(self, name=None, **kwargs):
        if name is None:
            name = unique_name.generate('_generated_var')
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype, **kwargs):
        p = Parameter(self, name, shape, dtype=dtype, **kwargs)
        self.vars[name] = p
        return p

    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError(f"var {name} not in block {self.idx}")
        return v

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        if name in self.vars:
            return self.vars[name]
        if self.parent_idx >= 0:
            return self.program.block(self.parent_idx)._find_var_recursive(name)
        return None

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # ---- ops ----
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump_version()
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def __repr__(self):
        lines = [f"Block[{self.idx}]"]
        for v in self.vars.values():
            lines.append('  ' + repr(v))
        for op in self.ops:
            lines.append('  ' + repr(op))
        return '\n'.join(lines)


class Program:
    """A sequence of blocks; the unit of compilation & execution.

    Mirrors fluid.framework.Program. `_version` invalidates the Executor's XLA
    compile cache on mutation. `clone(for_test=True)` prunes grad/optimizer ops
    and flips `is_test` attrs, like the reference's Program.clone
    (python/paddle/fluid/framework.py:3971).
    """

    _COUNTER = 0

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._version = 0
        Program._COUNTER += 1
        self._id = Program._COUNTER
        self._seed = None
        self.random_seed = None

    # ---- blocks ----
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent_idx=parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    # ---- queries ----
    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self):
        return [p for b in self.blocks for p in b.all_parameters()]

    def num_ops(self):
        return sum(len(b.ops) for b in self.blocks)

    # ---- transforms ----
    def clone(self, for_test=False):
        p = Program()
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            for name, v in b.vars.items():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[name] = nv
            for op in b.ops:
                if for_test and op.type == BACKWARD_OP_TYPE:
                    break  # drop backward marker and everything after it
                nop = Operator(nb, op.type,
                               {k: list(v) for k, v in op.inputs.items()},
                               {k: list(v) for k, v in op.outputs.items()},
                               copy.deepcopy(op.attrs))
                nop._site = op._site        # clones keep the original site
                if for_test and 'is_test' in nop.attrs:
                    nop.attrs['is_test'] = True
                nb.ops.append(nop)
            p.blocks.append(nb)
        p.current_block_idx = 0
        p.random_seed = self.random_seed
        amp = getattr(self, '_amp_config', None)
        if amp is not None:
            p._amp_config = amp
        if for_test:
            # dropping the backward tail orphans its vars (@GRAD buffers,
            # optimizer temps) — sweep them so eval/inference programs
            # don't carry dead declarations (paddle_tpu/analysis/ flags
            # them; found by the verifier's dead-var check)
            referenced = set()
            for b in p.blocks:
                for op in b.ops:
                    referenced |= set(op.input_names())
                    referenced |= set(op.output_names())
                    for a in ('loss', 'params', 'checkpoints', 'loop_vars',
                              'writes', 'carry', 'slice_names', 'pre_names',
                              'new_names', 'out_names', 'cond_out'):
                        v = op.attrs.get(a)
                        if isinstance(v, str):
                            referenced.add(v)
                        elif isinstance(v, (list, tuple)):
                            referenced.update(
                                x for x in v if isinstance(x, str))
            for b in p.blocks:
                b.vars = {n: v for n, v in b.vars.items()
                          if n in referenced or v.persistable or v.is_data}
        return p

    def _prune(self, targets):
        """Keep only ops needed to compute `targets` (list of Variables/names).

        Used by save_inference_model (ref: python/paddle/fluid/io.py:1099).
        """
        target_names = {t.name if isinstance(t, Variable) else t for t in targets}
        blk = self.global_block()
        needed = set(target_names)
        kept_idx = set()
        for i in range(len(blk.ops) - 1, -1, -1):
            op = blk.ops[i]
            if op.type == BACKWARD_OP_TYPE:
                continue
            if set(op.output_names()) & needed:
                kept_idx.add(i)
                needed |= set(op.input_names())
        p = self.clone()
        nb = p.global_block()
        # clone() preserves op order 1:1, so positional indices identify the
        # kept ops exactly (keying by (type, outputs) aliased reassignments)
        nb.ops = [op for i, op in enumerate(nb.ops) if i in kept_idx]
        # drop vars not referenced
        used = set()
        for op in nb.ops:
            used |= set(op.input_names()) | set(op.output_names())
        used |= target_names
        nb.vars = {k: v for k, v in nb.vars.items() if k in used or v.is_data}
        return p

    def __repr__(self):
        return '\n'.join(repr(b) for b in self.blocks)

    __str__ = __repr__

    def to_string(self, throw_on_error=False, with_details=False):
        return repr(self)


# ---------------------------------------------------------------------------
# default programs & guards (ref: fluid.framework default_main_program etc.)
# ---------------------------------------------------------------------------
_main_program_ = Program()
_startup_program_ = Program()


def default_main_program():
    return _main_program_


def default_startup_program():
    return _startup_program_


def switch_main_program(program):
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program):
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_start = None
    if startup_program is not None:
        old_start = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_start is not None:
            switch_startup_program(old_start)


_global_seed = 0


def manual_seed(seed):
    """Set the global random seed (ref: fluid.Program.random_seed + dygraph seed)."""
    global _global_seed
    _global_seed = int(seed)


def get_global_seed():
    return _global_seed


# ---------------------------------------------------------------------------
# shape inference helpers (jax.eval_shape based — no per-op InferShape code)
# ---------------------------------------------------------------------------

def shape_to_concrete(shape):
    """Replace -1 dims with the sentinel for eval_shape tracing."""
    return tuple(_DYNAMIC_DIM_SENTINEL if s == -1 else s for s in shape)


def shape_from_concrete(shape):
    """Map sentinel-derived dims back to -1 for display parity."""
    return tuple(-1 if s == _DYNAMIC_DIM_SENTINEL else s for s in shape)


# ---------------------------------------------------------------------------
# misc fluid.framework API parity
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def name_scope(prefix=None):
    """ref: fluid.name_scope (framework.py:name_scope). Records a debugging
    scope; the active path is readable via `_current_name_scope()` (op/var
    names in the op-list IR are already unique, so no renaming happens)."""
    if prefix:
        _NAME_SCOPE.append(str(prefix))
    try:
        yield
    finally:
        if prefix:
            _NAME_SCOPE.pop()


_NAME_SCOPE: list = []


def _current_name_scope():
    return '/'.join(_NAME_SCOPE)


@contextlib.contextmanager
def device_guard(device=None):
    """ref: fluid.device_guard (framework.py:device_guard): annotates ops
    appended inside with `op_device`. On TPU this is a placement HINT — the
    compiled step runs on the XLA device; PipelineOptimizer-style program
    splitting uses cut_list, not device annotations — so the attr is
    recorded for program inspection and otherwise inert."""
    global _DEVICE_GUARD
    old = _DEVICE_GUARD
    _DEVICE_GUARD = device
    try:
        yield
    finally:
        _DEVICE_GUARD = old


_DEVICE_GUARD = None


def load_op_library(lib_path):
    """ref: fluid.load_op_library — loads a custom C++ op .so. The TPU
    path for custom ops is ops.registry.register_op (jax functional) or
    layers.py_func; native code plugs in via ctypes like
    paddle_tpu/native. Accepted and ignored with a warning."""
    import warnings
    warnings.warn(
        f"load_op_library({lib_path!r}): CUDA custom-op libraries do not "
        f"apply on TPU; register a jax functional via "
        f"paddle_tpu.ops.registry.register_op or use layers.py_func",
        stacklevel=2)
    return None


def require_version(min_version, max_version=None):
    """ref: fluid.require_version — version gate for scripts."""
    import paddle_tpu

    def parse(v, width):
        parts = [int(x) for x in str(v).split('.') if x.isdigit()]
        return tuple(parts + [0] * (width - len(parts)))

    cur_str = getattr(paddle_tpu, '__version__', '1.7.0')
    width = max(len(str(v).split('.'))
                for v in (cur_str, min_version, max_version or '0'))
    cur = parse(cur_str, width)
    if parse(min_version, width) > cur:
        raise Exception(
            f"installed version {cur_str} is below required {min_version}")
    if max_version is not None and parse(max_version, width) < cur:
        raise Exception(
            f"installed version {cur_str} is above allowed {max_version}")
