"""Distributed lookup-table discovery (ref: python/paddle/fluid/
distribute_lookup_table.py) — real scans over the op-list IR for
`lookup_table` ops marked `is_distributed`. Op inputs/outputs are
slot-name → [var names] (framework.Operator)."""

__all__ = ['find_distributed_lookup_table',
           'find_distributed_lookup_table_inputs',
           'find_distributed_lookup_table_outputs']

LOOKUP_TABLE_TYPE = 'lookup_table'


def _dist_lookup_ops(program):
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and op.attrs.get('is_distributed'):
            yield op


def find_distributed_lookup_table(program):
    """ref :find_distributed_lookup_table — the single distributed table's
    weight name, or None; multiple distinct tables raise (same as ref)."""
    table_name = None
    for op in _dist_lookup_ops(program):
        name = op.inputs['w'][0]
        if table_name is None:
            table_name = name
        elif table_name != name:
            raise RuntimeError('all distributed lookup_table ops must '
                               'share one table')
    return table_name


def find_distributed_lookup_table_inputs(program, table_name):
    """ref :find_distributed_lookup_table_inputs — ids vars feeding the
    distributed table."""
    return [n for op in _dist_lookup_ops(program)
            if op.inputs['w'][0] == table_name
            for n in op.inputs.get('ids', [])]


def find_distributed_lookup_table_outputs(program, table_name):
    """ref :find_distributed_lookup_table_outputs."""
    return [n for op in _dist_lookup_ops(program)
            if op.inputs['w'][0] == table_name
            for n in op.outputs.get('Out', [])]
