"""Device workers (ref: python/paddle/fluid/device_worker.py).

The reference's device workers are C++ per-thread training loops (Hogwild,
DownpourSGD for PS, Section for pipeline). On TPU the training loop is ONE
jitted XLA program, so a device worker reduces to the strategy metadata it
contributes to the TrainerDesc; Executor.train_from_dataset runs the fused
step regardless of worker class.
"""

__all__ = ['DeviceWorker', 'Hogwild', 'DownpourSGD', 'DownpourSGDOPT',
           'Section']


class DeviceWorker:
    """ref device_worker.py:DeviceWorker."""

    def __init__(self):
        self._program = None
        self._infer = None

    def _set_infer(self, infer=False):
        self._infer = infer

    def _set_fleet_desc(self, fleet_desc):
        self._fleet_desc = fleet_desc

    def _set_program(self, program):
        self._program = program

    def _gen_worker_desc(self, trainer_desc):
        raise NotImplementedError(
            "DeviceWorker should not be used directly; use a subclass")


class Hogwild(DeviceWorker):
    """ref device_worker.py:Hogwild — the default dense worker."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.proto_desc['device_worker_name'] = 'HogwildWorker'
        if self._infer:
            trainer_desc.proto_desc.setdefault('hogwild_param', {})[
                'skip_ops'] = ['feed', 'fetch']


class DownpourSGD(DeviceWorker):
    """ref device_worker.py:DownpourSGD — PS sparse/dense pull-push worker.
    On TPU the PS tables lower to collective DP (incubate/fleet PS shims);
    the desc records the worker name for parity."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.proto_desc['device_worker_name'] = 'DownpourWorker'


class DownpourSGDOPT(DownpourSGD):
    """ref device_worker.py:DownpourSGDOPT."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.proto_desc['device_worker_name'] = 'DownpourWorkerOpt'


class Section(DeviceWorker):
    """ref device_worker.py:Section — pipeline-stage worker; the real TPU
    pipeline schedule is parallel/pipeline.py (GPipe over the pp mesh
    axis)."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.proto_desc['device_worker_name'] = 'SectionWorker'
        pipeline_opt = (self._program._pipeline_opt
                        if self._program is not None
                        and hasattr(self._program, '_pipeline_opt') else {})
        trainer_desc.proto_desc['section_param'] = {
            'queue_size': pipeline_opt.get('queue_size', 1),
            'sync_steps': pipeline_opt.get('sync_steps', 1)}
