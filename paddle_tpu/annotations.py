"""ref: python/paddle/fluid/annotations.py — the deprecated-API decorator
(stderr notice once per call site, appended to the docstring)."""
from __future__ import annotations

import functools
import sys

__all__ = ['deprecated']


def deprecated(since, instead, extra_message=''):
    def decorator(func):
        err_msg = (f'API {func.__name__} is deprecated since {since}. '
                   f'Please use {instead} instead.')
        if extra_message:
            err_msg += '\n' + extra_message

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            print(err_msg, file=sys.stderr)  # lint: allow-print (deprecation banner to stderr)
            return func(*args, **kwargs)

        wrapper.__doc__ = (wrapper.__doc__ or '') + '\n    ' + err_msg
        return wrapper

    return decorator
