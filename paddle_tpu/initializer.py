"""Parameter initializers.

Parity with reference python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArray). Dual-mode:
- static graph: append a fill op to the startup program (`__call__(var, block)`)
- direct: compute a jax array (`compute(shape, dtype, key)`) — used by dygraph
  Layers and by the startup lowering.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .core.dtypes import to_jax_dtype
from .core.random import default_generator


class Initializer:
    def __call__(self, var, block):
        """Append an init op for `var` to `block` (startup program)."""
        block.append_op('__init__', inputs={}, outputs={'Out': var.name},
                        attrs={'initializer': self, 'shape': list(var.shape),
                               'dtype': var.dtype})
        return var

    def compute(self, shape, dtype, key=None):
        raise NotImplementedError

    def _key(self, key):
        # a nonzero per-initializer seed pins the stream (ref semantics:
        # seed=0 defers to the global random seed)
        seed = getattr(self, 'seed', 0)
        if seed:
            return jax.random.PRNGKey(seed)
        return key if key is not None else default_generator.next_key()


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def compute(self, shape, dtype, key=None):
        return jnp.full(tuple(shape), self.value, to_jax_dtype(dtype))


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high = low, high
        self.seed = seed

    def compute(self, shape, dtype, key=None):
        return jax.random.uniform(self._key(key), tuple(shape),
                                  to_jax_dtype(dtype), self.low, self.high)


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale = loc, scale
        self.seed = seed

    def compute(self, shape, dtype, key=None):
        return self.loc + self.scale * jax.random.normal(
            self._key(key), tuple(shape), to_jax_dtype(dtype))


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale = loc, scale
        self.seed = seed

    def compute(self, shape, dtype, key=None):
        return self.loc + self.scale * jax.random.truncated_normal(
            self._key(key), -2.0, 2.0, tuple(shape), to_jax_dtype(dtype))


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels OIHW: fan_in = I*k, fan_out = O*k
    receptive = math.prod(shape[2:])
    return shape[1] * receptive, shape[0] * receptive


class XavierInitializer(Initializer):
    """Glorot (ref: initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out = uniform, fan_in, fan_out
        self.seed = seed

    def compute(self, shape, dtype, key=None):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return jax.random.uniform(self._key(key), tuple(shape),
                                      to_jax_dtype(dtype), -limit, limit)
        std = math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(self._key(key), tuple(shape),
                                       to_jax_dtype(dtype))


class MSRAInitializer(Initializer):
    """He/Kaiming (ref: initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in = uniform, fan_in
        self.seed = seed

    def compute(self, shape, dtype, key=None):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return jax.random.uniform(self._key(key), tuple(shape),
                                      to_jax_dtype(dtype), -limit, limit)
        std = math.sqrt(2.0 / fi)
        return std * jax.random.normal(self._key(key), tuple(shape),
                                       to_jax_dtype(dtype))


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose (ref: initializer.py)."""

    def compute(self, shape, dtype, key=None):
        weight = np.zeros(shape, dtype='float32')
        shape = tuple(shape)
        f = math.ceil(shape[3] / 2)
        c = (2 * f - 1 - f % 2) / (2 * f)
        for i in range(np.prod(shape[2:])):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            v = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[..., y, x] = v
        return jnp.asarray(weight, to_jax_dtype(dtype))


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def compute(self, shape, dtype, key=None):
        return jnp.asarray(self.value, to_jax_dtype(dtype)).reshape(tuple(shape))


# reference-compatible aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)


def force_init_on_cpu():
    return False


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    yield
