"""fluid.install_check (ref: python/paddle/fluid/install_check.py) —
`run_check()` trains a tiny linear model forward+backward on the local
device (and, when >1 device is visible, on a data-parallel mesh) to verify
the installation end to end."""
from .debugging import install_check as _install_check

__all__ = ['run_check']


def run_check():
    """ref install_check.py:run_check — raises on failure, prints success."""
    _install_check()
