"""Async parameter-server Communicator (ref: python/paddle/fluid/
communicator.py).

The reference runs C++ send/recv threads against remote pservers. On TPU
pods there are no parameter servers — dense state is sharded/replicated by
GSPMD and synchronized by XLA collectives inside the step — so the
communicator's lifecycle API is preserved while transfer itself is a no-op
(mirrors the PS-mode lowering in incubate/fleet/parameter_server).
"""

__all__ = ['Communicator']


class Communicator:
    def __init__(self, program, mode=None, kwargs=None, envs=None):
        """ref communicator.py — bind to a (transpiled) program."""
        self.program = program
        self.mode = mode
        self.envs = dict(envs or {})
        self._running = False

    def start(self):
        """ref :start — begin async communication (no-op on TPU: XLA
        collectives run in-step; a one-time warning makes the semantics
        change visible to ported async-PS scripts)."""
        from .transpiler import warn_ps_lowering
        warn_ps_lowering(self.mode or 'async')
        self._running = True

    def stop(self):
        """ref :stop."""
        self._running = False

    def is_running(self):
        """ref :is_running."""
        return self._running
