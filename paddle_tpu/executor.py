"""Executor: lowers a Program to ONE pure jax function and runs it jitted.

Parity with reference python/paddle/fluid/executor.py + the C++ executor
(/root/reference/paddle/fluid/framework/executor.cc). The TPU redesign (see
BASELINE.json north star): instead of per-op kernel dispatch, the whole
Program becomes `step(donated_state, kept_state, feeds, key) ->
(new_state, fetches)`, compiled through an XLA compile cache keyed by
(program version, feed shapes) and backed by the persistent cross-process
compilation cache (core/compile_cache.py). Parameter/optimizer-state buffers
are DONATED into the step (XLA updates them in place — no transient 2×
parameter HBM) unless fetch-aliased, buffer-shared, or opted out
(PADDLE_TPU_DONATE=0 / BuildStrategy.enable_inplace=False). Backward
markers lower to jax.value_and_grad; optimizer ops run inside the same fused
step; persistable writes return functionally and are stored back to the Scope.
"""
from __future__ import annotations

import os
from typing import Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from . import observability as _obs
from .core.compile_cache import record_program_cache
from .core.dtypes import to_jax_dtype
from .core.fetch_handle import (FetchHandle, InflightWindow,
                                resolve_inflight_steps)
from .core.places import _get_paddle_place
from .core.scope import global_scope
from .core.random import default_generator
from .framework import (BACKWARD_OP_TYPE, Program, Variable,
                        default_main_program)
from .ops.registry import NON_KERNEL_ATTRS, get_op
from .resilience import watchdog as _watchdog


def _fleet_spmd_mesh():
    """The partitioner's mesh when this is a REAL multi-host run whose
    mesh spans every process — the condition under which the executor
    must lower against GLOBAL arrays (feeds assembled from per-host
    shards, state placed once fleet-wide) so XLA derives the cross-host
    collectives. None single-process (the normal path, zero change)."""
    if jax.process_count() <= 1:
        return None
    from .partition import get_partitioner
    mesh = get_partitioner().mesh
    if mesh is None or mesh.devices.size != jax.device_count():
        return None
    return mesh


def _globalize_state(value, mesh, sharding):
    """Host-local state value (every host holds the identical/full value,
    by seed determinism or by restore) → global jax.Array under
    `sharding`. Already-global arrays — anything whose sharding spans
    the whole mesh, e.g. every warm step's own outputs (which come back
    as GSPMD shardings, not NamedShardings — attribute equality would
    re-place 1× state bytes per step) — pass through untouched."""
    sh = getattr(value, 'sharding', None)
    if sh is not None and len(sh.device_set) == mesh.devices.size:
        return value
    host_val = np.asarray(value)
    return jax.make_array_from_callback(
        host_val.shape, sharding, lambda idx: host_val[idx])


def _globalize_feed(value, mesh, spec):
    """Per-host feed rows → ONE global batch array sharded per `spec`
    (each host contributed its own process_index-strided slice — the
    DataLoader's fleet sharding). Feeds with no batch spec must be
    identical on every host and replicate."""
    from jax.experimental import multihost_utils
    return multihost_utils.host_local_array_to_global_array(
        np.asarray(value), mesh, spec)


class _OpRunner:
    """Executes one IR op given a name→value resolver. Shared by the jit
    lowering and the eager startup path."""

    @staticmethod
    def run(op, read, write, key):
        if op.type in _CONTROL_FLOW_OPS:
            _CONTROL_FLOW_OPS[op.type](op, read, write, key)
            return
        if op.type == '__init__':
            attrs = op.attrs
            out = attrs['initializer'].compute(attrs['shape'], attrs['dtype'],
                                               key=key)
            write(op.outputs['Out'][0], out)
            return
        if op.type == '__constant__':
            write(op.outputs['Out'][0], jnp.asarray(op.attrs['value']))
            return
        opdef = get_op(op.type)
        args = []
        for slot in opdef.input_slots:
            names = op.inputs.get(slot, [])
            if not names:
                args.append(None)
            elif slot in opdef.variadic:
                args.append([read(n) for n in names])
            else:
                args.append(read(names[0]))
        attrs = {k: v for k, v in op.attrs.items()
                 if k not in NON_KERNEL_ATTRS}
        if opdef.needs_rng:
            attrs['key'] = key
        amp = getattr(op.block.program, '_amp_config', None)
        if amp is not None:
            args = _amp_cast_args(op.type, args, amp)
        result = opdef.fn(*args, **attrs)
        if opdef.atomic_output:
            write(op.outputs['Out'][0], result)
            return
        results = [result] if len(opdef.output_slots) == 1 else list(result)
        for slot, res in zip(opdef.output_slots, results):
            names = op.outputs.get(slot, [])
            if not names:
                continue
            res_list = res if isinstance(res, (list, tuple)) else [res]
            if len(names) == 1 and len(res_list) == 1:
                write(names[0], res_list[0])
            else:
                for n, r in zip(names, res_list):
                    write(n, r)


def _amp_cast_args(op_type, args, amp):
    """Static AMP graph rewrite (ref: python/paddle/fluid/contrib/
    mixed_precision/fp16_utils.py:156 rewrite_program): white-list ops
    consume low-precision inputs (MXU dtype), black-list ops are pinned to
    fp32. Casts are inserted at trace time, so the lowered HLO carries them;
    master parameters stay fp32 in the state. jax.vjp differentiates through
    the casts, so grads come back fp32."""
    if op_type in amp['white']:
        target = amp['dtype']
    elif op_type in amp['black']:
        target = jnp.float32
    else:
        return args

    def cast(a):
        if a is None:
            return a
        if isinstance(a, (list, tuple)):
            return [cast(x) for x in a]
        a = jnp.asarray(a)
        if jnp.issubdtype(a.dtype, jnp.floating):
            return a.astype(target)
        return a

    return [cast(a) for a in args]


# ---------------------------------------------------------------------------
# structured control flow: sub-Block ops → XLA control-flow primitives.
# The TPU replacement for the reference's conditional_block/while interpreter
# ops (paddle/fluid/operators/controlflow/) — branches/bodies stay INSIDE the
# one compiled program (lax.cond / lax.while_loop / lax.switch / lax.scan).
# ---------------------------------------------------------------------------


def _run_block(block, read, write, key):
    """Run a sub-Block's ops over a local env chained onto the outer `read`."""
    for i, op in enumerate(block.ops):
        _OpRunner.run(op, read, write,
                      jax.random.fold_in(key, i) if _op_needs_key(op)
                      else None)


def _chained_env(overrides, outer_read):
    local = dict(overrides)

    def read(name):
        if name in local:
            return local[name]
        return outer_read(name)

    return local, read


def _as_bool(x):
    return jnp.reshape(jnp.asarray(x), ()).astype(bool)


def _run_cond(op, read, write, key):
    program = op.block.program
    pred = _as_bool(read(op.inputs['Cond'][0]))
    writes = op.attrs.get('writes', [])

    def branch(blk_idx, out_names):
        blk = program.block(blk_idx)

        def f(_):
            local, read2 = _chained_env({}, read)
            _run_block(blk, read2, local.__setitem__, key)
            # parent-var writes merge out of the branch; an untouched var
            # passes through its outer value so both branches line up
            return tuple(read2(n) for n in list(out_names) + writes)

        return f

    res = jax.lax.cond(pred,
                       branch(op.attrs['true_block'], op.attrs['true_outs']),
                       branch(op.attrs['false_block'], op.attrs['false_outs']),
                       None)
    for n, v in zip(op.outputs['Out'], res):
        write(n, v)


def _run_switch(op, read, write, key):
    program = op.block.program
    idx_val = jnp.reshape(jnp.asarray(read(op.inputs['Index'][0])),
                          ()).astype(jnp.int32)
    keys = op.attrs['keys']
    writes = op.attrs.get('writes', [])
    # map branch_index value → position in blocks list; unmatched → default
    pos = jnp.asarray(len(keys), jnp.int32)  # default branch position
    for i, k in enumerate(keys):
        pos = jnp.where(idx_val == k, jnp.asarray(i, jnp.int32), pos)

    def branch(blk_idx, out_names):
        blk = program.block(blk_idx)

        def f(_):
            local, read2 = _chained_env({}, read)
            _run_block(blk, read2, local.__setitem__, key)
            return tuple(read2(n) for n in list(out_names) + writes)

        return f

    branches = [branch(b, outs) for b, outs in
                zip(op.attrs['blocks'], op.attrs['branch_outs'])]
    res = jax.lax.switch(pos, branches, None)
    for n, v in zip(op.outputs['Out'], res):
        write(n, v)


def _run_while(op, read, write, key):
    program = op.block.program
    carry_names = op.attrs['loop_vars'] + op.attrs.get('writes', [])
    cond_blk = program.block(op.attrs['cond_block'])
    body_blk = program.block(op.attrs['body_block'])
    out_names = op.attrs['body_outs'] + op.attrs.get('writes', [])
    carry0 = (jnp.zeros((), jnp.int32),) + tuple(
        jnp.asarray(read(n)) for n in carry_names)

    def run_blk(blk, it, carry, names):
        local, read2 = _chained_env(dict(zip(carry_names, carry)), read)
        _run_block(blk, read2, local.__setitem__, jax.random.fold_in(key, it))
        return tuple(read2(n) for n in names)

    def cond_fun(c):
        return _as_bool(run_blk(cond_blk, c[0], c[1:],
                                [op.attrs['cond_out']])[0])

    def body_fun(c):
        new = run_blk(body_blk, c[0], c[1:], out_names)
        return (c[0] + 1,) + tuple(
            _check_carry(v, c0, n)
            for v, c0, n in zip(new, c[1:], carry_names))

    max_trips = op.attrs.get('max_trip_count')
    if max_trips is not None:
        # Reverse-differentiable lowering (ref WhileGradOp parity,
        # /root/reference/paddle/fluid/operators/controlflow/while_op.cc:154):
        # XLA's while has no reverse-mode rule, so with a static trip bound
        # the loop becomes a lax.scan of `max_trip_count` masked steps — an
        # inactive step keeps the previous carry via jnp.where (select is
        # differentiable; the dead branch's cotangent is zeroed).
        def scan_step(c, _):
            active = cond_fun(c)
            new = body_fun(c)
            kept = tuple(
                jnp.where(active, nv, cv) for nv, cv in zip(new, c))
            return kept, None
        res, _ = jax.lax.scan(scan_step, carry0, None, length=int(max_trips))
    else:
        res = jax.lax.while_loop(cond_fun, body_fun, carry0)
    for n, v in zip(op.outputs['Out'], res[1:]):
        write(n, v)


def _check_carry(new, init, name):
    """Loop carries must keep shape+dtype; raise instead of silently casting
    (a silent cast floors float updates into int carries)."""
    new = jnp.asarray(new)
    if new.shape != init.shape or new.dtype != init.dtype:
        raise TypeError(
            f"while loop carry '{name}' changed from "
            f"{init.shape}/{init.dtype} to {new.shape}/{new.dtype}; loop "
            f"variables must keep a fixed shape and dtype across iterations")
    return new


def _run_while_legacy(op, read, write, key):
    program = op.block.program
    body_blk = program.block(op.attrs['body_block'])
    carry_names = op.attrs['carry']
    carry0 = (jnp.zeros((), jnp.int32),) + tuple(
        jnp.asarray(read(n)) for n in carry_names)

    def cond_fun(c):
        return _as_bool(c[1])

    def body_fun(c):
        local, read2 = _chained_env(dict(zip(carry_names, c[1:])), read)
        _run_block(body_blk, read2, local.__setitem__,
                   jax.random.fold_in(key, c[0]))
        return (c[0] + 1,) + tuple(
            _check_carry(read2(n), c0, n)
            for n, c0 in zip(carry_names, c[1:]))

    res = jax.lax.while_loop(cond_fun, body_fun, carry0)
    for n, v in zip(carry_names, res[1:]):
        write(n, v)


def _run_scan(op, read, write, key):
    program = op.block.program
    blk = program.block(op.attrs['block'])
    slice_names = op.attrs['slice_names']
    pre_names = op.attrs['pre_names']
    new_names = op.attrs['new_names']
    out_names = op.attrs['out_names']
    xs = tuple(read(n) for n in op.inputs.get('X', []))
    init = tuple(read(n) for n in op.inputs.get('Init', []))

    def scan_fn(carry, x_t):
        it, mems = carry
        overrides = dict(zip(pre_names, mems))
        overrides.update(zip(slice_names, x_t))
        local, read2 = _chained_env(overrides, read)
        _run_block(blk, read2, local.__setitem__, jax.random.fold_in(key, it))
        new_mems = tuple(read2(n) for n in new_names)
        outs = tuple(read2(n) for n in out_names)
        return (it + 1, new_mems), outs

    _, ys = jax.lax.scan(scan_fn, (jnp.zeros((), jnp.int32), init), xs)
    for n, v in zip(op.outputs['Out'], ys):
        write(n, v)


def _run_create_array(op, read, write, key):
    write(op.outputs['Out'][0], [])


_CONTROL_FLOW_OPS = {
    '__create_array__': _run_create_array,
    '__cond__': _run_cond,
    '__switch__': _run_switch,
    '__while__': _run_while,
    '__while_legacy__': _run_while_legacy,
    '__scan__': _run_scan,
}


def _op_needs_key(op):
    """Whether tracing this op must fold a PRNG key. Eagerly folding for
    EVERY op left 3 dead equations (random_wrap/fold_in/unwrap) per non-RNG
    op in the jaxpr — pure trace+compile bloat. Skipping the fold cannot
    change numerics: fold_in(k, salt) depends only on (k, salt), never on
    which other ops folded."""
    t = op.type
    if t in ('__constant__', '__create_array__'):
        return False
    if t in _CONTROL_FLOW_OPS or t == '__init__':
        return True          # sub-blocks may contain RNG consumers
    from .ops.registry import has_op
    return has_op(t) and get_op(t).needs_rng


def _op_read_names(op):
    """All var names an op may read, including reads made by its sub-blocks
    (control-flow branches chain onto the outer env, so their reads are not
    declared in op.inputs) AND reads the control-flow machinery itself
    performs: cond/switch merge their `writes` vars out of every branch,
    reading the OUTER value for a branch that leaves one untouched
    (_run_cond/_run_switch), and while loops seed their carry from the
    outer env (_run_while/_run_while_legacy). Omitting these made DCE drop
    the producer of a cond `writes` var nothing else read — the program
    then died at trace time with a bare KeyError (found by the PR 10
    static verifier; regression: test_program_verifier.py)."""
    names = set(op.input_names())
    for attr in ('writes', 'loop_vars', 'carry'):
        v = op.attrs.get(attr)
        if isinstance(v, (list, tuple)):
            names.update(x for x in v if isinstance(x, str))
    program = op.block.program
    sub_blocks = []
    for attr in ('true_block', 'false_block', 'cond_block', 'body_block',
                 'block'):
        if attr in op.attrs:
            sub_blocks.append(op.attrs[attr])
    sub_blocks.extend(op.attrs.get('blocks', []))
    for bi in sub_blocks:
        for o in program.block(bi).ops:
            names |= _op_read_names(o)
    return names


def _pipeline_plan(program, fwd_ops, marker, feed_names, state_names,
                   fetch_names=(), feed_shapes=None):
    """Static analysis for PipelineOptimizer lowering (ref optimizer.py:3405):
    split the forward at the cut vars into stages + a loss tail. If the
    schedule is 'gpipe' and the stages are isomorphic (same op/attr
    sequence, same param shapes, single chained activation) and the default
    mesh has a matching 'pp' axis, the step runs the real SPMD GPipe
    schedule (partition/pipeline.gpipe); otherwise it lowers to a
    microbatched lax.scan whose gradient structure follows the schedule —
    gpipe numerics via scan-transpose, 1F1B/interleaved via per-microbatch
    (per-wave) backward inside the scan (sched_fwd_grad)."""
    pipe = marker.attrs.get('pipeline')
    if not pipe or not pipe.get('cut_vars'):
        return None
    cut_vars = list(pipe['cut_vars'])
    n_stages = len(cut_vars) + 1
    # knob resolution: env wins over the marker attr (which carries the
    # PipelineOptimizer/DistributedStrategy value) — strict-parse both
    from .partition.pipeline import pp_microbatches, pp_schedule
    schedule = pp_schedule(pipe.get('schedule')) or 'gpipe'
    m_attr = int(pipe.get('num_microbatches') or 0)
    m = pp_microbatches(m_attr if m_attr > 0 else None)
    if m is None:
        # auto (0-sentinel): smallest count whose predicted staged peak
        # fits PADDLE_TPU_HBM_BUDGET_MB — the auto_remat consumption
        # pattern; no budget (or an unplannable cut — the lowering falls
        # back regardless) → one microbatch per stage
        from .ir.auto_remat import hbm_budget_bytes
        budget = hbm_budget_bytes()
        m = n_stages
        if budget is not None:
            from .analysis.stage import solve_microbatches
            try:
                m, _peak, _fits = solve_microbatches(
                    program, cut_vars, schedule, budget,
                    fetch_names=fetch_names, feed_names=feed_names,
                    feed_shapes=feed_shapes)
            except Exception:
                pass
    # microbatch-combine rule for the loss: mean-reduced losses average
    # across microbatches, sum-reduced losses add — anything else cannot be
    # reassembled exactly from per-microbatch values (scan_fwd raises)
    loss_producer = next((o.type for o in reversed(fwd_ops)
                          if marker.attrs['loss'] in o.output_names()), None)
    combine = ('mean' if loss_producer in ('mean', 'reduce_mean')
               else 'sum' if loss_producer in ('reduce_sum', 'sum')
               else None)
    fallback = {'mode': 'scan', 'm': m, 'combine': combine,
                'schedule': schedule, 'n_stages': n_stages}
    if schedule != 'gpipe':
        # 1F1B/interleaved restructure the backward — they always lower
        # through the schedule-structured scan, never the SPMD gpipe mode
        return fallback
    producer = {}
    for i, op in enumerate(fwd_ops):
        for n in op.output_names():
            producer[n] = i
    if any(c not in producer for c in cut_vars):
        return fallback
    bounds = [producer[c] + 1 for c in cut_vars]
    if bounds != sorted(bounds) or len(set(bounds)) != len(bounds):
        return fallback
    stages, prev = [], 0
    for b in bounds:
        stages.append((prev, b))
        prev = b
    tail = (prev, len(fwd_ops))
    param_set = set(marker.attrs['params'])
    state_set = set(state_names)

    def op_sig(op):
        # op_device annotations must not break stage isomorphism — per-stage
        # device_guard is the canonical fluid PipelineOptimizer idiom
        attrs = tuple(sorted((k, repr(v)) for k, v in op.attrs.items()
                             if k not in NON_KERNEL_ATTRS))
        return (op.type, attrs)

    template_sig = [op_sig(o) for o in fwd_ops[stages[0][0]:stages[0][1]]]
    if any([op_sig(o) for o in fwd_ops[lo:hi]] != template_sig
           for lo, hi in stages[1:]):
        return fallback

    def stage_params(lo, hi):
        seen = []
        for op in fwd_ops[lo:hi]:
            for n in op.input_names():
                if n in param_set and n not in seen:
                    seen.append(n)
        return seen

    spn = [stage_params(lo, hi) for lo, hi in stages]
    if any(len(s) != len(spn[0]) for s in spn):
        return fallback
    blk = program.global_block()
    for s in spn[1:]:
        for a, b in zip(spn[0], s):
            if tuple(blk.var(a).shape or ()) != tuple(blk.var(b).shape or ()):
                return fallback

    def external_reads(lo, hi):
        produced, reads = set(), []
        for op in fwd_ops[lo:hi]:
            for n in _op_read_names(op):
                if (n not in produced and n not in param_set
                        and n not in reads):
                    reads.append(n)
            produced |= set(op.output_names())
        return reads

    ext = [external_reads(lo, hi) for lo, hi in stages]
    # stage 0 consumes exactly one feed; stage i consumes only cut i-1; no
    # stage reads mutable state (BN stats etc. would break the template map)
    if (len(ext[0]) != 1 or ext[0][0] not in feed_names
            or any(e != [cut_vars[i - 1]] for i, e in enumerate(ext)
                   if i > 0)
            or any(n in state_set for e in ext for n in e)):
        return fallback
    # gpipe_fwd materializes ONLY the final cut activation (stage-internal
    # vars and earlier cuts live inside the shard_map): the loss tail must
    # read nothing else, and fetches must be reachable — otherwise scan mode
    tail_outs = set()
    for o in fwd_ops[tail[0]:tail[1]]:
        tail_outs |= set(o.output_names())
    reachable = (tail_outs | {cut_vars[-1]} | set(feed_names)
                 | set(state_names))
    if any(f not in reachable for f in fetch_names):
        return fallback
    tail_reads = external_reads(*tail)
    if any(n not in reachable and n not in param_set for n in tail_reads):
        return fallback
    from .parallel.mesh import get_default_mesh
    mesh = get_default_mesh()
    if mesh is None or 'pp' not in mesh.shape or \
            mesh.shape['pp'] != len(stages):
        return fallback
    return {'mode': 'gpipe', 'm': m, 'stages': stages, 'tail': tail,
            'spn': spn, 'x_name': ext[0][0], 'out_name': cut_vars[0],
            'cut_out': cut_vars[-1], 'mesh': mesh,
            'schedule': 'gpipe', 'n_stages': len(stages)}


def _remat_segments(fwd_ops, checkpoints):
    """Split the forward op list at checkpoint-producing ops. Returns a list
    of (lo, hi) index ranges; each range becomes one jax.checkpoint segment
    (RecomputeOptimizer parity, ref python/paddle/fluid/optimizer.py:3705)."""
    ckpt = set(checkpoints)
    bounds = sorted({i + 1 for i, o in enumerate(fwd_ops)
                     if set(o.output_names()) & ckpt})
    segs, prev = [], 0
    for b in bounds:
        if b > prev:
            segs.append((prev, b))
            prev = b
    if prev < len(fwd_ops):
        segs.append((prev, len(fwd_ops)))
    return segs


def _lower(program: Program, feed_names, fetch_names, state_names,
           feed_shapes=None):
    """Build the pure step function for `program`.

    The step takes the training state SPLIT in two dicts so the caller can
    donate the hot one: `step(dstate, kstate, feeds, key)`. `dstate` holds
    parameters/optimizer slots whose HBM XLA may reuse in place
    (jit donate_argnums=(0,)); `kstate` holds state that must survive the
    call — fetch-aliased persistables and anything sharing a buffer with
    another argument. The split is the caller's choice; the lowering only
    sees the union."""
    ops = list(program.global_block().ops)
    bwd_idx = next((i for i, op in enumerate(ops)
                    if op.type == BACKWARD_OP_TYPE), None)
    state_set = frozenset(state_names)

    # ---- static backward-plan analysis (trace-independent) ----
    if bwd_idx is not None:
        marker = ops[bwd_idx]
        loss_name = marker.attrs['loss']
        param_names = marker.attrs['params']
        checkpoints = list(marker.attrs.get('checkpoints') or [])
        fwd_ops = ops[:bwd_idx]
        # rows-only embedding gradients (docs/SPARSE.md): per-site
        # surrogate params expose the per-occurrence cotangents, the
        # post-backward coalesce writes the padded-COO pair the
        # sparse_* update ops consume
        sparse_params = list(marker.attrs.get('sparse_params') or [])
        sparse_sites = [tuple(s) for s in
                        (marker.attrs.get('sparse_sites') or [])]
        sparse_rows_names = dict(zip(sparse_params,
                                     marker.outputs.get('SparseRows', [])))
        sparse_vals_names = dict(zip(sparse_params,
                                     marker.outputs.get('SparseVals', [])))
        pplan = _pipeline_plan(program, fwd_ops, marker, feed_names,
                               state_names, fetch_names, feed_shapes)
        if pplan is not None and sparse_params \
                and pplan['mode'] == 'gpipe':
            # the scan lowerings split the per-site surrogates per
            # microbatch (docs/SPARSE.md); only the SPMD gpipe mode —
            # whose stages live inside a shard_map the surrogate context
            # cannot cross — still rejects the composition
            raise NotImplementedError(
                'sparse embedding gradients are not composable with the '
                'SPMD gpipe pipeline mode; use the scan lowering '
                '(non-isomorphic stages or PADDLE_TPU_PP_SCHEDULE=1f1b) '
                'or set PADDLE_TPU_SPARSE_GRAD=0')
        loss_var_shape = None
        blk0 = program.global_block()
        if blk0.has_var(loss_name):
            shp = blk0.var(loss_name).shape
            if shp is not None and int(np.prod(shp or (1,))) == 1:
                loss_var_shape = tuple(shp)
        if pplan is not None:
            checkpoints = []       # pipeline owns the memory schedule
        segs = (_remat_segments(fwd_ops, checkpoints)
                if checkpoints else [(0, len(fwd_ops))])
        # names each segment boundary must carry forward: reads of later
        # ops + loss/fetches/state-writes. Everything else is dropped at
        # the boundary so jax.checkpoint only saves the live set and
        # remats the rest during the backward pass.
        live_after = []
        downstream = (set().union(*(_op_read_names(o)
                                    for o in ops[bwd_idx + 1:]))
                      if bwd_idx + 1 < len(ops) else set())
        downstream |= {loss_name, *fetch_names, *state_set, *checkpoints}
        # the coalesce after the backward reads every sparse site's ids
        downstream |= {ids_name for _, _, ids_name in sparse_sites}
        for _, hi in segs:
            live = set(downstream)
            for o in fwd_ops[hi:]:
                live |= _op_read_names(o)
            live_after.append(live)
        # state vars written during the forward (BN stats etc.) — the scan
        # fallback threads them through the microbatch loop carry
        written_state = [n for n in state_names
                        if any(n in o.output_names() for o in fwd_ops)]

    def step(dstate, kstate, feeds, base_key):
        state = {**dstate, **kstate}
        env: Dict[str, object] = dict(feeds)

        def make_read(*stores):
            def read(name):
                for s in stores:
                    if name in s:
                        return s[name]
                raise KeyError(
                    f"variable '{name}' has no value: not a feed, not in "
                    f"scope (did you run the startup program?)")
            return read

        def run_seq(op_list, offset, read, write, key=None):
            k = base_key if key is None else key
            for i, op in enumerate(op_list):
                # pass-pipeline-stamped ops carry their pre-rewrite position
                # (ir/pass_base.py): the RNG stream is position-independent,
                # so pass-on and pass-off programs stay bit-identical
                if _op_needs_key(op):
                    salt = op.attrs.get('_rng_salt')
                    kk = jax.random.fold_in(
                        k, offset + i if salt is None else salt)
                else:
                    kk = None
                try:
                    _OpRunner.run(op, read, write, kk)
                except Exception as e:
                    _annotate_trace_error(e, op, offset + i)
                    raise

        def _annotate_trace_error(e, op, pos):
            # trace-time failures name the op and — with construction-site
            # capture on (PADDLE_TPU_VERIFY ≠ off) — the model line that
            # built it, so the error points at user code, not the lowering
            site = getattr(op, '_site', None)
            note = (f"[while lowering op '{op.type}' (op #{pos})"
                    + (f" built at {site}" if site else '') + ']')
            if hasattr(e, 'add_note'):              # Python ≥3.11
                e.add_note(note)
            elif e.args and isinstance(e.args[0], str) \
                    and note not in e.args[0]:
                # 3.10 fallback: fold the note into the message (guarded
                # against double-annotation by nested run_seq frames)
                e.args = (f'{e.args[0]} {note}',) + e.args[1:]

        if bwd_idx is None:
            run_seq(ops, 0, make_read(env, state), env.__setitem__)
        else:
            # diff targets come from state (parameters) or from the feeds
            # (fluid.gradients w.r.t. data inputs, ref backward.py:1672)
            params = {}
            for n in param_names:
                if n in state_set:
                    params[n] = state[n]
                elif n in feeds:
                    params[n] = feeds[n]
                else:
                    raise KeyError(
                        f"gradient target '{n}' is neither a persistable "
                        f"parameter nor a fed variable")
            # one zero (nnz, D) surrogate per sparse lookup site: its
            # gradient is the per-occurrence row cotangent (the table
            # itself stays a constant — no dense V×D scatter ever exists)
            site_vals = {}
            site_keys = [s[0] for s in sparse_sites]
            for site_key, pname, ids_name in sparse_sites:
                if ids_name not in feeds:
                    raise KeyError(
                        f"sparse lookup site {site_key!r}: ids var "
                        f"{ids_name!r} is not fed this run; feed it or set "
                        f"PADDLE_TPU_SPARSE_GRAD=0")
                shp = tuple(feeds[ids_name].shape)
                if len(shp) >= 2 and shp[-1] == 1:
                    shp = shp[:-1]
                nnz = int(np.prod(shp)) if shp else 1
                table = state[pname]
                params[site_key] = jnp.zeros((nnz, int(table.shape[1])),
                                             table.dtype)

            def make_segment(lo, hi):
                def seg(e_in, pvals):
                    e = dict(e_in)
                    run_seq(fwd_ops[lo:hi], lo, make_read(e, pvals, state),
                            e.__setitem__)
                    return e
                return seg

            def plain_fwd(pvals):
                if site_keys:
                    # publish this trace's surrogate tracers for the
                    # lookup kernels (ops/sparse_ops.site_value); the
                    # dict stays bound through the whole value_and_grad
                    # call so checkpointed-segment replays re-read it
                    site_vals.update({k: pvals[k] for k in site_keys})
                e = {k: pvals.get(k, v) for k, v in feeds.items()}
                for (lo, hi), live in zip(segs, live_after):
                    seg = make_segment(lo, hi)
                    if checkpoints:
                        seg = jax.checkpoint(seg)
                    e = seg(e, pvals)
                    if checkpoints:
                        e = {n: v for n, v in e.items() if n in live}
                loss = e[loss_name]
                return jnp.sum(loss), e

            def gpipe_fwd(pvals):
                """Real SPMD GPipe: stage params stacked over 'pp', scan +
                ppermute schedule (partition/pipeline.gpipe), loss tail on
                the reassembled full batch."""
                from .partition.pipeline import gpipe
                e = {k: pvals.get(k, v) for k, v in feeds.items()}
                spn = pplan['spn']

                def getp(n):
                    return pvals[n] if n in pvals else state[n]

                stacked = {t: jnp.stack([getp(s[j]) for s in spn])
                           for j, t in enumerate(spn[0])}
                lo0, hi0 = pplan['stages'][0]
                x = e[pplan['x_name']]
                mm = pplan['m']
                if x.shape[0] % mm != 0:
                    raise ValueError(
                        f"pipeline: batch {x.shape[0]} not divisible by "
                        f"num_microbatches {mm}")
                xm = x.reshape((mm, x.shape[0] // mm) + x.shape[1:])

                def stage_fn(pstage, xs):
                    e2 = {pplan['x_name']: xs}
                    read2 = make_read(e2, pstage, state)
                    # per-stage RNG stream (microbatches within a stage
                    # share one — documented dropout caveat of gpipe mode)
                    ks = jax.random.fold_in(
                        base_key, jax.lax.axis_index('pp') + 1)
                    for i, op in enumerate(fwd_ops[lo0:hi0]):
                        if _op_needs_key(op):
                            salt = op.attrs.get('_rng_salt')
                            kk = jax.random.fold_in(
                                ks, lo0 + i if salt is None else salt)
                        else:
                            kk = None
                        _OpRunner.run(op, read2, e2.__setitem__, kk)
                    return e2[pplan['out_name']]

                ym = gpipe(stage_fn, stacked, xm, mesh=pplan['mesh'])
                e[pplan['cut_out']] = ym.reshape(
                    (ym.shape[0] * ym.shape[1],) + ym.shape[2:])
                tlo, thi = pplan['tail']
                run_seq(fwd_ops[tlo:thi], tlo, make_read(e, pvals, state),
                        e.__setitem__)
                return jnp.sum(e[loss_name]), e

            def micro_split(pvals):
                """Shared scan-mode prologue: batch-major feeds and the
                per-site sparse surrogates split (m, batch/m, ...);
                scalars pass through. Microbatch i's lookup occurrences
                are the contiguous surrogate row block i (ids are
                batch-major, so flatten order is block-contiguous)."""
                mm = pplan['m']
                if pplan['combine'] is None:
                    raise ValueError(
                        "pipeline microbatching requires a mean- or "
                        "sum-reduced scalar loss (loss producer must be "
                        "mean/reduce_mean/reduce_sum); restructure the loss "
                        "or remove cut_list")
                fv = {k: pvals.get(k, v) for k, v in feeds.items()}
                dims = {v.shape[0] for v in fv.values()
                        if getattr(v, 'ndim', 0) >= 1}
                if len(dims) != 1:
                    raise ValueError(
                        f"pipeline microbatching requires all batch-major "
                        f"feeds to share one leading dim; got {sorted(dims)}")
                batch = dims.pop() if dims else 0
                if batch == 0 or batch % mm != 0:
                    raise ValueError(
                        f"pipeline: batch {batch} not divisible by "
                        f"num_microbatches {mm}")
                mb = batch // mm
                split, rest = {}, {}
                for kf, v in fv.items():
                    if getattr(v, 'ndim', 0) >= 1:
                        split[kf] = v.reshape((mm, mb) + v.shape[1:])
                    else:
                        rest[kf] = v
                site_split = {}
                for k in site_keys:
                    v = pvals[k]
                    if v.shape[0] % mm != 0:
                        raise ValueError(
                            f"pipeline+sparse: lookup site {k!r} has "
                            f"{v.shape[0]} id occurrences, not divisible "
                            f"by num_microbatches {mm}")
                    site_split[k] = v.reshape(
                        (mm, v.shape[0] // mm) + v.shape[1:])
                return fv, split, rest, site_split, mb, mm

            def micro_fetch_names():
                # fetches of forward intermediates: collected per microbatch
                # and reassembled after the scan (grad fetches are bound
                # after fwd by the marker, so only fwd-produced names count)
                fwd_produced = {n for o in fwd_ops
                                for n in o.output_names()}
                return [n for n in fetch_names
                        if n in fwd_produced and n not in state_set
                        and n != loss_name]

            def micro_stitch(e, micro_fetch, ys, mm, mb):
                for n, v in zip(micro_fetch, ys):
                    if v.ndim >= 2 and v.shape[1] == mb:
                        # batch-major intermediate: stitch microbatches back
                        e[n] = v.reshape((mm * mb,) + v.shape[2:])
                    else:
                        # per-microbatch scalar/metric: average (exact for
                        # mean-type metrics over equal microbatches)
                        e[n] = jnp.mean(v, axis=0)

            def scan_fwd(pvals):
                """GPipe-numerics fallback: microbatched lax.scan with loss
                (and grad, via autodiff of the scan) accumulation; state
                writes thread through the carry in microbatch order."""
                fv, split, rest, site_split, mb, mm = micro_split(pvals)
                sw0 = {n: state[n] for n in written_state}
                micro_fetch = micro_fetch_names()

                def body(carry, xs):
                    loss_acc, sw = carry
                    mb_idx, xslices, ssl = xs
                    if site_keys:
                        # rebind the site surrogates to this trace's
                        # per-microbatch slices (grads flow back through
                        # the scan transpose into pvals[site])
                        site_vals.update(ssl)
                    e = dict(rest)
                    e.update(xslices)
                    e.update(sw)
                    run_seq(fwd_ops, 0, make_read(e, pvals, state),
                            e.__setitem__,
                            key=jax.random.fold_in(base_key, 7919 + mb_idx))
                    new_sw = {n: e[n] for n in written_state}
                    outs = tuple(jnp.asarray(e[n]) for n in micro_fetch)
                    return (loss_acc + jnp.sum(e[loss_name]), new_sw), outs

                (loss_tot, sw_fin), ys = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), sw0),
                    (jnp.arange(mm), split, site_split))
                loss = loss_tot / mm if pplan['combine'] == 'mean' \
                    else loss_tot
                e = dict(fv)          # all feeds stay fetchable
                e.update(sw_fin)
                e[loss_name] = (jnp.reshape(loss, loss_var_shape)
                                if loss_var_shape is not None else loss)
                micro_stitch(e, micro_fetch, ys, mm, mb)
                return jnp.reshape(loss, ()), e

            def sched_fwd_grad(pvals):
                """Schedule-structured gradients for 1F1B/interleaved: the
                backward runs per microbatch (1F1B) or per wave
                (interleaved) INSIDE the scan, so only one wave of
                residuals is ever live — the staged planner's
                ``host_peak_bytes`` prediction, visible to XLA as a
                smaller temp arena than the gpipe scan-transpose.

                1F1B runs its scan in reverse: jax's scan transpose
                accumulates constant-operand cotangents from the last
                microbatch down, so reverse per-microbatch accumulation
                reproduces the gpipe schedule's float association exactly
                — bitwise grad parity on the same cut. The per-microbatch
                cotangent seed is ``loss_sum / m`` (the same literal
                division the transpose injects). With forward-written
                state (BN stats) the scan must run forward; parity then
                holds to tolerance, not bitwise. Returns ``(env, grads)``
                — the backward is internal, no outer value_and_grad."""
                fv, split, rest, site_split, mb, mm = micro_split(pvals)
                sw0 = {n: state[n] for n in written_state}
                micro_fetch = micro_fetch_names()
                dense = {n: pvals[n] for n in param_names}
                combine = pplan['combine']

                def mb_fwd(pv, sv, xslices, sw, mb_idx):
                    if site_keys:
                        site_vals.update(sv)
                    e = dict(rest)
                    e.update(xslices)
                    e.update(sw)
                    run_seq(fwd_ops, 0, make_read(e, pv, state),
                            e.__setitem__,
                            key=jax.random.fold_in(base_key, 7919 + mb_idx))
                    lsum = jnp.sum(e[loss_name])
                    seed = lsum / mm if combine == 'mean' else lsum
                    new_sw = {n: e[n] for n in written_state}
                    outs = tuple(jnp.asarray(e[n]) for n in micro_fetch)
                    return seed, (lsum, new_sw, outs)

                gacc0 = {n: jnp.zeros_like(v) for n, v in dense.items()}
                if pplan['schedule'] == '1f1b':
                    def body(carry, xs):
                        gacc, sw = carry
                        mb_idx, xslices, ssl = xs
                        (_, (lsum, new_sw, outs)), (gd, gs) = \
                            jax.value_and_grad(
                                mb_fwd, argnums=(0, 1), has_aux=True)(
                                dense, ssl, xslices, sw, mb_idx)
                        gacc = {n: gacc[n] + gd[n] for n in gacc}
                        return (gacc, new_sw), (lsum, outs, gs)

                    (gacc, sw_fin), (lsums, ys, gsite) = jax.lax.scan(
                        body, (gacc0, sw0),
                        (jnp.arange(mm), split, site_split),
                        reverse=not written_state)
                else:                                       # interleaved
                    from .analysis.stage import wave_size
                    w = wave_size('interleaved', pplan['n_stages'], mm)
                    nw = mm // w
                    wsplit = {k: v.reshape((nw, w) + v.shape[1:])
                              for k, v in split.items()}
                    wsite = {k: v.reshape((nw, w) + v.shape[1:])
                             for k, v in site_split.items()}
                    widx = jnp.arange(mm).reshape(nw, w)

                    def wave_fwd(pv, sv, wslices, sw, idxs):
                        def inner(c, ixs):
                            sacc, sw_i = c
                            mb_idx, xsl, ssl = ixs
                            seed, (lsum, new_sw, outs) = mb_fwd(
                                pv, ssl, xsl, sw_i, mb_idx)
                            return (sacc + seed, new_sw), (lsum, outs)

                        (seed_tot, sw_out), (lsums, outs) = jax.lax.scan(
                            inner, (jnp.zeros((), jnp.float32), sw),
                            (idxs, wslices, sv))
                        return seed_tot, (lsums, sw_out, outs)

                    def body(carry, xs):
                        gacc, sw = carry
                        idxs, wslices, wsl = xs
                        (_, (lsums, sw_out, outs)), (gd, gs) = \
                            jax.value_and_grad(
                                wave_fwd, argnums=(0, 1), has_aux=True)(
                                dense, wsl, wslices, sw, idxs)
                        gacc = {n: gacc[n] + gd[n] for n in gacc}
                        return (gacc, sw_out), (lsums, outs, gs)

                    (gacc, sw_fin), (lsums, ys, gsite) = jax.lax.scan(
                        body, (gacc0, sw0), (widx, wsplit, wsite))
                    lsums = lsums.reshape((mm,))
                    ys = tuple(v.reshape((mm,) + v.shape[2:]) for v in ys)
                    gsite = {k: v.reshape((mm,) + v.shape[2:])
                             for k, v in gsite.items()}
                # loss assembled in FORWARD microbatch order — the same
                # float association as scan_fwd's carry accumulation
                loss_acc = jnp.zeros((), jnp.float32)
                for i in range(mm):
                    loss_acc = loss_acc + lsums[i]
                loss = loss_acc / mm if combine == 'mean' else loss_acc
                e = dict(fv)
                e.update(sw_fin)
                e[loss_name] = (jnp.reshape(loss, loss_var_shape)
                                if loss_var_shape is not None else loss)
                micro_stitch(e, micro_fetch, ys, mm, mb)
                grads = dict(gacc)
                for k in site_keys:
                    g = gsite[k]
                    grads[k] = g.reshape((-1,) + g.shape[2:])
                return e, grads

            from .ops import sparse_ops as _sp
            if pplan is not None and pplan['mode'] == 'scan' \
                    and pplan['schedule'] != 'gpipe':
                # 1F1B/interleaved own their backward (per-microbatch /
                # per-wave value_and_grad inside the scan)
                with _sp.site_context(site_vals):
                    env, grads = sched_fwd_grad(params)
            else:
                if pplan is None:
                    fwd = plain_fwd
                elif pplan['mode'] == 'gpipe':
                    fwd = gpipe_fwd
                else:
                    fwd = scan_fwd
                with _sp.site_context(site_vals):
                    (_, env), grads = jax.value_and_grad(
                        fwd, has_aux=True)(params)
            for n, gname in zip(param_names, marker.outputs['Grads']):
                env[gname] = grads[n]
            if sparse_sites:
                # coalesce per-occurrence cotangents into the padded-COO
                # pair (@GRAD@ROWS/@GRAD@VALS) the sparse_* updates read
                per_param = {}
                for site_key, pname, ids_name in sparse_sites:
                    per_param.setdefault(pname, []).append(
                        (site_key, ids_name))
                for pname, psites in per_param.items():
                    table = state[pname]
                    dim = int(table.shape[1])
                    ids_cat = jnp.concatenate(
                        [_sp.flatten_ids(feeds[i]) for _, i in psites])
                    vals_cat = jnp.concatenate(
                        [grads[k].reshape(-1, dim) for k, _ in psites])
                    rows, vals = _sp.coalesce_rows(ids_cat, vals_cat,
                                                   int(table.shape[0]))
                    env[sparse_rows_names[pname]] = rows
                    env[sparse_vals_names[pname]] = vals
            run_seq(ops[bwd_idx + 1:], bwd_idx + 1,
                    make_read(env, state), env.__setitem__)

        # ALL state passes through (donated inputs alias unwritten outputs —
        # otherwise the scope would keep handles to donated buffers)
        new_state = {n: env.get(n, state[n]) for n in state_set}
        read = make_read(env, state)
        fetches = [read(n) for n in fetch_names]
        return new_state, fetches

    return step


def _dataset_logger():
    """INFO logger for *_from_dataset fetch reporting (repo invariant:
    framework code never print()s — tools/lint_codebase.py enforces it)."""
    import logging
    from .log_helper import get_logger
    return get_logger(__name__, logging.INFO, fmt='%(message)s')


def _default_len_feeds(block, feed_vals):
    """Plain-array feeds to lod_level>0 vars: the companion '@LEN' var
    defaults to full lengths (every row spans the padded time dim) so
    non-ragged feeds keep the pre-LoDTensor semantics."""
    for name in list(feed_vals):
        ln = name + '@LEN'
        if (not name.endswith('@LEN') and ln not in feed_vals
                and block.has_var(ln) and block.var(ln).is_data):
            arr = feed_vals[name]
            if getattr(arr, 'ndim', 0) >= 2:
                feed_vals[ln] = jnp.full((arr.shape[0],), arr.shape[1],
                                         jnp.int32)


class Executor:
    """fluid.Executor parity. `place` is accepted for compat; execution always
    targets the default XLA backend."""

    def __init__(self, place=None):
        self.place = _get_paddle_place(place)
        self._cache = {}
        self._step_counter = 0
        self._partition_placed = set()
        self._lookup_meta_cache = {}
        # async pipeline bookkeeping: dispatched steps whose FetchHandles
        # are still pending (K-in-flight window + donation protection)
        self._window = InflightWindow()
        # persistent cross-process XLA compile cache underneath the
        # in-process program+shape jit cache (core/compile_cache.py)
        from .core.compile_cache import setup_persistent_cache
        setup_persistent_cache()

    # ------------------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True, feed_var_name='feed',
            fetch_var_name='fetch'):
        """Run `program` once. Fetch results come back three ways:

        - default (synchronous): numpy arrays, one blocking D2H per fetch —
          the exact pre-pipeline behavior (`PADDLE_TPU_ASYNC=0` pins this);
        - `return_numpy=False`: :class:`FetchHandle` s backed by on-device
          arrays — `np.asarray(handle)` materializes on read, with snapshot
          semantics (later steps cannot donate-over a pending handle);
        - async mode (`PADDLE_TPU_ASYNC=1`/`K`, or
          `ExecutionStrategy.num_inflight_steps > 1` on a CompiledProgram):
          always returns FetchHandles and keeps up to K dispatched steps
          outstanding, blocking on the oldest handle only when the window
          is full — host feed prep and dispatch of step N+1 overlap device
          execution of step N (PERF.md §12, tools/bench_pipeline.py).
        """
        # hang watchdog (resilience/watchdog.py, PADDLE_TPU_WATCHDOG): a
        # wedged device step breaches the 'executor_step' lease — deadline
        # tracks this executor's own rolling-median run time (the first,
        # compiling run gets the larger cold deadline). Free when no
        # process watchdog is armed.
        lease = _watchdog.arm_step('executor_step')
        try:
            if not _obs._ENABLED:
                return self._run_impl(program, feed, fetch_list, scope,
                                      return_numpy)
            # telemetry on: every run is one span tree — prepare / lower /
            # execute / fetch phases nest under executor/run (trace.json),
            # the phase durations + donation/byte counts land in the metrics
            # registry and one steps.jsonl record (docs/OBSERVABILITY.md)
            with _obs.span('executor/run', step=self._step_counter + 1):
                return self._run_impl(program, feed, fetch_list, scope,
                                      return_numpy)
        finally:
            _watchdog.disarm(lease)

    def _lookup_feed_meta(self, program):
        """Per-program map of embedding lookups fed directly from data
        vars: [(ids_name, vocab, table_name, is_sparse_site)]. Cached per
        (program id, version) — one op scan, not one per run."""
        key = (program._id, program._version)
        meta = self._lookup_meta_cache.get(key)
        if meta is None:
            meta = []
            blk = program.global_block()
            for op in blk.ops:
                if op.type != 'lookup_table':
                    continue
                ids = (op.inputs.get('ids') or [None])[0]
                w = (op.inputs.get('w') or [None])[0]
                if not (ids and w and blk.has_var(ids) and blk.has_var(w)
                        and getattr(blk.var(ids), 'is_data', False)):
                    continue
                shape = blk.var(w).shape or ()
                if not shape or not isinstance(shape[0], int) \
                        or shape[0] <= 0:
                    continue
                meta.append((ids, int(shape[0]), w,
                             op.attrs.get('_sparse_site') is not None))
            self._lookup_meta_cache[key] = meta
        return meta

    def _embedding_feed_checks(self, program, block, feed):
        """Two per-run hooks over embedding-id feeds (docs/SPARSE.md):

        - ``PADDLE_TPU_VERIFY=full`` + ``PADDLE_TPU_EMBED_OOB=error``:
          host-side dtype/range validation — an out-of-range id would
          silently clip to row V-1 on device and train the wrong row.
          ``PADDLE_TPU_EMBED_OOB=clip`` is the legacy escape hatch.
        - always-on ``sparse_*`` metrics for rows-only-gradient tables
          (host-resident feeds only; staged device arrays are counted at
          coalesce by their bucket instead of forcing a D2H sync).
        """
        meta = self._lookup_feed_meta(program)
        if not meta:
            return
        from .core.lod import LoDTensor
        from . import analysis
        from .ops import sparse_ops as _sp
        check_range = analysis.verify_level() == 'full' \
            and _sp.oob_policy() == 'error'
        for ids_name, vocab, table, is_sparse_site in meta:
            value = feed.get(ids_name)
            if value is None:
                continue
            if isinstance(value, LoDTensor):
                value = value.data
            if isinstance(value, jax.Array):
                continue      # staged feed: no host copy without a sync
            arr = np.asarray(value)
            if check_range:
                if not np.issubdtype(arr.dtype, np.integer):
                    raise ValueError(
                        f"feed {ids_name!r} indexes embedding table "
                        f"{table!r} but has dtype {arr.dtype} (expected "
                        f"an integer id dtype)")
                if arr.size and (arr.min() < 0 or arr.max() >= vocab):
                    raise ValueError(
                        f"feed {ids_name!r} holds ids outside [0, {vocab}) "
                        f"for embedding table {table!r} (min {arr.min()}, "
                        f"max {arr.max()}); on device they would silently "
                        f"clip to row {vocab - 1} and train the wrong row. "
                        f"Set PADDLE_TPU_EMBED_OOB=clip for the legacy "
                        f"clipping behavior.")
            if is_sparse_site and arr.size:
                _sp.record_sparse_lookup(
                    arr.size, _sp.nnz_bucket(arr.size),
                    dedup_rows=int(np.unique(arr).size), table=table)

    def _run_impl(self, program, feed, fetch_list, scope, return_numpy):
        from .compiler import CompiledProgram
        sharding = None
        build_strategy = None
        exec_strategy = None
        donate = os.environ.get('PADDLE_TPU_DONATE', '1') != '0'
        if isinstance(program, CompiledProgram):
            sharding = program._data_sharding
            bs = build_strategy = program._build_strategy
            exec_strategy = program._exec_strategy
            # fluid memory knobs map onto donation: enable_inplace=False or
            # memory_optimize=False opts the whole program out of buffer reuse
            if bs is not None and (bs.enable_inplace is False
                                   or bs.memory_optimize is False):
                donate = False
            program = program._program
        # K > 0: pipelined loop with up to K dispatched steps outstanding.
        # Pipelining turns donation OFF for the dispatched steps: donating a
        # buffer that is still being produced by the PREVIOUS in-flight step
        # makes the runtime block the dispatch until the producer finishes
        # (measured: the whole overlap win disappears on the CPU PJRT
        # client), and K-deep double buffering fundamentally needs the old
        # and new state live at once. The cost is the classic double-buffer
        # transient (2× pipelined-state HBM) — PERF.md §12.
        inflight_k = resolve_inflight_steps(exec_strategy)
        use_handles = bool(inflight_k) or not return_numpy
        if inflight_k:
            donate = False
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in fetch_list]

        block = program.global_block()
        if any(op.type == '__init__' for op in block.ops):
            with _obs.span('executor/startup'):
                self._run_startup(program, scope)
            return []

        prep_span = _obs.span('executor/prepare')
        prep_span.__enter__()
        # persistable vars = training state
        state_names = sorted(v.name for v in program.list_vars()
                             if v.persistable)
        # partitioned state placement (paddle_tpu/partition): programs a
        # fleet strategy stamped (`_fsdp_axis` legacy pure-fsdp, or
        # `_partition_params` full rule-table resolution — tp Megatron
        # specs + fsdp tiles on one mesh) get their persistables
        # device_put with the partitioner-resolved NamedShardings, the
        # pjit-style in_shardings of the jitted step. Place once per
        # (program, scope): step outputs keep the sharding, so
        # re-placing every run would only add host-side dispatch cost.
        # program._id is a never-recycled counter (unlike id())
        spec_fn = None
        part_key = (program._id, id(scope))
        if part_key not in self._partition_placed:
            from .partition import state_spec_fn
            spec_fn = state_spec_fn(program)
            if spec_fn is not None:
                self._partition_placed.add(part_key)
        # multi-host fleet (fleet_runtime/): state must live as GLOBAL
        # arrays on the process-spanning mesh — partitioner-resolved
        # shardings (fsdp tiles, tp tiles) or replicated — so the jitted
        # step is one SPMD program over all hosts and XLA emits the
        # cross-host gradient reduction the c_allreduce sync points
        # describe. The guard per value is one attribute check; already-
        # global step outputs pass straight through on warm steps.
        fleet_mesh = _fleet_spmd_mesh()
        fleet_spec_fn = None
        if fleet_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from .partition import state_spec_fn as _state_spec_fn
            fleet_spec_fn = _state_spec_fn(program) or (
                lambda n, s: NamedSharding(fleet_mesh, PartitionSpec()))
        state = {}
        for n in state_names:
            val = scope.find(n)
            if val is None:
                raise RuntimeError(
                    f"persistable var '{n}' is uninitialized; run the startup "
                    f"program first (exe.run(fluid.default_startup_program()))")
            if fleet_mesh is not None and hasattr(val, 'shape'):
                val = _globalize_state(val, fleet_mesh,
                                       fleet_spec_fn(n, val.shape))
            elif spec_fn is not None and hasattr(val, 'shape'):
                val = jax.device_put(val, spec_fn(n, val.shape))
            state[n] = val

        from .core.lod import LoDTensor
        feed_vals = {}
        passthrough_bytes = 0
        if fleet_mesh is not None:
            # fleet feeds: every host contributes its local rows, the
            # step consumes ONE global batch (docs/DISTRIBUTED.md). Data
            # vars shard their leading dim over the partitioner's data
            # axes; everything else must be host-identical and
            # replicates. LoD feeds have no row-aligned global form.
            from .partition import get_partitioner
            from jax.sharding import PartitionSpec
            part = get_partitioner()
            data_spec = part.data_spec()
            for name, value in feed.items():
                if isinstance(value, LoDTensor):
                    raise NotImplementedError(
                        f'feed {name!r}: LoDTensor feeds are not '
                        f'supported on a multi-host fleet (shard the '
                        f'reader and pad to dense)')
                dtype = block.var(name).dtype if block.has_var(name) \
                    else None
                if dtype == 'int64':
                    from .core.dtypes import check_int32_bounds
                    check_int32_bounds(np.asarray(value), name)
                target = to_jax_dtype(dtype) if dtype else None
                host_val = np.asarray(value)
                if target is not None:
                    host_val = host_val.astype(target, copy=False)
                is_data = block.has_var(name) and \
                    getattr(block.var(name), 'is_data', False)
                spec = (data_spec if is_data and host_val.ndim
                        else PartitionSpec())
                feed_vals[name] = _globalize_feed(host_val, fleet_mesh,
                                                  spec)
        else:
            for name, value in feed.items():
                if isinstance(value, LoDTensor):
                    # ragged feed: bind the padded data plus the companion
                    # length var that data(lod_level>0) declared
                    if block.has_var(name + '@LEN'):
                        from .core.dtypes import check_int32_bounds
                        feed_vals[name + '@LEN'] = jnp.asarray(
                            check_int32_bounds(value.lengths, name + '@LEN'))
                    value = value.data
                dtype = block.var(name).dtype if block.has_var(name) \
                    else None
                target = to_jax_dtype(dtype) if dtype else None
                if (isinstance(value, jax.Array)
                        and not isinstance(value, jax.core.Tracer)
                        and (target is None or value.dtype == target)
                        and (sharding is None
                             or value.sharding == sharding)):
                    # zero-copy staged feed: the DataLoader producer thread
                    # already committed this batch to the device (reader.py
                    # device_put) — and ran the int64 bounds check
                    # host-side at staging — so re-converting here would
                    # only put H2D (and, for int64, a device→host bounds
                    # scan = a full sync) back on the critical path
                    passthrough_bytes += getattr(value, 'nbytes', 0)
                    feed_vals[name] = value
                    continue
                if dtype == 'int64':
                    # int64 computes as int32 on device (core/dtypes.py); a
                    # feed that would wrap must fail loudly, not silently
                    from .core.dtypes import check_int32_bounds
                    check_int32_bounds(value, name)
                arr = jnp.asarray(value, target)
                if sharding is not None:
                    arr = jax.device_put(arr, sharding)
                feed_vals[name] = arr
        if _obs._ENABLED and passthrough_bytes:
            _obs.inc('executor_feed_passthrough_bytes', passthrough_bytes,
                     help='feed bytes recognized as already device-committed '
                          'and passed through without a second device_put')
        _default_len_feeds(block, feed_vals)
        self._embedding_feed_checks(program, block, feed)
        prep_span.__exit__(None, None, None)

        from . import ir
        feed_sig = tuple(sorted((n, v.shape, str(v.dtype))
                                for n, v in feed_vals.items()))
        # the pp knobs restructure the lowering (schedule/microbatch
        # count), so a knob flip must re-lower, not hit the cache
        key = (id(program), program._version, feed_sig, tuple(fetch_names),
               tuple(state_names), donate,
               ir.pipeline_signature(build_strategy),
               os.environ.get('PADDLE_TPU_PP_SCHEDULE', ''),
               os.environ.get('PADDLE_TPU_PP_MICROBATCHES', ''))
        fn = self._cache.get(key)
        compiled_now = fn is None
        record_program_cache(hit=not compiled_now)
        lower_span = _obs.span('executor/lower', program=program._id)
        if fn is None:
            with lower_span:
                # pre-lowering validation (PADDLE_TPU_VERIFY=full): the
                # static verifier rejects malformed programs HERE, with the
                # op and its Python construction site, instead of deep in
                # the XLA trace. Runs per compile-cache miss, never per step.
                from . import analysis
                if analysis.verify_level() == 'full':
                    analysis.assert_verified(
                        program, fetch_names=fetch_names,
                        feed_names=list(feed_vals), stage='pre-lower')
                # program-level IR passes rewrite a CLONE before the trace
                # (op fusion / DCE / constant folding — paddle_tpu/ir/);
                # their runtime lands inside executor/lower and therefore in
                # executor_compile_seconds, same as the trace they shrink
                opt_program, _ = ir.apply_pipeline(
                    program, fetch_names=fetch_names,
                    feed_names=list(feed_vals),
                    build_strategy=build_strategy,
                    feed_shapes={n: tuple(v.shape) for n, v in
                                 feed_vals.items()
                                 if hasattr(v, 'shape')})
                # static memory plan (paddle_tpu/analysis/plan.py): peak
                # HBM predicted from the VarInfos before the trace runs —
                # milliseconds, zero tracing, once per compile-cache miss
                self._plan_telemetry(opt_program, fetch_names, feed_vals,
                                     donate)
                step = _lower(opt_program, list(feed_vals), fetch_names,
                              state_names,
                              feed_shapes={n: tuple(v.shape)
                                           for n, v in feed_vals.items()
                                           if hasattr(v, 'shape')})
                fn = jax.jit(step, donate_argnums=(0,))
            self._cache[key] = fn

        # Donation guards: a fetch-aliased persistable must survive the call
        # (the caller observes its pre-step buffer), and a buffer shared
        # between two state names — or with a feed — may be donated at most
        # once. A persistable fetched by a still-PENDING FetchHandle from an
        # earlier async step is protected too: donating it would overwrite
        # the handle's snapshot in place. Everything else (params, optimizer
        # slots, BN stats) is donated so XLA updates it in place instead of
        # doubling live HBM.
        fetch_set = frozenset(fetch_names)
        pending_protected = self._window.protected_names()
        seen_ids = {id(v) for v in feed_vals.values()}
        dstate, kstate = {}, {}
        for n in state_names:
            v = state[n]
            if (donate and n not in fetch_set and n not in pending_protected
                    and id(v) not in seen_ids):
                dstate[n] = v
                seen_ids.add(id(v))
            else:
                kstate[n] = v

        self._step_counter += 1
        base_key = jax.random.fold_in(default_generator.base_key(),
                                      self._step_counter)
        from .debugging import check_nan_inf_enabled
        check_nan = check_nan_inf_enabled() and bool(fetch_names)
        if inflight_k:
            # bounded in-flight window: block on the OLDEST dispatched
            # step only when K are already outstanding, so this step's
            # dispatch (and the next step's host feed prep) overlap the
            # device executing steps N..N-K+1
            self._window.admit(inflight_k)
        # execute = host-side dispatch of the jitted step (on a cache miss
        # this includes trace + XLA compile); fetch = scope write-back plus
        # the device→host transfer that synchronizes with the computation
        exec_span = _obs.span('executor/execute', compile=compiled_now)
        try:
            with exec_span:
                new_state, fetches = fn(dstate, kstate, feed_vals, base_key)
        except FloatingPointError:
            # jax_debug_nans (enable_check_nan_inf) raised inside the step:
            # record the detection so a NaN storm is a telemetry series,
            # not only the first traceback
            _obs.inc('nonfinite_detections', 1,
                     help='fetched variables containing NaN/Inf '
                          '(FLAGS_check_nan_inf)')
            _obs.instant('nonfinite_detected', source='jax_debug_nans')
            raise
        fetch_span = _obs.span('executor/fetch')
        with fetch_span:
            for n, v in new_state.items():
                scope.set(n, v)
            if use_handles:
                # non-blocking fetches: hand back FetchHandles over the
                # still-on-device arrays; np.asarray(handle) is the sync
                # point. The window entry records which persistables the
                # handles alias so later donation can't corrupt them, and
                # (with FLAGS_check_nan_inf) the non-finite scan moves to
                # materialization time instead of re-serializing the loop.
                result = [FetchHandle(f, name=n, check_nan=check_nan)
                          for n, f in zip(fetch_names, fetches)]
                self._window.push(result,
                                  protected=fetch_set & frozenset(state_names))
            else:
                result = [np.asarray(f) for f in fetches]

        if check_nan and not use_handles:
            # FLAGS_check_nan_inf parity on the fused step: scan the fetched
            # host values; detections land in telemetry (counter + instant
            # trace marker) BEFORE the raise so a NaN storm is visible in
            # the artifacts, not only in the first traceback
            with _obs.span('executor/check_nan_inf'):
                self._check_fetches_finite(fetch_names, fetches)

        if _obs._ENABLED:
            _obs.inc('executor_steps',
                     help='completed Executor.run training/eval steps')
            _obs.inc('executor_donated_buffers', len(dstate),
                     help='state buffers donated into the step (in-place '
                          'XLA update)')
            _obs.inc('executor_kept_buffers', len(kstate),
                     help='state buffers excluded from donation '
                          '(fetch-aliased or buffer-shared)')
            feed_bytes = sum(getattr(v, 'nbytes', 0)
                             for v in feed_vals.values())
            fetch_bytes = sum(getattr(f, 'nbytes', 0) for f in result)
            _obs.inc('executor_feed_bytes', feed_bytes,
                     help='bytes fed into Executor.run')
            _obs.inc('executor_fetch_bytes', fetch_bytes,
                     help='bytes fetched out of Executor.run')
            # measured counterpart of program_plan_accounted_bytes: the
            # same state+feed+fetch accounting from the LIVE buffers
            state_bytes = sum(getattr(v, 'nbytes', 0)
                              for v in new_state.values())
            _obs.set_gauge('program_measured_hbm_bytes',
                           state_bytes + feed_bytes + fetch_bytes,
                           help='measured state+feed+fetch bytes of the '
                                'last step (predicted-vs-measured delta '
                                'in tools/telemetry_report.py)')
            if compiled_now:
                _obs.observe(
                    'executor_compile_seconds',
                    lower_span.duration + exec_span.duration,
                    help='lower + first-execution (trace/XLA-compile) time '
                         'per program+shape cache miss')
            _obs.log_step(
                kind='executor', step=self._step_counter,
                compiled=compiled_now, donated=len(dstate),
                kept=len(kstate), feed_bytes=feed_bytes,
                fetch_bytes=fetch_bytes,
                prepare_s=round(prep_span.duration, 6),
                lower_s=round(lower_span.duration, 6),
                execute_s=round(exec_span.duration, 6),
                fetch_s=round(fetch_span.duration, 6))
        return result

    @staticmethod
    def _plan_telemetry(program, fetch_names, feed_vals, donate):
        """Record the static memory plan for a freshly-lowered program:
        ``program_plan_seconds`` + predicted peak/accounted gauges
        (docs/OBSERVABILITY.md "Memory plan"). Telemetry-gated and
        failure-isolated — a planning bug must never break lowering."""
        if not _obs._ENABLED:
            return
        import time
        from .analysis.plan import plan_program
        t0 = time.perf_counter()
        try:
            plan = plan_program(
                program, fetch_names=fetch_names,
                feed_shapes={n: tuple(v.shape)
                             for n, v in feed_vals.items()
                             if hasattr(v, 'shape')},
                donate=donate)
        except Exception:
            _obs.inc('program_plan_failures', 1,
                     help='memory-plan attempts that raised (planning is '
                          'best-effort; lowering proceeds)')
            return
        _obs.observe('program_plan_seconds',
                     time.perf_counter() - t0,
                     help='wall time per static memory-plan computation '
                          '(once per program+shape compile-cache miss)')
        _obs.set_gauge('program_peak_hbm_bytes', plan.peak_bytes,
                       help='predicted peak HBM of the last lowered '
                            'program (analysis/plan.py)')
        _obs.set_gauge('program_plan_accounted_bytes',
                       plan.accounted_bytes,
                       help='predicted state+feed+fetch bytes — the '
                            'subset program_measured_hbm_bytes measures')

    @staticmethod
    def _check_fetches_finite(fetch_names, fetches):
        """Count + raise on non-finite fetched values (FLAGS_check_nan_inf).
        The counter increments even when telemetry is disabled-at-env — it
        is a no-op then — so enabling both shows NaN storms as a
        `nonfinite_detections` series instead of a lone traceback."""
        from .debugging import check_numerics
        bad = {}
        for n, f in zip(fetch_names, fetches):
            arr = np.asarray(f)
            if arr.dtype.kind == 'f' and not np.isfinite(arr).all():
                bad[n] = arr
        if bad:
            _obs.inc('nonfinite_detections', len(bad),
                     help='fetched variables containing NaN/Inf '
                          '(FLAGS_check_nan_inf)')
            _obs.instant('nonfinite_detected', variables=','.join(bad))
            check_numerics(bad, 'fetches')

    # ------------------------------------------------------------------
    def snapshot_persistables(self, program=None, scope=None):
        """Zero-copy, non-blocking snapshot of the program's persistable
        state for async checkpointing (paddle_tpu/resilience/): each value
        is wrapped in a :class:`FetchHandle` registered as
        donation-PROTECTED on this executor's inflight window — subsequent
        `run` calls keep those exact buffers out of the donated set (they
        run copy-in/copy-out for that state) until the checkpoint writer
        materializes the handles, at which point donation resumes. The
        step loop therefore never waits on checkpoint D2H.

        Note the protected set changes the donated/kept pytree split, so
        the first run after a snapshot (and the first run after the
        handles drain) each hit their own step-cache entry — two compiled
        variants total, both reused across checkpoints."""
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        handles = {}
        for v in program.list_vars():
            if not v.persistable:
                continue
            val = scope.find(v.name)
            if val is None:
                raise RuntimeError(
                    f"snapshot_persistables: '{v.name}' is uninitialized; "
                    f"run the startup program first")
            handles[v.name] = FetchHandle(val, name=v.name)
        self._window.protect(handles.values())
        return handles

    # ------------------------------------------------------------------
    def _run_from_dataset(self, program, dataset, scope, debug, fetch_list,
                          fetch_info, print_period, fetch_handler):
        if dataset is None:
            raise RuntimeError('dataset is required for *_from_dataset')
        if not dataset.use_vars:
            raise RuntimeError('dataset.set_use_var was never called')
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [getattr(f, 'name', f) for f in fetch_list]
        monitor = None
        if fetch_handler is not None:
            from .trainer_factory import FetchHandlerMonitor
            monitor = FetchHandlerMonitor(scope, fetch_handler)
            monitor.start()
        try:
            for step, batch in enumerate(dataset._batches()):
                fetches = self.run(program, feed=batch,
                                   fetch_list=fetch_list, scope=scope)
                if (debug or fetch_list) and step % print_period == 0:
                    msg = ', '.join(
                        f'{info}={np.asarray(val).ravel()[:4]}'
                        for info, val in zip(fetch_info, fetches))
                    if msg:
                        _dataset_logger().info('step %d: %s', step, msg)
        finally:
            if monitor is not None:
                monitor.stop()

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """ref executor.py:train_from_dataset — one pass over a
        fluid.dataset (QueueDataset/InMemoryDataset), running the jitted
        step per batch. `thread` is accepted for parity: host-side parsing
        threads are not the TPU bottleneck (the step is one XLA program)."""
        self._run_from_dataset(program, dataset, scope, debug, fetch_list,
                               fetch_info, print_period, fetch_handler)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """ref executor.py:infer_from_dataset — same loop; the program
        decides whether backward/update ops exist."""
        self._run_from_dataset(program, dataset, scope, debug, fetch_list,
                               fetch_info, print_period, fetch_handler)

    # ------------------------------------------------------------------
    def lower_to_callable(self, program, feed, fetch_list, scope=None):
        """(program, example feed dict, fetch_list) → (fn, arg_vals): a pure
        jittable fn over the feed arrays with the scope's parameters closed
        over as constants — the export surface for StableHLO (inference.py)."""
        scope = scope if scope is not None else global_scope()
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in fetch_list]
        from .core.lod import LoDTensor
        feed = dict(feed)
        block0 = program.global_block()
        for n in list(feed):
            if isinstance(feed[n], LoDTensor):
                if block0.has_var(n + '@LEN'):
                    feed[n + '@LEN'] = feed[n].lengths
                feed[n] = feed[n].data
        for n in list(feed):
            ln = n + '@LEN'
            if (not n.endswith('@LEN') and ln not in feed
                    and block0.has_var(ln) and block0.var(ln).is_data):
                arr = np.asarray(feed[n])
                if arr.ndim >= 2:
                    feed[ln] = np.full((arr.shape[0],), arr.shape[1],
                                       np.int32)
        feed_names = sorted(feed)
        state_names = sorted(v.name for v in program.list_vars()
                             if v.persistable)
        state = {}
        for n in state_names:
            val = scope.find(n)
            if val is None:
                raise RuntimeError(f"persistable var '{n}' is uninitialized")
            state[n] = jnp.asarray(val)
        step = _lower(program, feed_names, fetch_names, state_names,
                      feed_shapes={n: tuple(np.asarray(feed[n]).shape)
                                   for n in feed_names})
        base_key = default_generator.base_key()

        def fn(*feed_arrays):
            feed_vals = dict(zip(feed_names, feed_arrays))
            # export path: nothing is donated (state is closed over as
            # constants and must stay readable across calls)
            _, fetches = step({}, dict(state), feed_vals, base_key)
            return fetches

        block = program.global_block()
        arg_vals = []
        for n in feed_names:
            dtype = block.var(n).dtype if block.has_var(n) else None
            arg_vals.append(jnp.asarray(feed[n],
                                        to_jax_dtype(dtype) if dtype
                                        else None))
        return fn, arg_vals

    # ------------------------------------------------------------------
    def _run_startup(self, program, scope):
        """Run an init program eagerly (once-per-training cost; not jitted)."""
        self._step_counter += 1
        base_key = jax.random.fold_in(default_generator.base_key(),
                                      self._step_counter)
        env = {}

        def read(name):
            if name in env:
                return env[name]
            v = scope.find(name)
            if v is None:
                raise KeyError(f"startup: uninitialized input '{name}'")
            return v

        for i, op in enumerate(program.global_block().ops):
            _OpRunner.run(op, read, env.__setitem__,
                          jax.random.fold_in(base_key, i)
                          if _op_needs_key(op) else None)
        for v in program.list_vars():
            if v.persistable and v.name in env:
                scope.set(v.name, env[v.name])

    def close(self):
        self._cache.clear()


def scope_has_initialized(program, scope=None):
    scope = scope or global_scope()
    return all(scope.find(v.name) is not None
               for v in program.list_vars() if v.persistable)
