"""Distributed trace context (docs/OBSERVABILITY.md "Distributed tracing").

A request that crosses processes (router -> replica -> scheduler ->
engine) carries ONE `TraceContext`: a 16-hex `trace_id` shared by every
span the request produces anywhere in the fleet, the `span_id` of the
span the carrier was minted under (which becomes the *parent* of spans
recorded on the receiving side), and a `sampled` bit so the disabled
path costs a single header check.

Propagation is one HTTP header::

    X-PaddleTPU-Trace: <trace_id>-<span_id>-<0|1>

Sampling is decided ONCE at the edge (the router, or whoever submits
the request) by `maybe_sample()` from `PADDLE_TPU_TRACE_SAMPLE` and then
travels with the request — downstream processes never re-roll the dice,
so a trace is always complete or absent, never partial.
"""

import os
import random
import uuid

TRACE_HEADER = 'X-PaddleTPU-Trace'

ENV_TRACE_SAMPLE = 'PADDLE_TPU_TRACE_SAMPLE'
ENV_TRACE_DIR = 'PADDLE_TPU_TRACE_DIR'


def _new_id():
    return uuid.uuid4().hex[:16]


class TraceContext(object):
    """Immutable-by-convention carrier of one request's trace identity."""

    __slots__ = ('trace_id', 'span_id', 'parent_span_id', 'sampled')

    def __init__(self, trace_id, span_id, parent_span_id=None,
                 sampled=True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.sampled = bool(sampled)

    # -- construction ---------------------------------------------------
    @classmethod
    def root(cls, sampled=True):
        """Fresh trace: new trace_id, new root span id, no parent."""
        return cls(_new_id(), _new_id(), None, sampled)

    def child(self):
        """Same trace, fresh span id, parented under this context's span.

        The receiving side records its spans under `child()` contexts so
        every span's parent_span_id resolves to a span the sender
        actually recorded."""
        return TraceContext(self.trace_id, _new_id(), self.span_id,
                            self.sampled)

    # -- wire codec -----------------------------------------------------
    def to_header(self):
        return '%s-%s-%d' % (self.trace_id, self.span_id,
                             1 if self.sampled else 0)

    def to_headers(self):
        return {TRACE_HEADER: self.to_header()}

    @classmethod
    def from_header_value(cls, value):
        """Parse the header value; raises ValueError on a malformed one
        (servers turn that into HTTP 400 — a garbled trace header is a
        client bug worth surfacing, not silently dropping)."""
        parts = str(value).strip().split('-')
        if len(parts) != 3:
            raise ValueError(
                'malformed %s header %r: expected '
                '<trace_id>-<span_id>-<0|1>' % (TRACE_HEADER, value))
        trace_id, span_id, flag = parts
        ok = (len(trace_id) == 16 and len(span_id) == 16
              and all(c in '0123456789abcdef' for c in trace_id + span_id)
              and flag in ('0', '1'))
        if not ok:
            raise ValueError(
                'malformed %s header %r: ids must be 16 lowercase hex '
                'chars and the sampled flag 0 or 1'
                % (TRACE_HEADER, value))
        return cls(trace_id, span_id, None, flag == '1')

    @classmethod
    def from_headers(cls, headers):
        """`headers` is any mapping with .get (http.client headers work).
        Returns None when the header is absent."""
        value = headers.get(TRACE_HEADER)
        if value is None:
            return None
        return cls.from_header_value(value)

    def __repr__(self):
        return ('TraceContext(trace_id=%r, span_id=%r, parent=%r, '
                'sampled=%r)' % (self.trace_id, self.span_id,
                                 self.parent_span_id, self.sampled))


def sample_rate():
    """Strict-parse `PADDLE_TPU_TRACE_SAMPLE`: a float in [0, 1].

    Unset/empty means 0.0 (tracing off — the production default costs
    one env read + one float compare per request). Malformed values
    raise naming the knob, per the repo's knob contract."""
    raw = os.environ.get(ENV_TRACE_SAMPLE, '')
    if not raw.strip():
        return 0.0
    try:
        rate = float(raw)
    except ValueError:
        raise ValueError(
            '%s=%r is not a float; supported: a sampling probability '
            'in [0, 1]' % (ENV_TRACE_SAMPLE, raw))
    if not 0.0 <= rate <= 1.0:
        raise ValueError(
            '%s=%r out of range; supported: a sampling probability '
            'in [0, 1]' % (ENV_TRACE_SAMPLE, raw))
    return rate


def maybe_sample():
    """Edge sampling decision: a fresh root context with probability
    `PADDLE_TPU_TRACE_SAMPLE`, else None (request is untraced)."""
    rate = sample_rate()
    if rate <= 0.0:
        return None
    if rate < 1.0 and random.random() >= rate:
        return None
    return TraceContext.root(sampled=True)
