"""Thread-safe metrics registry: counters, gauges, time-bucketed histograms.

The registry is the numeric half of the telemetry subsystem (the tracer is
the timeline half). Design constraints, in order:

1. **Near-zero overhead when disabled** — instrumentation sites guard on the
   module flag `observability._ENABLED` before touching any metric, so the
   disabled hot path pays one attribute read. Nothing in this module runs.
2. **Thread-safe when enabled** — the DataLoader producer thread, reader
   decorator threads, and the training loop all record concurrently. Each
   metric carries its own lock; the registry lock only guards creation.
3. **Two export formats** — `to_dict()` (consumed by the step logger, the
   bench sidecar, and tools/telemetry_report.py) and `prometheus_text()`
   (the text exposition format, scrape-able by any Prometheus agent).

Metric naming: snake_case, unit-suffixed (`_seconds`, `_bytes`, `_total`
implied for counters). Labels are a small dict (e.g. ``{'op': 'matmul'}``);
each distinct label set is one child series under the parent metric.
"""
from __future__ import annotations

import math
import threading

__all__ = ['Counter', 'Gauge', 'Histogram', 'MetricsRegistry', 'registry']

# Default histogram bounds for latencies: 10 µs … ~81 s, ×3 per bucket.
# Dispatch latencies (~10 µs–1 ms), step phases (~1 ms–1 s), and XLA
# compiles (~0.1 s–1 min) all land mid-range instead of saturating an end.
DEFAULT_TIME_BUCKETS = tuple(1e-5 * 3.0 ** i for i in range(15))


def _label_key(labels):
    return tuple(sorted(labels.items())) if labels else ()


class _Metric:
    kind = None

    def __init__(self, name, help=''):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children = {}   # label_key -> child state

    def labels(self, **labels):
        key = _label_key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    key, self._new_child(dict(labels)))
        return child

    def to_dict(self):
        with self._lock:
            children = list(self._children.values())
        return {'type': self.kind, 'help': self.help,
                'samples': [c.sample() for c in children]}


class _CounterChild:
    __slots__ = ('_labels', '_value', '_lock')

    def __init__(self, labels):
        self._labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    @property
    def value(self):
        return self._value

    def sample(self):
        return {'labels': self._labels, 'value': self._value}


class Counter(_Metric):
    """Monotonically increasing count (events, bytes, detections)."""
    kind = 'counter'

    def _new_child(self, labels):
        return _CounterChild(labels)

    def inc(self, amount=1.0):
        self.labels().inc(amount)

    @property
    def value(self):
        return self.labels().value


class _GaugeChild(_CounterChild):
    def set(self, value):
        with self._lock:
            self._value = float(value)


class Gauge(_Metric):
    """Point-in-time value (queue depth, last wait, cache size)."""
    kind = 'gauge'

    def _new_child(self, labels):
        return _GaugeChild(labels)

    def set(self, value):
        self.labels().set(value)

    def inc(self, amount=1.0):
        self.labels().inc(amount)

    @property
    def value(self):
        return self.labels().value


#: retained raw observations per histogram child for exact percentiles —
#: a ring, so a long-running process keeps the RECENT distribution, which
#: is what p99 questions are about.
RECENT_SAMPLES = 512


class _HistogramChild:
    __slots__ = ('_labels', '_bounds', '_counts', '_sum', '_count', '_min',
                 '_max', '_ring', '_lock')

    def __init__(self, labels, bounds):
        self._labels = labels
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # last bucket = +Inf
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._ring = [0.0] * RECENT_SAMPLES      # bounded sample ring
        self._lock = threading.Lock()

    def observe(self, value):
        value = float(value)
        i = 0
        bounds = self._bounds
        while i < len(bounds) and value > bounds[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._ring[self._count % RECENT_SAMPLES] = value
            self._sum += value
            self._count += 1
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    def percentile(self, q):
        """Exact q-th percentile (0..100) over the last RECENT_SAMPLES
        observations (linear interpolation, numpy convention); None when
        empty. Exact — unlike inferring from exponential bucket edges,
        which is off by up to the 3× bucket width for long-tail decode
        latencies."""
        with self._lock:
            n = min(self._count, RECENT_SAMPLES)
            samples = sorted(self._ring[:n])
        if not samples:
            return None
        if len(samples) == 1:
            return samples[0]
        pos = (len(samples) - 1) * (float(q) / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(samples) - 1)
        frac = pos - lo
        return samples[lo] * (1.0 - frac) + samples[hi] * frac

    def sample(self):
        with self._lock:
            n = min(self._count, RECENT_SAMPLES)
            return {'labels': self._labels, 'buckets': list(self._counts),
                    'bounds': list(self._bounds), 'sum': self._sum,
                    'count': self._count,
                    'min': None if self._count == 0 else self._min,
                    'max': None if self._count == 0 else self._max,
                    'recent': sorted(self._ring[:n])}


class Histogram(_Metric):
    """Time-bucketed distribution; exponential latency bounds by default."""
    kind = 'histogram'

    def __init__(self, name, help='', bounds=DEFAULT_TIME_BUCKETS):
        super().__init__(name, help)
        self._bounds = tuple(float(b) for b in bounds)

    def _new_child(self, labels):
        return _HistogramChild(labels, self._bounds)

    def observe(self, value):
        self.labels().observe(value)

    def percentile(self, q):
        return self.labels().percentile(q)


class MetricsRegistry:
    """Name → metric map with at-export collectors.

    A collector is a zero-arg callable run at export time — the cheap way to
    snapshot externally-owned counters (the eager kernel cache, jax cache
    internals) into gauges without touching their hot paths.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._collectors = []

    def _get(self, name, factory):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = factory()
                    self._metrics[name] = m
        return m

    def counter(self, name, help=''):
        m = self._get(name, lambda: Counter(name, help))
        if m.kind != 'counter':
            raise TypeError(f"metric '{name}' already registered as {m.kind}")
        return m

    def gauge(self, name, help=''):
        m = self._get(name, lambda: Gauge(name, help))
        if m.kind != 'gauge':
            raise TypeError(f"metric '{name}' already registered as {m.kind}")
        return m

    def histogram(self, name, help='', bounds=DEFAULT_TIME_BUCKETS):
        m = self._get(name, lambda: Histogram(name, help, bounds))
        if m.kind != 'histogram':
            raise TypeError(f"metric '{name}' already registered as {m.kind}")
        return m

    def register_collector(self, fn):
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    def _run_collectors(self):
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass    # a broken collector must never take down the export

    # -- exports -----------------------------------------------------------
    def to_dict(self):
        self._run_collectors()
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.to_dict() for name, m in sorted(metrics.items())}

    def prometheus_text(self, prefix='paddle_tpu_'):
        """Prometheus text exposition format, version 0.0.4."""
        self._run_collectors()
        with self._lock:
            metrics = dict(self._metrics)
        lines = []
        for name, m in sorted(metrics.items()):
            full = prefix + name
            if m.help:
                lines.append(f"# HELP {full} {m.help}")
            lines.append(f"# TYPE {full} {m.kind}")
            for s in m.to_dict()['samples']:
                if m.kind == 'histogram':
                    cum = 0
                    for bound, c in zip(s['bounds'] + [math.inf],
                                        s['buckets']):
                        cum += c
                        le = '+Inf' if bound == math.inf else repr(bound)
                        lines.append(
                            f"{full}_bucket"
                            f"{_prom_labels(s['labels'], le=le)} {cum}")
                    lines.append(
                        f"{full}_sum{_prom_labels(s['labels'])} {s['sum']}")
                    lines.append(
                        f"{full}_count{_prom_labels(s['labels'])} "
                        f"{s['count']}")
                else:
                    lines.append(
                        f"{full}{_prom_labels(s['labels'])} "
                        f"{_prom_num(s['value'])}")
        return '\n'.join(lines) + '\n'


def _prom_escape(v):
    return str(v).replace('\\', r'\\').replace('"', r'\"').replace('\n', r'\n')


def _prom_labels(labels, **extra):
    items = dict(labels or {})
    items.update(extra)
    if not items:
        return ''
    body = ','.join(f'{k}="{_prom_escape(v)}"'
                    for k, v in sorted(items.items()))
    return '{' + body + '}'


def _prom_num(v):
    # integral values print without the trailing .0 (matches client_python)
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


registry = MetricsRegistry()
