"""Structured per-step logger: JSON-lines to PADDLE_TPU_METRICS_DIR.

Each training step appends one JSON object to `steps.jsonl` (timestamp,
step counter, phase durations, donation counts, byte volumes, loss when the
caller passes it). A human-readable mirror goes through log_helper.get_logger
at DEBUG — never print() — so headless runs can capture it with ordinary
logging config, and the default INFO level keeps stderr quiet.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time

from ..log_helper import get_logger

__all__ = ['StepLogger', 'step_logger']

_logger = get_logger(
    'paddle_tpu.telemetry', logging.INFO,
    fmt='%(asctime)s-%(levelname)s: %(message)s')


class StepLogger:
    def __init__(self):
        self._lock = threading.Lock()
        self._file = None
        self._path = None
        self.records = 0

    def open(self, directory):
        """(Re)point the JSONL stream at `directory`/steps.jsonl."""
        path = os.path.join(directory, 'steps.jsonl')
        with self._lock:
            if self._path == path and self._file is not None:
                return path
            self.close()
            os.makedirs(directory, exist_ok=True)
            self._file = open(path, 'a')
            self._path = path
        return path

    @property
    def path(self):
        return self._path

    def log(self, record):
        """Append one step record. Unopened logger → DEBUG mirror only."""
        rec = {'ts': time.time()}
        rec.update(record)
        line = json.dumps(rec, default=str)
        with self._lock:
            self.records += 1
            if self._file is not None:
                self._file.write(line + '\n')
                self._file.flush()
        _logger.debug('step %s', line)

    def close(self):
        # caller holds no lock here only via open(); guard for direct use
        f, self._file, self._path = self._file, None, None
        if f is not None:
            try:
                f.close()
            except Exception:
                pass


step_logger = StepLogger()
