"""Step tracer: host-side span trees written as chrome-trace JSON.

Every `Executor.run`, `TrainStep.__call__`, and (optionally) tape dispatch
opens a span; nesting is tracked per thread, so the emitted events form a
tree under each step exactly the way Perfetto / chrome://tracing render
"complete" (`ph: "X"`) events — containment of [ts, ts+dur] on one tid IS
the tree. Unlike profiler.start_profiler this does not touch jax.profiler:
it works on any backend, costs two perf_counter() calls per span, and the
output is a single self-contained JSON file.

The event buffer is bounded (PADDLE_TPU_TRACE_MAX_EVENTS, default 100000);
past the bound new events are dropped and counted, never silently lost.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ['Span', 'StepTracer', 'tracer']


class Span:
    """One timed region. Context manager; after exit `duration` is valid."""

    __slots__ = ('name', 'args', 'start', 'duration', '_tracer', '_depth')

    def __init__(self, tracer, name, args):
        self.name = name
        self.args = args
        self.start = 0.0
        self.duration = 0.0
        self._tracer = tracer
        self._depth = 0

    def __enter__(self):
        self._depth = self._tracer._enter()
        self.start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        end = time.perf_counter()
        self.duration = end - self.start
        self._tracer._exit(self, exc_type)
        return False


class _NullSpan:
    """Shared no-op span for the disabled path (one instance, no allocs)."""

    __slots__ = ()
    name = None
    duration = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


NULL_SPAN = _NullSpan()


class StepTracer:
    def __init__(self, max_events=None):
        if max_events is None:
            max_events = int(os.environ.get('PADDLE_TPU_TRACE_MAX_EVENTS',
                                            '100000'))
        self.max_events = max_events
        self._lock = threading.Lock()
        self._events = []
        self.dropped = 0
        self._epoch = time.perf_counter()
        self._local = threading.local()

    # -- recording ---------------------------------------------------------
    def span(self, name, **args):
        return Span(self, name, args or None)

    def _enter(self):
        depth = getattr(self._local, 'depth', 0)
        self._local.depth = depth + 1
        return depth

    def _exit(self, span, exc_type):
        self._local.depth = span._depth
        ev = {
            'name': span.name,
            'ph': 'X',
            'ts': (span.start - self._epoch) * 1e6,      # µs, trace-relative
            'dur': span.duration * 1e6,
            'pid': os.getpid(),
            'tid': threading.get_ident(),
        }
        args = span.args
        if exc_type is not None:
            args = dict(args or {}, error=exc_type.__name__)
        if args:
            ev['args'] = {k: _jsonable(v) for k, v in args.items()}
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
            else:
                self._events.append(ev)

    def complete(self, name, start_perf, end_perf, **args):
        """Append one already-measured complete event (ph 'X') from
        explicit perf_counter stamps — distributed trace spans are often
        measured retroactively (queue wait is known only at admission),
        so they can't ride the context-manager path."""
        ev = {'name': name, 'ph': 'X',
              'ts': (start_perf - self._epoch) * 1e6,
              'dur': max(0.0, end_perf - start_perf) * 1e6,
              'pid': os.getpid(), 'tid': threading.get_ident()}
        if args:
            ev['args'] = {k: _jsonable(v) for k, v in args.items()}
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
            else:
                self._events.append(ev)

    def instant(self, name, **args):
        """Zero-duration marker (ph 'i') — e.g. a nonfinite detection."""
        ev = {'name': name, 'ph': 'i', 's': 't',
              'ts': (time.perf_counter() - self._epoch) * 1e6,
              'pid': os.getpid(), 'tid': threading.get_ident()}
        if args:
            ev['args'] = {k: _jsonable(v) for k, v in args.items()}
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
            else:
                self._events.append(ev)

    # -- export ------------------------------------------------------------
    def snapshot(self):
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        return {'traceEvents': events, 'displayTimeUnit': 'ms',
                'otherData': {'producer': 'paddle_tpu.observability',
                              'dropped_events': dropped}}

    def chrome_trace_json(self):
        return json.dumps(self.snapshot())

    def dump(self, path):
        """Write the Perfetto-loadable chrome-trace file; returns `path`."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, 'w') as f:
            json.dump(self.snapshot(), f)
        return path

    def reset(self):
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._epoch = time.perf_counter()

    def __len__(self):
        with self._lock:
            return len(self._events)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    return str(v)


tracer = StepTracer()
