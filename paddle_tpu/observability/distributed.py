"""Fleet-wide observability (docs/OBSERVABILITY.md "Fleet-wide").

Three subsystems, all process-local code with cross-process artifacts:

1. **Span records** — `SpanRecorder` streams one JSONL line per traced
   span into ``PADDLE_TPU_TRACE_DIR`` (``spans-<pid>.jsonl``), with a
   first-line clock record (pid, unix_time, perf_counter) and router-side
   clock-offset records, so ``tools/trace_merge.py`` can align N
   processes' spans into ONE chrome-trace timeline. `record_span` also
   mirrors every span into the in-process chrome tracer tagged with its
   trace_id, so a single process's ``trace.json`` already shows its share
   of the distributed request.

2. **Metric merging** — a Prometheus text-format parser plus
   `merge_fleet_metrics`, the ONE merge semantics used by both the
   router's ``/metrics/fleet`` and the training fleet's host-0 aggregate:
   counters sum across processes per label-set, gauges gain a
   ``replica``/``host`` label (summing a utilization gauge would be a
   lie), histograms merge bucket-by-bucket when the bound ladders agree
   and fall back to labeling when they don't. Training hosts publish
   snapshots through the PR 12 coordinator KV (`publish_host_snapshot`)
   and host 0 folds them (`aggregate_fleet_snapshots`).

3. **Windowed series + monitors** — `WindowedSeries` keeps a fixed ring
   of per-window sample snapshots giving sliding-window p50/p99/rate for
   named series (queue depth, TTFT, tokens/s, step time ...); the
   `StragglerMonitor` flags hosts whose step time is a robust-z outlier
   against the fleet (``straggler_*`` gauges + quarantine-style JSONL),
   and the `SLOMonitor` evaluates the declarative ``PADDLE_TPU_SLO``
   spec into burn counters and the ``/healthz`` ``slo`` block.

Layering: this module may import :mod:`observability.metrics` and the
tracer, but never ``serving.*`` (serving imports observability); the
coordinator KV is imported lazily inside the fleet helpers because it
pulls in jax.
"""

import collections
import json
import os
import threading
import time

from .metrics import registry
from .tracer import tracer
from .trace_context import ENV_TRACE_DIR

ENV_SLO = 'PADDLE_TPU_SLO'

#: coordinator-KV prefix for per-host metric snapshots
METRICS_KV_PREFIX = 'paddle_tpu/metrics/'

# ---------------------------------------------------------------------------
# span records
# ---------------------------------------------------------------------------


class SpanRecorder(object):
    """Per-process JSONL span stream (`steplog` idiom: append + flush per
    line so a kill -9'd process loses at most the in-flight span — the
    failover drill reads a victim's spans after SIGKILL)."""

    def __init__(self, path, process):
        self._path = path
        self._process = str(process)
        self._fh = None
        self._lock = threading.Lock()

    @property
    def path(self):
        return self._path

    @property
    def process(self):
        return self._process

    def _ensure_open_locked(self):
        if self._fh is None:
            d = os.path.dirname(self._path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self._path, 'a')
            # Clock record first: the merge tool pairs (unix_time,
            # perf_counter) per process to translate perf-based spans
            # onto one wall-clock axis.
            self._write_locked({'clock': {
                'pid': os.getpid(), 'process': self._process,
                'unix_time': time.time(),
                'perf_counter': time.perf_counter()}})

    def _write_locked(self, record):
        self._fh.write(json.dumps(record) + '\n')
        self._fh.flush()

    def write(self, record):
        with self._lock:
            self._ensure_open_locked()
            self._write_locked(record)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


_RECORDER = None
_RECORDER_LOCK = threading.Lock()


def span_recorder(process=None):
    """The process-wide SpanRecorder, or None when
    ``PADDLE_TPU_TRACE_DIR`` is unset (tracing artifacts off)."""
    global _RECORDER
    trace_dir = os.environ.get(ENV_TRACE_DIR)
    if not trace_dir:
        return None
    with _RECORDER_LOCK:
        if _RECORDER is None:
            label = process if process else 'pid-%d' % os.getpid()
            _RECORDER = SpanRecorder(
                os.path.join(trace_dir, 'spans-%d.jsonl' % os.getpid()),
                label)
        return _RECORDER


def set_process_label(label):
    """Name this process in span records (replicas pass their
    replica_id, the router passes 'router'). Must run before the first
    span is recorded to land in the clock record."""
    rec = span_recorder(process=label)
    if rec is not None and rec._fh is None:
        rec._process = str(label)
    return rec


def record_span(ctx, name, start_perf, end_perf, **args):
    """Record one completed span of a sampled trace.

    `start_perf`/`end_perf` are ``time.perf_counter()`` stamps taken by
    the caller around the work. No-op (a single None/flag check) when
    the request is untraced — the disabled path must stay free."""
    if ctx is None or not ctx.sampled:
        return None
    now_perf = time.perf_counter()
    now_unix = time.time()
    dur_s = max(0.0, end_perf - start_perf)
    start_unix = now_unix - (now_perf - start_perf)
    span = {'name': name, 'trace_id': ctx.trace_id,
            'span_id': ctx.span_id, 'parent_span_id': ctx.parent_span_id,
            'start_unix': start_unix, 'dur_s': dur_s}
    if args:
        span['args'] = {k: v for k, v in args.items()}
    rec = span_recorder()
    if rec is not None:
        span = dict(span, process=rec.process)
        rec.write({'span': span})
    # Mirror into the in-process chrome buffer, tagged so a per-process
    # trace.json can still be filtered by trace_id.
    targs = dict(args)
    targs['trace_id'] = ctx.trace_id
    targs['span_id'] = ctx.span_id
    if ctx.parent_span_id:
        targs['parent_span_id'] = ctx.parent_span_id
    tracer.complete(name, start_perf, end_perf, **targs)
    return span


def record_clock_offset(process, offset_s, rtt_s=None):
    """Router-side: persist the estimated (replica_unix - local_unix)
    clock offset for `process`, measured by the health-poll handshake.
    The merge tool uses these to shift every process onto the recording
    process's clock."""
    rec = span_recorder()
    if rec is not None:
        doc = {'process': str(process), 'offset_s': float(offset_s),
               'unix_time': time.time()}
        if rtt_s is not None:
            doc['rtt_s'] = float(rtt_s)
        rec.write({'offset': doc})


# ---------------------------------------------------------------------------
# prometheus text parsing + fleet merge
# ---------------------------------------------------------------------------


def _parse_labels(raw):
    """``a="x",b="y\"z"`` → dict. Handles the text-format escapes."""
    labels = {}
    i, n = 0, len(raw)
    while i < n:
        j = raw.index('=', i)
        key = raw[i:j].strip()
        i = j + 1
        if raw[i] != '"':
            raise ValueError('unquoted label value in %r' % raw)
        i += 1
        buf = []
        while raw[i] != '"':
            ch = raw[i]
            if ch == '\\':
                nxt = raw[i + 1]
                buf.append({'n': '\n', '\\': '\\', '"': '"'}.get(nxt, nxt))
                i += 2
            else:
                buf.append(ch)
                i += 1
        labels[key] = ''.join(buf)
        i += 1
        while i < n and raw[i] in ', ':
            i += 1
    return labels


def parse_prometheus_text(text):
    """Prometheus text 0.0.4 → ordered ``{family: {'type', 'help',
    'samples': [(sample_name, labels_dict, value)]}}``.

    Histogram families keep their ``_bucket``/``_sum``/``_count``
    samples under the base family name (TYPE lines carry the base)."""
    families = collections.OrderedDict()

    def family_for(sample_name):
        for fam in (sample_name, sample_name.rsplit('_bucket', 1)[0],
                    sample_name.rsplit('_sum', 1)[0],
                    sample_name.rsplit('_count', 1)[0]):
            if fam in families:
                return fam
        return sample_name

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith('#'):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == 'HELP':
                families.setdefault(
                    parts[2], {'type': 'untyped', 'help': '',
                               'samples': []})['help'] = parts[3]
            elif len(parts) >= 4 and parts[1] == 'TYPE':
                families.setdefault(
                    parts[2], {'type': 'untyped', 'help': '',
                               'samples': []})['type'] = parts[3].strip()
            continue
        if '{' in line:
            name = line[:line.index('{')]
            rest = line[line.index('{') + 1:]
            labels_raw, value_raw = rest.rsplit('}', 1)
            labels = _parse_labels(labels_raw)
        else:
            name, value_raw = line.split(None, 1)
            labels = {}
        fam = family_for(name)
        families.setdefault(fam, {'type': 'untyped', 'help': '',
                                  'samples': []})
        families[fam]['samples'].append(
            (name, labels, float(value_raw.strip())))
    return families


def _labels_key(labels, drop=()):
    return tuple(sorted((k, v) for k, v in labels.items()
                        if k not in drop))


def _fmt_num(value):
    if value == float('inf'):
        return '+Inf'
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _fmt_labels(labels):
    if not labels:
        return ''
    items = ['%s="%s"' % (k, str(v).replace('\\', r'\\')
                          .replace('\n', r'\n').replace('"', r'\"'))
             for k, v in sorted(labels.items())]
    return '{%s}' % ','.join(items)


def merge_fleet_metrics(scrapes, source_label='replica'):
    """Merge N processes' Prometheus exports into one fleet export.

    `scrapes` is ``[(source_name, prom_text), ...]``. Semantics
    (docs/OBSERVABILITY.md "Aggregation semantics"):

    - **counter**: summed across sources per identical label-set — a
      fleet request count is the sum of replica request counts;
    - **gauge**: per-source sample with a ``replica=<source>`` (or
      ``host=``) label added — utilization/occupancy gauges of different
      processes are different facts, never summable;
    - **histogram**: per label-set, bucket counts summed per ``le``
      plus summed ``_sum``/``_count`` — valid because every process
      builds the same bucket ladder from the same code; if the ladders
      disagree (version skew mid-rolling-restart) that label-set falls
      back to gauge-style source labeling;
    - **untyped**: treated as gauge.

    Returns the merged text, parseable by `parse_prometheus_text`.
    """
    merged = collections.OrderedDict()
    for source, text in scrapes:
        for fam, info in parse_prometheus_text(text).items():
            slot = merged.setdefault(
                fam, {'type': info['type'], 'help': info['help'],
                      'per_source': collections.OrderedDict()})
            if slot['type'] == 'untyped' and info['type'] != 'untyped':
                slot['type'] = info['type']
            if not slot['help']:
                slot['help'] = info['help']
            slot['per_source'][source] = info['samples']

    out = []
    for fam, slot in merged.items():
        kind = slot['type']
        if slot['help']:
            out.append('# HELP %s %s' % (fam, slot['help']))
        out.append('# TYPE %s %s' % (fam, kind))
        if kind == 'counter':
            acc = collections.OrderedDict()
            for samples in slot['per_source'].values():
                for name, labels, value in samples:
                    key = (name, _labels_key(labels))
                    if key not in acc:
                        acc[key] = [labels, 0.0]
                    acc[key][1] += value
            for (name, _), (labels, value) in acc.items():
                out.append('%s%s %s' % (name, _fmt_labels(labels),
                                        _fmt_num(value)))
        elif kind == 'histogram':
            out.extend(_merge_histogram_family(
                slot['per_source'], source_label))
        else:  # gauge / untyped → label by source
            for source, samples in slot['per_source'].items():
                for name, labels, value in samples:
                    labeled = dict(labels)
                    labeled[source_label] = source
                    out.append('%s%s %s' % (name, _fmt_labels(labeled),
                                            _fmt_num(value)))
    return '\n'.join(out) + '\n' if out else ''


def _merge_histogram_family(per_source, source_label):
    # group: labels-without-le → {source: {'buckets': {le: v},
    #                                      'sum': x, 'count': n, labels}}
    groups = collections.OrderedDict()
    for source, samples in per_source.items():
        for name, labels, value in samples:
            key = _labels_key(labels, drop=('le',))
            grp = groups.setdefault(key, collections.OrderedDict())
            ent = grp.setdefault(source, {
                'buckets': collections.OrderedDict(), 'sum': 0.0,
                'count': 0.0,
                'labels': {k: v for k, v in labels.items() if k != 'le'}})
            if name.endswith('_bucket'):
                le = labels.get('le', '+Inf')
                ent['buckets'][le] = ent['buckets'].get(le, 0.0) + value
                ent['base'] = name[:-len('_bucket')]
            elif name.endswith('_sum'):
                ent['sum'] += value
                ent['base'] = name[:-len('_sum')]
            elif name.endswith('_count'):
                ent['count'] += value
                ent['base'] = name[:-len('_count')]

    lines = []
    for key, grp in groups.items():
        ladders = {tuple(ent['buckets'].keys()) for ent in grp.values()}
        base = next(iter(grp.values())).get('base', '')
        labels = next(iter(grp.values()))['labels']
        if len(ladders) == 1:
            buckets = collections.OrderedDict()
            total_sum, total_count = 0.0, 0.0
            for ent in grp.values():
                for le, v in ent['buckets'].items():
                    buckets[le] = buckets.get(le, 0.0) + v
                total_sum += ent['sum']
                total_count += ent['count']
            for le, v in buckets.items():
                blabels = dict(labels, le=le)
                lines.append('%s_bucket%s %s' % (
                    base, _fmt_labels(blabels), _fmt_num(v)))
            lines.append('%s_sum%s %s' % (base, _fmt_labels(labels),
                                          repr(float(total_sum))))
            lines.append('%s_count%s %s' % (base, _fmt_labels(labels),
                                            _fmt_num(total_count)))
        else:  # ladder skew → label by source instead of merging
            for source, ent in grp.items():
                slabels = dict(labels)
                slabels[source_label] = source
                for le, v in ent['buckets'].items():
                    blabels = dict(slabels, le=le)
                    lines.append('%s_bucket%s %s' % (
                        base, _fmt_labels(blabels), _fmt_num(v)))
                lines.append('%s_sum%s %s' % (
                    base, _fmt_labels(slabels), repr(float(ent['sum']))))
                lines.append('%s_count%s %s' % (
                    base, _fmt_labels(slabels), _fmt_num(ent['count'])))
    return lines


# ---------------------------------------------------------------------------
# windowed time series
# ---------------------------------------------------------------------------


class WindowedSeries(object):
    """Sliding-window series: a fixed ring of per-window snapshots.

    Each window holds a bounded reservoir-style sample list plus exact
    count/total; `percentile` pools the retained samples across the ring
    (exact when windows stay under `max_samples` observations — the
    intended regime for per-second serving signals), `rate` divides the
    ring's total count by its covered wall time. O(1) per observe, O(ring)
    memory, no timers — windows roll lazily on the next observe/read."""

    __slots__ = ('name', 'window_s', '_ring', '_cur', '_max_samples',
                 '_lock')

    def __init__(self, name, window_s=10.0, windows=6, max_samples=512):
        self.name = name
        self.window_s = float(window_s)
        self._ring = collections.deque(maxlen=int(windows))
        self._max_samples = int(max_samples)
        self._cur = None
        self._lock = threading.Lock()

    def _roll_locked(self, now):
        if self._cur is None:
            self._cur = {'start': now, 'count': 0, 'total': 0.0,
                         'samples': []}
        while now - self._cur['start'] >= self.window_s:
            self._cur['end'] = self._cur['start'] + self.window_s
            self._ring.append(self._cur)
            self._cur = {'start': self._cur['end'], 'count': 0,
                         'total': 0.0, 'samples': []}

    def observe(self, value, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            self._roll_locked(now)
            cur = self._cur
            cur['count'] += 1
            cur['total'] += value
            if len(cur['samples']) < self._max_samples:
                cur['samples'].append(value)
            else:
                # deterministic decimation: keep every k-th overflow so
                # the tail is still represented without unbounded memory
                k = cur['count'] % self._max_samples
                cur['samples'][k] = value

    def _windows_locked(self, now):
        self._roll_locked(now)
        return list(self._ring) + [self._cur]

    def percentile(self, q, now=None):
        """Exact q-th percentile (0..100) over retained samples across
        the ring; None when empty."""
        now = time.monotonic() if now is None else now
        with self._lock:
            samples = []
            for w in self._windows_locked(now):
                samples.extend(w['samples'])
        if not samples:
            return None
        samples.sort()
        if len(samples) == 1:
            return samples[0]
        # linear interpolation, numpy 'linear' convention
        pos = (len(samples) - 1) * (q / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(samples) - 1)
        frac = pos - lo
        return samples[lo] * (1.0 - frac) + samples[hi] * frac

    def rate(self, now=None):
        """Observations per second over the covered window span."""
        now = time.monotonic() if now is None else now
        with self._lock:
            windows = self._windows_locked(now)
            count = sum(w['count'] for w in windows)
            covered = now - windows[0]['start']
        if covered <= 0:
            return 0.0
        return count / covered

    def count(self, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            return sum(w['count']
                       for w in self._windows_locked(now))

    def mean(self, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            windows = self._windows_locked(now)
            count = sum(w['count'] for w in windows)
            total = sum(w['total'] for w in windows)
        return total / count if count else None

    def snapshot(self, now=None):
        return {'p50': self.percentile(50, now=now),
                'p99': self.percentile(99, now=now),
                'mean': self.mean(now=now),
                'rate': self.rate(now=now),
                'count': self.count(now=now)}


_SERIES = {}
_SERIES_LOCK = threading.Lock()


def series(name, window_s=10.0, windows=6):
    """Get-or-create the named process-wide WindowedSeries."""
    with _SERIES_LOCK:
        s = _SERIES.get(name)
        if s is None:
            s = _SERIES[name] = WindowedSeries(
                name, window_s=window_s, windows=windows)
        return s


def series_snapshot():
    with _SERIES_LOCK:
        items = list(_SERIES.items())
    return {name: s.snapshot() for name, s in items}


# ---------------------------------------------------------------------------
# straggler monitor
# ---------------------------------------------------------------------------


class StragglerMonitor(object):
    """Per-host step-time outlier detection over the fleet.

    Robust z-score: ``z = (x - median) / (1.4826*MAD + floor)`` where the
    floor (5% of the median) keeps microsecond-level jitter at small
    step times from manufacturing outliers, and makes a zero-MAD fleet
    (every healthy host identical, one sleeper) still resolvable. A host
    with z > `threshold` is flagged: ``straggler_zscore{host=}`` gauges,
    a ``straggler_count`` gauge, and a quarantine-style JSONL record
    (``straggler.jsonl`` in `out_dir`) naming the host — the same shape
    the resilience layer's supervisor records use."""

    def __init__(self, threshold=3.5, window=8, out_dir=None):
        self.threshold = float(threshold)
        self._times = {}           # host -> deque of recent step times
        self._window = int(window)
        self._out_dir = out_dir
        self._lock = threading.Lock()

    def record(self, host, step_time_s):
        with self._lock:
            dq = self._times.setdefault(
                str(host), collections.deque(maxlen=self._window))
            dq.append(float(step_time_s))

    def evaluate(self, step=None):
        """→ ``{'stragglers': [host...], 'zscores': {host: z}}``; sets
        the ``straggler_*`` gauges as a side effect."""
        with self._lock:
            means = {h: sum(dq) / len(dq)
                     for h, dq in self._times.items() if dq}
        if len(means) < 2:
            registry.gauge('straggler_count',
                           'hosts currently flagged as stragglers').set(0)
            return {'stragglers': [], 'zscores': {}}
        values = sorted(means.values())
        n = len(values)
        median = (values[n // 2] if n % 2
                  else 0.5 * (values[n // 2 - 1] + values[n // 2]))
        abs_dev = sorted(abs(v - median) for v in values)
        mad = (abs_dev[n // 2] if n % 2
               else 0.5 * (abs_dev[n // 2 - 1] + abs_dev[n // 2]))
        denom = 1.4826 * mad + max(0.05 * abs(median), 1e-9)
        zscores, stragglers = {}, []
        zgauge = registry.gauge(
            'straggler_zscore',
            'robust z-score of each host step time vs the fleet')
        for host, mean in means.items():
            z = (mean - median) / denom
            zscores[host] = z
            zgauge.labels(host=host).set(z)
            if z > self.threshold:
                stragglers.append(host)
        registry.gauge(
            'straggler_count',
            'hosts currently flagged as stragglers').set(len(stragglers))
        if stragglers:
            registry.counter(
                'straggler_flags',
                'cumulative straggler detections').inc(len(stragglers))
            self._write_records(stragglers, zscores, means, step)
        return {'stragglers': sorted(stragglers), 'zscores': zscores}

    def _write_records(self, stragglers, zscores, means, step):
        if not self._out_dir:
            return
        try:
            os.makedirs(self._out_dir, exist_ok=True)
            path = os.path.join(self._out_dir, 'straggler.jsonl')
            with open(path, 'a') as f:
                for host in stragglers:
                    f.write(json.dumps({
                        'host': host, 'zscore': zscores[host],
                        'mean_step_time_s': means[host], 'step': step,
                        'unix_time': time.time(),
                        'action': 'flag'}) + '\n')
        except OSError:
            pass


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------

_SLO_AGGS = ('p50', 'p99', 'mean', 'rate')


class SLOClause(object):
    __slots__ = ('series', 'agg', 'op', 'bound', 'text')

    def __init__(self, series_name, agg, op, bound, text):
        self.series = series_name
        self.agg = agg
        self.op = op
        self.bound = bound
        self.text = text


def parse_slo_spec(raw):
    """``PADDLE_TPU_SLO`` grammar: comma-separated
    ``<series>.<agg><op><value>`` clauses, e.g.
    ``ttft.p99<0.2,queue_depth.p50<32,tokens.rate>100``.
    agg ∈ p50|p99|mean|rate, op ∈ <|>. Malformed clauses raise naming
    the knob and the supported grammar (repo knob contract)."""
    clauses = []
    for part in str(raw).split(','):
        part = part.strip()
        if not part:
            continue
        err = ValueError(
            '%s clause %r is malformed; supported: '
            '<series>.<agg><op><value> with agg in %s and op < or > '
            '(e.g. ttft.p99<0.2)' % (ENV_SLO, part, '|'.join(_SLO_AGGS)))
        op = '<' if '<' in part else ('>' if '>' in part else None)
        if op is None:
            raise err
        lhs, _, rhs = part.partition(op)
        if '.' not in lhs:
            raise err
        series_name, _, agg = lhs.rpartition('.')
        if not series_name or agg not in _SLO_AGGS:
            raise err
        try:
            bound = float(rhs)
        except ValueError:
            raise err
        clauses.append(SLOClause(series_name, agg, op, bound, part))
    return clauses


class SLOMonitor(object):
    """Evaluates parsed SLO clauses against the windowed series registry.

    Each evaluation sets ``slo_ok{slo=<clause>}`` (1/0) and increments
    the ``slo_breaches{slo=<clause>}`` burn counter on violation; a
    clause whose series has no data yet is vacuously ok (cold start is
    not an outage)."""

    def __init__(self, clauses):
        self.clauses = list(clauses)

    @classmethod
    def from_env(cls):
        raw = os.environ.get(ENV_SLO, '').strip()
        if not raw:
            return None
        return cls(parse_slo_spec(raw))

    def evaluate(self):
        results = []
        all_ok = True
        ok_gauge = registry.gauge(
            'slo_ok', '1 when the SLO clause currently holds')
        burn = registry.counter(
            'slo_breaches', 'evaluations where the SLO clause was '
            'violated (burn counter)')
        for clause in self.clauses:
            s = series(clause.series)
            if clause.agg == 'rate':
                value = s.rate()
            elif clause.agg == 'mean':
                value = s.mean()
            else:
                value = s.percentile(50 if clause.agg == 'p50' else 99)
            if value is None:
                ok = True
            elif clause.op == '<':
                ok = value < clause.bound
            else:
                ok = value > clause.bound
            ok_gauge.labels(slo=clause.text).set(1 if ok else 0)
            if not ok:
                burn.labels(slo=clause.text).inc()
                all_ok = False
            results.append({'slo': clause.text, 'value': value,
                            'ok': ok})
        return {'ok': all_ok, 'clauses': results}


# ---------------------------------------------------------------------------
# training-fleet snapshot publish / aggregate (coordinator KV)
# ---------------------------------------------------------------------------


def publish_host_snapshot(rank, step, step_time_s=None):
    """Publish this host's metric snapshot through the coordinator KV at
    a step boundary (rank-keyed; last write wins — the aggregate wants
    the freshest boundary, not history)."""
    from ..fleet_runtime import coordinator  # lazy: pulls in jax
    doc = {'host': int(rank), 'step': int(step),
           'unix_time': time.time(), 'step_time_s': step_time_s,
           'metrics': registry.to_dict(),
           'series': series_snapshot()}
    return coordinator.kv_set('%shost%04d' % (METRICS_KV_PREFIX, rank),
                              json.dumps(doc))


def _labels_suffix(labels):
    if not labels:
        return ''
    return '{%s}' % ','.join('%s=%s' % (k, v)
                             for k, v in sorted(labels.items()))


def read_fleet_snapshots():
    """→ ``{rank: snapshot_doc}`` for every published host (one
    non-blocking KV directory poll)."""
    from ..fleet_runtime import coordinator  # lazy: pulls in jax
    out = {}
    for key, val in coordinator.kv_dir(METRICS_KV_PREFIX).items():
        try:
            doc = json.loads(val)
            out[int(doc['host'])] = doc
        except (ValueError, KeyError, TypeError):
            continue
    return out


def aggregate_fleet_snapshots(straggler=None, out_path=None, step=None):
    """Host-0 aggregation: fold every host's published snapshot into one
    fleet document (counter-sum / gauge-label semantics mirroring
    `merge_fleet_metrics`), feed per-host step times into `straggler`
    when given, and atomically export to `out_path` when given."""
    snaps = read_fleet_snapshots()
    fleet = {'hosts': sorted(snaps), 'step': step,
             'unix_time': time.time(), 'counters': {}, 'gauges': {},
             'step_time_s': {}, 'series': {}}
    for rank in sorted(snaps):
        doc = snaps[rank]
        for name, info in doc.get('metrics', {}).items():
            kind = info.get('type')
            if kind == 'counter':
                # counters sum across hosts per label-set
                for s in info.get('samples', []):
                    key = name + _labels_suffix(s.get('labels'))
                    fleet['counters'][key] = (
                        fleet['counters'].get(key, 0.0) + s['value'])
            elif kind == 'gauge':
                # gauges are per-host facts: label, never sum
                for s in info.get('samples', []):
                    key = name + _labels_suffix(s.get('labels'))
                    fleet['gauges'].setdefault(key, {})[
                        'host%d' % rank] = s['value']
        if doc.get('step_time_s') is not None:
            fleet['step_time_s'][str(rank)] = doc['step_time_s']
            if straggler is not None:
                straggler.record(rank, doc['step_time_s'])
        fleet['series']['host%d' % rank] = doc.get('series', {})
    if straggler is not None:
        fleet['straggler'] = straggler.evaluate(step=step)
    if out_path:
        from ..resilience.snapshot import atomic_write_bytes
        try:
            atomic_write_bytes(out_path,
                               json.dumps(fleet, indent=1).encode())
        except OSError:
            pass
    return fleet


# ---------------------------------------------------------------------------
# test / lifecycle hooks
# ---------------------------------------------------------------------------


def reset_distributed():
    """Drop process-wide state (tests; mirrors observability.reset())."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is not None:
            _RECORDER.close()
        _RECORDER = None
    with _SERIES_LOCK:
        _SERIES.clear()
