"""Runtime telemetry subsystem: metrics registry + step tracer + step logger.

The single switch is ``PADDLE_TPU_TELEMETRY`` (default off). Every
instrumentation site in the framework guards on the module-level bool
``_ENABLED`` — one attribute read — so the disabled hot path (eager dispatch
at ~10 µs/op) measurably pays nothing. With telemetry on:

- counters/gauges/histograms accumulate in :data:`metrics.registry`
  (dict export + Prometheus text exposition);
- a span tree per Executor.run / TrainStep call / tape dispatch is recorded
  by :data:`tracer.tracer` and written as Perfetto-loadable chrome-trace
  JSON — no jax.profiler required;
- one JSON line per step goes to ``$PADDLE_TPU_METRICS_DIR/steps.jsonl``.

Artifacts land in ``PADDLE_TPU_METRICS_DIR`` (when set) at interpreter exit
or on an explicit :func:`dump_artifacts` call:

    metrics.json   registry dict export
    metrics.prom   Prometheus text exposition
    trace.json     chrome trace (load in ui.perfetto.dev)
    steps.jsonl    structured per-step log

``tools/telemetry_report.py`` renders a run summary from that directory.
See docs/OBSERVABILITY.md for the metric catalog and span naming scheme.
"""
from __future__ import annotations

import atexit
import contextlib
import os
import time

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      registry)
from .tracer import NULL_SPAN, Span, StepTracer, tracer  # noqa: F401
from .steplog import StepLogger, step_logger  # noqa: F401
from .trace_context import TraceContext, TRACE_HEADER  # noqa: F401
from . import distributed  # noqa: F401

__all__ = ['enabled', 'enable', 'disable', 'telemetry_guard', 'metrics_dir',
           'span', 'instant', 'inc', 'set_gauge', 'observe', 'log_step',
           'record_op_dispatch', 'dump_artifacts', 'registry', 'tracer',
           'step_logger', 'TraceContext', 'TRACE_HEADER', 'distributed']

# THE hot-path flag. Instrumentation sites read this attribute directly
# (``if _obs._ENABLED:``); everything else in this module is off-path.
_ENABLED = os.environ.get('PADDLE_TPU_TELEMETRY', '0') not in ('0', '')

_atexit_registered = False


def enabled():
    return _ENABLED


def metrics_dir():
    """Artifact directory, or None (collect in memory only)."""
    return os.environ.get('PADDLE_TPU_METRICS_DIR') or None


def _register_atexit():
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(_atexit_dump)


def _atexit_dump():
    if _ENABLED and metrics_dir():
        try:
            dump_artifacts(metrics_dir())
        except Exception:
            pass   # interpreter teardown: never turn exit into a traceback


def enable(directory=None):
    """Turn telemetry on at runtime (the programmatic form of
    PADDLE_TPU_TELEMETRY=1). `directory` additionally points
    PADDLE_TPU_METRICS_DIR so artifacts auto-dump at exit."""
    global _ENABLED
    _ENABLED = True
    if directory is not None:
        os.environ['PADDLE_TPU_METRICS_DIR'] = str(directory)
    d = metrics_dir()
    if d:
        step_logger.open(d)
    _register_atexit()


def disable():
    global _ENABLED
    _ENABLED = False


@contextlib.contextmanager
def telemetry_guard(on=True, directory=None):
    """Scope telemetry on/off (tests, A/B overhead measurements). Restores
    the enabled flag, PADDLE_TPU_METRICS_DIR, and the step-log stream."""
    global _ENABLED
    old = _ENABLED
    old_dir = os.environ.get('PADDLE_TPU_METRICS_DIR')
    try:
        if on:
            enable(directory)
        else:
            _ENABLED = False
        yield
    finally:
        _ENABLED = old
        if directory is not None:
            step_logger.close()
            if old_dir is None:
                os.environ.pop('PADDLE_TPU_METRICS_DIR', None)
            else:
                os.environ['PADDLE_TPU_METRICS_DIR'] = old_dir


if _ENABLED:
    # env-enabled process: open the step log + arm the exit dump eagerly so
    # a script needs zero telemetry-specific code to produce artifacts
    if metrics_dir():
        step_logger.open(metrics_dir())
    _register_atexit()


# ---------------------------------------------------------------------------
# thin recording facade — every helper is a no-op when disabled, so call
# sites stay one-liners. The hottest site (tape dispatch) bypasses even
# these and checks `_ENABLED` inline.
# ---------------------------------------------------------------------------

def span(name, **args):
    """Context manager timing a named region into the trace."""
    if not _ENABLED:
        return NULL_SPAN
    return tracer.span(name, **args)


def instant(name, **args):
    if _ENABLED:
        tracer.instant(name, **args)


def inc(name, amount=1.0, help='', **labels):
    if _ENABLED:
        c = registry.counter(name, help)
        (c.labels(**labels) if labels else c).inc(amount)


def set_gauge(name, value, help='', **labels):
    if _ENABLED:
        g = registry.gauge(name, help)
        (g.labels(**labels) if labels else g).set(value)


def observe(name, value, help='', **labels):
    if _ENABLED:
        h = registry.histogram(name, help)
        (h.labels(**labels) if labels else h).observe(value)


def log_step(**record):
    if _ENABLED:
        step_logger.log(record)


# per-op dispatch is the one site hot enough to deserve a dedicated child
# cache: one dict lookup per call instead of registry.histogram + labels()
_dispatch_children = {}


def record_op_dispatch(op_type, seconds, cached):
    """Histogram sample for one eager tape dispatch (tape.dispatch_op)."""
    key = (op_type, cached)
    child = _dispatch_children.get(key)
    if child is None:
        child = registry.histogram(
            'tape_dispatch_seconds',
            'eager dygraph op dispatch latency by op (cached = kernel-cache '
            'hit path)').labels(op=op_type, cached=str(bool(cached)).lower())
        _dispatch_children[key] = child
    child.observe(seconds)


def reset():
    """Drop all recorded telemetry (tests). Keeps the enabled flag."""
    registry.reset()
    tracer.reset()
    _dispatch_children.clear()
    distributed.reset_distributed()


def dump_artifacts(directory=None):
    """Write metrics.json / metrics.prom / trace.json into `directory`
    (default $PADDLE_TPU_METRICS_DIR). Returns {artifact: path}."""
    import json
    directory = directory or metrics_dir()
    if not directory:
        raise ValueError(
            'dump_artifacts: no directory given and PADDLE_TPU_METRICS_DIR '
            'is unset')
    os.makedirs(directory, exist_ok=True)
    paths = {}
    m = os.path.join(directory, 'metrics.json')
    with open(m, 'w') as f:
        json.dump({'generated_unix_time': time.time(),
                   'metrics': registry.to_dict()}, f, indent=1)
    paths['metrics'] = m
    p = os.path.join(directory, 'metrics.prom')
    with open(p, 'w') as f:
        f.write(registry.prometheus_text())
    paths['prometheus'] = p
    t = os.path.join(directory, 'trace.json')
    tracer.dump(t)
    paths['trace'] = t
    if step_logger.path:
        paths['steps'] = step_logger.path
    return paths
