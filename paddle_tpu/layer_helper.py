"""LayerHelper: shared machinery for static-graph layer functions.

Parity with reference python/paddle/fluid/layer_helper.py: creates parameters
(+ their init ops in the startup program), temp output variables with shapes
inferred via jax.eval_shape over the op functional, and appends ops.
"""
from __future__ import annotations

import copy

import jax
import jax.numpy as jnp

from .core import unique_name
from .core.dtypes import convert_dtype, to_jax_dtype
from .framework import (Variable, default_main_program, default_startup_program,
                        shape_to_concrete, shape_from_concrete)
from .initializer import (ConstantInitializer, XavierInitializer)
from .param_attr import ParamAttr
from .ops.registry import get_op


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get('name')
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def input(self, name='input'):
        return self.kwargs[name]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get('param_attr'))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get('bias_attr'))

    # ---- variables ----
    def create_parameter(self, attr, shape, dtype='float32', is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        # copy before naming (ref layer_helper_base.py:296): a ParamAttr
        # with no explicit name reused across create_parameter calls must
        # yield DISTINCT parameters, not silently alias the first one
        attr = copy.copy(attr)
        if attr.name is None:
            attr.name = unique_name.generate('.'.join([self.name, 'w' if not is_bias else 'b']))
        init = attr.initializer or default_initializer or (
            ConstantInitializer(0.0) if is_bias else XavierInitializer())
        block = self.main_program.global_block()
        if block.has_var(attr.name):
            return block.var(attr.name)
        p = block.create_parameter(
            attr.name, [int(s) for s in shape], convert_dtype(dtype),
            trainable=attr.trainable, regularizer=attr.regularizer,
            learning_rate=attr.learning_rate,
            do_model_average=attr.do_model_average)
        # mirror into startup program with its init op
        sblock = self.startup_program.global_block()
        sp = sblock.create_parameter(
            attr.name, [int(s) for s in shape], convert_dtype(dtype),
            trainable=attr.trainable)
        init(sp, sblock)
        return p

    def create_variable_for_type_inference(self, dtype='float32', name=None):
        return self.main_program.current_block().create_var(
            name=name or unique_name.generate('.'.join([self.name, 'tmp'])),
            dtype=convert_dtype(dtype), shape=None)

    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, shape, dtype='float32', persistable=True,
                               name=None, stop_gradient=True):
        return self.main_program.global_block().create_var(
            name=name or unique_name.generate('.'.join([self.name, 'global'])),
            shape=[int(s) for s in shape], dtype=convert_dtype(dtype),
            persistable=persistable, stop_gradient=stop_gradient)

    # ---- ops ----
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = self.main_program.current_block().append_op(
            type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self._infer_shapes(op)
        return op

    def _infer_shapes(self, op):
        """Fill in missing output var shapes via jax.eval_shape on the op fn.

        When eval_shape cannot run (an input's shape is still unknown —
        typical inside control-flow sub-blocks — or the abstract eval
        raises), fall back to the static rule engine
        (paddle_tpu/analysis/infer.py) so declared output DTYPES stay
        truthful: before this fallback, an arg_max emitted on an
        unknown-shape input kept its input's float32 as the declared
        dtype, which anything reading declarations (the verifier, bucket
        sizing, donation stability) then mis-trusted."""
        try:
            opdef = get_op(op.type)
        except KeyError:
            return
        block = op.block

        def spec_of(name):
            v = block.var(name)
            if v.shape is None:
                return None
            return jax.ShapeDtypeStruct(shape_to_concrete(v.shape),
                                        to_jax_dtype(v.dtype))

        args = []
        for slot in opdef.input_slots:
            names = op.inputs.get(slot, [])
            if not names:
                args.append(None)
            elif slot in opdef.variadic:
                specs = [spec_of(n) for n in names]
                if any(s is None for s in specs):
                    return self._static_infer(op)
                args.append(specs)
            else:
                s = spec_of(names[0])
                if s is None:
                    return self._static_infer(op)
                args.append(s)
        from .ops.registry import NON_KERNEL_ATTRS
        attrs = {k: v for k, v in op.attrs.items()
                 if k not in NON_KERNEL_ATTRS}
        try:
            if opdef.needs_rng:
                key_spec = jax.ShapeDtypeStruct((2,), jnp.uint32)
                out = jax.eval_shape(
                    lambda key, *a: opdef.fn(*a, key=key, **attrs), key_spec, *args)
            else:
                out = jax.eval_shape(lambda *a: opdef.fn(*a, **attrs), *args)
        except Exception:
            return self._static_infer(op)
        outs = [out] if len(opdef.output_slots) == 1 else list(out)
        flat_out_names = []
        for slot in opdef.output_slots:
            flat_out_names.append(op.outputs.get(slot, []))
        # match: one result per output slot; variadic slot gets a list result
        for slot_names, res in zip(flat_out_names, outs):
            res_list = res if isinstance(res, (list, tuple)) else [res]
            for n, r in zip(slot_names, res_list):
                v = block.var(n)
                if v.shape is None:
                    v.shape = shape_from_concrete(r.shape)
                    v.dtype = convert_dtype(r.dtype)

    def _static_infer(self, op):
        """Best-effort declared-info refinement from the analysis rules
        when eval_shape cannot run: dtypes always (they are shape-
        independent facts the rules know exactly), shapes when the rule
        derives one (unknown dims map to -1)."""
        from .analysis.infer import infer_op
        try:
            result = infer_op(op, {}, op.block)
        except Exception:
            return
        if not result:
            return
        opdef = get_op(op.type)
        for slot in opdef.output_slots:
            names = op.outputs.get(slot, [])
            res = result.get(slot)
            infos = (list(res) if isinstance(res, (list, tuple))
                     else [res] * len(names))
            for n, info in zip(names, infos):
                if info is None or not op.block.has_var(n):
                    continue
                v = op.block.var(n)
                if v.shape is None:
                    if info.dtype is not None:
                        v.dtype = convert_dtype(info.dtype)
                    if info.shape is not None:
                        v.shape = info.display_shape()

    def append_activation(self, out):
        act = self.kwargs.get('act')
        if act is None:
            return out
        tmp = self.create_variable_for_type_inference(out.dtype)
        self.append_op(type=act, inputs={'x': out.name}, outputs={'Out': tmp.name})
        return tmp

    def append_bias_op(self, input_var, bias, axis=-1):
        if bias is None:
            return input_var
        tmp = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type='elementwise_add',
                       inputs={'x': input_var.name, 'y': bias.name},
                       outputs={'Out': tmp.name}, attrs={'axis': axis})
        return tmp
