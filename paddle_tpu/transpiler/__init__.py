"""Transpiler API shims (ref: python/paddle/fluid/transpiler/__init__.py).

The reference DistributeTranspiler rewrites a Program into trainer programs
(send/recv grad ops) + pserver programs (param update + listen_and_serv),
routed over RPC (ref: transpiler/distribute_transpiler.py). On TPU there are
no parameter servers: parameters are replicated over the device mesh and XLA
AllReduce over ICI replaces the grad send / param recv pair. The shim keeps
the full API surface so reference PS scripts run unmodified — the trainer
program is the original program (executed data-parallel via sharded feeds),
and pserver programs are empty placeholders.

memory_optimize / release_memory (ref: transpiler/memory_optimization_
transpiler.py) are no-ops: XLA's buffer assignment performs liveness-based
reuse during compilation, which is exactly the pass these implemented.
"""
from __future__ import annotations

import warnings

from ..framework import Program, default_main_program, default_startup_program

_ps_warned = False


def warn_ps_lowering(mode='sync'):
    """One-time, visible notice that PS-mode scripts change training
    semantics on TPU (VERDICT r4 weak #3): there are no parameter servers,
    so async/geo schedules lower to synchronous collective DP unless the
    in-process geo/local-SGD steps are used."""
    global _ps_warned
    if _ps_warned:
        return
    _ps_warned = True
    warnings.warn(
        f"parameter-server mode ({mode}) lowers to SYNCHRONOUS collective "
        "data parallelism on TPU: there are no pservers, gradients "
        "all-reduce over ICI every step. Async/geo-SGD staleness semantics "
        "are available in-process via paddle_tpu.parallel.geo_sgd."
        "GeoSGDStep / parallel.local_sgd.LocalSGDStep.",
        UserWarning, stacklevel=3)


class DistributeTranspilerConfig:
    """ref: transpiler/distribute_transpiler.py:DistributeTranspilerConfig."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.enable_dc_asgd = False
        self.mode = 'pserver'
        self.print_log = False
        self.wait_port = True
        self.runtime_split_send_recv = False
        self.sync_mode = True
        # geo-sgd knobs (accepted)
        self.geo_sgd_mode = False
        self.geo_sgd_need_push_nums = 100


class DistributeTranspiler:
    """ref: transpiler/distribute_transpiler.py:DistributeTranspiler.

    transpile() records the topology; get_trainer_program() returns the
    original main program unchanged — data parallelism comes from running it
    through a CompiledProgram/fleet with feeds sharded over the mesh 'dp'
    axis, so no send/recv ops are inserted. get_pserver_program() returns an
    empty Program: no process serves parameters on TPU.
    """

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._main = None
        self._startup = None
        self.trainer_id = 0
        self.trainers = 1
        self._pserver_eps = []

    def transpile(self, trainer_id, program=None, pservers='127.0.0.1:6174',
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint='127.0.0.1:6174'):
        warn_ps_lowering('sync' if sync_mode else 'async')
        self.trainer_id = trainer_id
        self.trainers = trainers
        self._main = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        if isinstance(pservers, str):
            self._pserver_eps = [e for e in pservers.split(',') if e]
        else:
            self._pserver_eps = list(pservers or [])
        self.config.sync_mode = sync_mode

    def get_trainer_program(self, wait_port=True):
        if self._main is None:
            raise RuntimeError("call transpile() before get_trainer_program()")
        return self._main

    def get_pserver_program(self, endpoint):
        if endpoint not in self._pserver_eps:
            raise ValueError(f"endpoint {endpoint!r} not in pserver list "
                             f"{self._pserver_eps}")
        return Program()

    def get_pserver_programs(self, endpoint):
        prog = self.get_pserver_program(endpoint)
        return prog, Program()

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        return self._startup if self._startup is not None else Program()


class GeoSgdTranspiler(DistributeTranspiler):
    """ref: transpiler/geo_sgd_transpiler.py — geo-SGD (delayed delta-sum
    sync) PS transpiler.

    The program-rewrite surface is kept (trainer program unchanged, empty
    pserver programs — no pservers exist on TPU); the geo STALENESS
    SEMANTICS — k local steps, then the summed deltas advance a shared base
    — are real and live in `paddle_tpu.parallel.geo_sgd.GeoSGDStep`, which
    `build_geo_step` constructs from this transpiler's config.
    """

    def __init__(self, config=None):
        super().__init__(config)
        self.config.geo_sgd_mode = True

    def transpile(self, trainer_id, program=None, pservers='127.0.0.1:6174',
                  trainers=1, sync_mode=False, startup_program=None,
                  current_endpoint='127.0.0.1:6174'):
        warn_ps_lowering('geo-sgd')
        super().transpile(trainer_id, program, pservers, trainers,
                          sync_mode, startup_program, current_endpoint)

    def build_geo_step(self, loss_fn, params, mesh, lr=0.1, axis='dp'):
        """The executable geo-SGD schedule for this config's push interval
        (`geo_sgd_need_push_nums`)."""
        from ..parallel.geo_sgd import GeoSGDStep
        return GeoSGDStep(loss_fn, params, mesh,
                          need_push_nums=self.config.geo_sgd_need_push_nums,
                          lr=lr, axis=axis)


class PSDispatcher:
    """ref: transpiler/ps_dispatcher.py:PSDispatcher — base placement
    policy mapping vars onto pserver endpoints (placement only; no RPC —
    irrelevant on TPU but kept executable for parity)."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError('Interface has not been implemented.')


class HashName(PSDispatcher):
    """ref ps_dispatcher.py:HashName — stable hash(name) % n placement."""

    def _hash_block(self, block_str, total):
        return hash(block_str) % total

    def dispatch(self, varlist):
        return [self._eps[self._hash_block(v.name, len(self._eps))]
                for v in varlist]


class RoundRobin(PSDispatcher):
    """ref ps_dispatcher.py:RoundRobin — cyclic placement."""

    def dispatch(self, varlist):
        out = []
        for v in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """No-op: XLA buffer assignment already does liveness-based reuse."""
    return None


def release_memory(input_program, skip_opt_set=None):
    return None


__all__ = ['DistributeTranspiler', 'DistributeTranspilerConfig',
           'GeoSgdTranspiler', 'PSDispatcher', 'HashName', 'RoundRobin',
           'memory_optimize', 'release_memory']
