"""TrainerDesc (ref: python/paddle/fluid/trainer_desc.py) — configuration
record for dataset-driven training (Executor.train_from_dataset).

The reference serializes a trainer_desc.proto consumed by C++ trainers;
here the same fields live in a dict and the Executor reads them directly
(fetch config, print period, debug flag).
"""

__all__ = ['TrainerDesc', 'MultiTrainer', 'DistMultiTrainer',
           'PipelineTrainer']


class TrainerDesc:
    """ref trainer_desc.py:TrainerDesc."""

    def __init__(self):
        self.proto_desc = {'class_name': '', 'device_worker_name': '',
                           'thread_num': 1, 'debug': False,
                           'fetch_config': {'fetch_var_names': [],
                                            'fetch_var_str_format': [],
                                            'print_period': 100}}
        self._program = None
        self._device_worker = None
        self._infer = False

    def _set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        fc = self.proto_desc['fetch_config']
        fc['fetch_var_names'] = [getattr(v, 'name', v) for v in fetch_vars]
        fc['fetch_var_str_format'] = list(fetch_info or [])
        fc['print_period'] = int(print_period)

    def _set_debug(self, debug):
        self.proto_desc['debug'] = bool(debug)

    def _set_thread(self, thread_num):
        self.proto_desc['thread_num'] = int(thread_num)

    def _set_device_worker(self, device_worker):
        self._device_worker = device_worker

    def _set_program(self, program):
        self._program = program

    def _set_infer(self, infer):
        self._infer = bool(infer)

    def _gen_trainer_desc(self):
        if self._device_worker is not None:
            self._device_worker._set_program(self._program)
            self._device_worker._set_infer(self._infer)
            self._device_worker._gen_worker_desc(self)

    def _desc(self):
        return self.proto_desc


class MultiTrainer(TrainerDesc):
    """ref trainer_desc.py:MultiTrainer — the default dense trainer."""

    def __init__(self):
        super().__init__()
        self.proto_desc['class_name'] = 'MultiTrainer'


class DistMultiTrainer(TrainerDesc):
    """ref trainer_desc.py:DistMultiTrainer — PS-mode trainer."""

    def __init__(self):
        super().__init__()
        self.proto_desc['class_name'] = 'DistMultiTrainer'


class PipelineTrainer(TrainerDesc):
    """ref trainer_desc.py:PipelineTrainer — pipeline trainer (the TPU
    pipeline itself is parallel/pipeline.py)."""

    def __init__(self):
        super().__init__()
        self.proto_desc['class_name'] = 'PipelineTrainer'
