"""Metrics (ref: python/paddle/fluid/metrics.py): MetricBase, CompositeMetric,
Precision, Recall, Accuracy, ChunkEvaluator, EditDistance, DetectionMAP, Auc.
Host-side accumulators over fetched numpy values, matching ref semantics.
"""
from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith('_'):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0 if isinstance(v, int) else 0.0)

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith('_')}


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        d = self.tp + self.fn
        return float(self.tp) / d if d else 0.0


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        return self.value / self.weight if self.weight else 0.0


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        p = self.num_correct_chunks / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        r = self.num_correct_chunks / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * p * r / (p + r) if (p + r) else 0.0
        return p, r, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances).reshape(-1)
        self.total_distance += float(np.sum(d))
        self.seq_num += int(np.asarray(seq_num).reshape(-1)[0])
        self.instance_error += int(np.sum(d != 0))

    def eval(self):
        avg = self.total_distance / self.seq_num if self.seq_num else 0.0
        err = self.instance_error / self.seq_num if self.seq_num else 0.0
        return avg, err


class Auc(MetricBase):
    def __init__(self, name=None, curve='ROC', num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] >= 2 \
            else preds.reshape(-1)
        bins = np.minimum((pos_prob * self._num_thresholds).astype(np.int64),
                          self._num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            p = self._stat_pos[i]
            n = self._stat_neg[i]
            auc += n * (tot_pos + p / 2.0)
            tot_pos += p
            tot_neg += n
        return auc / (tot_pos * tot_neg) if tot_pos * tot_neg else 0.0


class DetectionMAP:
    """ref: metrics.py:DetectionMAP — wraps the detection_map evaluation;
    full pipeline lands with layers.detection (SURVEY §2.2 detection suite)."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version='integral'):
        from .layers.detection import detection_map
        self.cur_map, self.accum_map = detection_map(
            input, gt_label, gt_box, class_num=class_num,
            background_label=background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version)

    def get_map_var(self):
        return self.cur_map, self.accum_map
