"""Dygraph gradient clipping classes (ref: python/paddle/fluid/
dygraph_grad_clip.py:34-191). Each takes/returns a list of
(parameter, gradient) pairs; clipping runs as jax ops so it stays on
device and fuses into a jitted step when traced.
"""
import jax.numpy as jnp

__all__ = ['GradClipByValue', 'GradClipByNorm', 'GradClipByGlobalNorm']


class GradClipBase:
    def _clip(self, para_and_grad):
        raise NotImplementedError

    def __call__(self, para_and_grad):
        return self._clip(para_and_grad)


class GradClipByValue(GradClipBase):
    """Clamp every gradient element to [min_value, max_value]
    (ref dygraph_grad_clip.py:46). With one argument, the range is
    symmetric: [-|v|, |v|]."""

    def __init__(self, min_value, max_value=None):
        if max_value is None:
            max_value = abs(min_value)
            min_value = -max_value
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def _clip(self, para_and_grad):
        return [(p, None if g is None
                 else jnp.clip(g, self.min_value, self.max_value))
                for p, g in para_and_grad]


class GradClipByNorm(GradClipBase):
    """Scale each gradient so its own L2 norm is at most clip_norm
    (ref dygraph_grad_clip.py:120)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, para_and_grad):
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, None))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, g * scale))
        return out


class GradClipByGlobalNorm(GradClipBase):
    """Scale ALL gradients jointly so the global L2 norm is at most
    max_global_norm (ref dygraph_grad_clip.py:191)."""

    def __init__(self, max_global_norm):
        self.max_global_norm = float(max_global_norm)

    def _clip(self, para_and_grad):
        grads = [g for _, g in para_and_grad if g is not None]
        if not grads:
            return para_and_grad
        global_norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in grads))
        scale = jnp.minimum(
            self.max_global_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        return [(p, None if g is None else g * scale)
                for p, g in para_and_grad]
