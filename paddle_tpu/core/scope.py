"""Variable scope: runtime storage for persistable variables.

Parity with the reference's framework::Scope
(/root/reference/paddle/fluid/framework/scope.h). In the TPU design a Scope is
a flat name → jax.Array store (a pytree leaf dict) so the whole training state
can be passed into / donated to a jitted step function.
"""
from __future__ import annotations


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self._parent = parent
        self._kids = []

    def new_scope(self):
        kid = Scope(parent=self)
        self._kids.append(kid)
        return kid

    def var(self, name):
        """Find-or-declare. Returns current value (may be None if undeclared)."""
        if name not in self._vars and (self._parent is None or self._parent.find(name) is None):
            self._vars[name] = None
        return self.find(name)

    def find(self, name):
        if name in self._vars:
            return self._vars[name]
        if self._parent is not None:
            return self._parent.find(name)
        return None

    def has(self, name):
        return name in self._vars or (self._parent is not None and self._parent.has(name))

    def set(self, name, value):
        # write where the var lives, else locally
        s = self
        while s is not None:
            if name in s._vars:
                s._vars[name] = value
                return
            s = s._parent
        self._vars[name] = value

    def erase(self, name):
        self._vars.pop(name, None)

    def local_names(self):
        return list(self._vars.keys())

    def all_items(self):
        items = {} if self._parent is None else self._parent.all_items()
        items.update(self._vars)
        return items

    def drop_kids(self):
        self._kids.clear()


_global_scope = Scope()


def global_scope():
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old
