"""Dtype system for paddle_tpu.

Parity with the reference's VarType dtypes
(/root/reference/paddle/fluid/framework/framework.proto: VarType.Type) but
TPU-first: bfloat16 is a first-class training dtype, float16 is a compat alias
path, and float64 is supported-but-discouraged (TPU emulates it slowly).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Canonical dtype names → jnp dtypes. Mirrors the reference's
# convert_np_dtype_to_dtype_ (python/paddle/fluid/framework.py:958).
_NAME_TO_DTYPE = {
    'bool': jnp.bool_,
    'int8': jnp.int8,
    'uint8': jnp.uint8,
    'int16': jnp.int16,
    'int32': jnp.int32,
    'int64': jnp.int64,
    'float16': jnp.float16,
    'bfloat16': jnp.bfloat16,
    'float32': jnp.float32,
    'float64': jnp.float64,
    'complex64': jnp.complex64,
}

FLOAT_DTYPES = ('float16', 'bfloat16', 'float32', 'float64')
INT_DTYPES = ('int8', 'uint8', 'int16', 'int32', 'int64')


def convert_dtype(dtype):
    """Normalize a dtype spec (str | np.dtype | jnp dtype) to canonical string."""
    if dtype is None:
        return 'float32'
    if isinstance(dtype, str):
        name = dtype.lower()
        if name in ('float', 'fp32'):
            name = 'float32'
        elif name in ('double',):
            name = 'float64'
        elif name in ('half', 'fp16'):
            name = 'float16'
        elif name in ('bf16',):
            name = 'bfloat16'
        elif name in ('int', 'long'):
            name = 'int64' if name == 'long' else 'int32'
        if name not in _NAME_TO_DTYPE:
            raise TypeError(f"unsupported dtype: {dtype!r}")
        return name
    # numpy / jax dtype objects
    name = np.dtype(dtype).name if not hasattr(dtype, 'name') else dtype.name
    if name not in _NAME_TO_DTYPE:
        raise TypeError(f"unsupported dtype: {dtype!r}")
    return name


def to_jax_dtype(dtype):
    """Canonical name → the dtype jax will actually use on device.

    int64/uint64 boundary (TPU-first contract): with jax x64 disabled (the
    default here — TPU integer units and HBM favor 32-bit), `int64`
    declarations COMPUTE in int32 on device. Mapping int64→int32 up front
    keeps jax from warning at every asarray; the executor's feed path
    guards values ≥ 2³¹ with a hard error instead of a silent wrap (see
    `check_int32_bounds`). Set JAX_ENABLE_X64=1 to opt into true 64-bit
    (e.g. embedding id spaces ≥ 2³¹) at double the index memory.
    """
    from jax import config as _cfg
    name = convert_dtype(dtype)
    if name == 'int64' and not _cfg.jax_enable_x64:
        return jnp.int32
    return _NAME_TO_DTYPE[name]


def runtime_int64():
    """The device dtype for values declared int64: int32 under the default
    x64-off config (see to_jax_dtype), real int64 when x64 is enabled.
    Library code uses this instead of jnp.int64 so jax never emits a
    truncation warning."""
    from jax import config as _cfg
    return jnp.int64 if _cfg.jax_enable_x64 else jnp.int32


_INT32_MAX = 2 ** 31 - 1
_INT32_MIN = -2 ** 31


def check_int32_bounds(value, name=''):
    """Raise on host-side int64 data that will not survive the int64→int32
    on-device mapping. Called on numpy feeds — never inside a jit."""
    import numpy as _np
    from jax import config as _cfg
    if _cfg.jax_enable_x64:
        return value
    a = _np.asarray(value)
    if a.dtype == _np.int64 and a.size and (
            a.max(initial=0) > _INT32_MAX or a.min(initial=0) < _INT32_MIN):
        raise OverflowError(
            f"int64 feed {name!r} holds values outside int32 range "
            f"[{_INT32_MIN}, {_INT32_MAX}]; on TPU int64 computes as int32 "
            "(see core/dtypes.py). Set JAX_ENABLE_X64=1 to enable true "
            "64-bit integers, or re-index the data below 2^31.")
    return value


def is_float(dtype):
    return convert_dtype(dtype) in FLOAT_DTYPES


def is_integer(dtype):
    return convert_dtype(dtype) in INT_DTYPES
