"""Dtype system for paddle_tpu.

Parity with the reference's VarType dtypes
(/root/reference/paddle/fluid/framework/framework.proto: VarType.Type) but
TPU-first: bfloat16 is a first-class training dtype, float16 is a compat alias
path, and float64 is supported-but-discouraged (TPU emulates it slowly).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Canonical dtype names → jnp dtypes. Mirrors the reference's
# convert_np_dtype_to_dtype_ (python/paddle/fluid/framework.py:958).
_NAME_TO_DTYPE = {
    'bool': jnp.bool_,
    'int8': jnp.int8,
    'uint8': jnp.uint8,
    'int16': jnp.int16,
    'int32': jnp.int32,
    'int64': jnp.int64,
    'float16': jnp.float16,
    'bfloat16': jnp.bfloat16,
    'float32': jnp.float32,
    'float64': jnp.float64,
    'complex64': jnp.complex64,
}

FLOAT_DTYPES = ('float16', 'bfloat16', 'float32', 'float64')
INT_DTYPES = ('int8', 'uint8', 'int16', 'int32', 'int64')


def convert_dtype(dtype):
    """Normalize a dtype spec (str | np.dtype | jnp dtype) to canonical string."""
    if dtype is None:
        return 'float32'
    if isinstance(dtype, str):
        name = dtype.lower()
        if name in ('float', 'fp32'):
            name = 'float32'
        elif name in ('double',):
            name = 'float64'
        elif name in ('half', 'fp16'):
            name = 'float16'
        elif name in ('bf16',):
            name = 'bfloat16'
        elif name in ('int', 'long'):
            name = 'int64' if name == 'long' else 'int32'
        if name not in _NAME_TO_DTYPE:
            raise TypeError(f"unsupported dtype: {dtype!r}")
        return name
    # numpy / jax dtype objects
    name = np.dtype(dtype).name if not hasattr(dtype, 'name') else dtype.name
    if name not in _NAME_TO_DTYPE:
        raise TypeError(f"unsupported dtype: {dtype!r}")
    return name


def to_jax_dtype(dtype):
    return _NAME_TO_DTYPE[convert_dtype(dtype)]


def is_float(dtype):
    return convert_dtype(dtype) in FLOAT_DTYPES


def is_integer(dtype):
    return convert_dtype(dtype) in INT_DTYPES
