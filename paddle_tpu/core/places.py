"""Device places.

Parity with the reference's platform::Place hierarchy
(/root/reference/paddle/fluid/platform/place.h): CPUPlace, CUDAPlace,
CUDAPinnedPlace. TPU-native design: the primary place is TPUPlace (an XLA
device); CUDAPlace is accepted as a compat shim that maps onto the accelerator
so existing reference scripts run unmodified (BASELINE.json north star).
"""
from __future__ import annotations

import jax


class Place:
    """Base class for device placements."""

    _device_kind = None  # 'cpu' | 'accel'

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        """Resolve to a concrete jax.Device."""
        if self._device_kind == 'cpu':
            devs = [d for d in jax.devices('cpu')] if _has_platform('cpu') else jax.devices()
        else:
            devs = jax.devices()  # default backend = accelerator when present
        return devs[self.device_id % len(devs)]


def _has_platform(name):
    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


class CPUPlace(Place):
    _device_kind = 'cpu'

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    """A single XLA accelerator device. The TPU-native analogue of CUDAPlace."""
    _device_kind = 'accel'


# The reference API names, mapped onto the accelerator so fluid scripts written
# for GPU run on TPU unmodified (see BASELINE.json north star).
class CUDAPlace(TPUPlace):
    pass


class XLAPlace(TPUPlace):
    pass


class CUDAPinnedPlace(Place):
    """Host memory staging area. On TPU, maps to host RAM feeding the HBM DMA
    path used by the DataLoader (ref: paddle/fluid/memory/memcpy.cc)."""
    _device_kind = 'cpu'

    def __init__(self):
        super().__init__(0)


def is_compiled_with_cuda():
    """Compat: reports whether an accelerator backend is present."""
    return jax.default_backend() != 'cpu'


def cuda_places(device_ids=None):
    """Compat shim for fluid.cuda_places(): one place per local accelerator."""
    n = len(jax.devices())
    ids = range(n) if device_ids is None else device_ids
    return [TPUPlace(i) for i in ids]


def cpu_places(device_count=1):
    return [CPUPlace() for _ in range(device_count)]


def tpu_places(device_ids=None):
    return cuda_places(device_ids)


def _get_paddle_place(place):
    """Normalize user-specified place (str | Place | None) to a Place."""
    if place is None:
        return TPUPlace(0) if is_compiled_with_cuda() else CPUPlace()
    if isinstance(place, Place):
        return place
    if isinstance(place, str):
        s = place.lower()
        if s == 'cpu':
            return CPUPlace()
        for prefix in ('tpu', 'gpu', 'cuda', 'xla'):
            if s.startswith(prefix):
                rest = s[len(prefix):].lstrip(':')
                return TPUPlace(int(rest) if rest else 0)
    raise ValueError(f"unknown place: {place!r}")


def cuda_pinned_places(device_count=None):
    """ref: fluid.cuda_pinned_places — pinned host staging areas; on TPU
    the DataLoader ring stages via device_put, so these are CPU places."""
    n = 1 if device_count is None else int(device_count)
    return [CUDAPinnedPlace() for _ in range(n)]
