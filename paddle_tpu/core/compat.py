"""jax API compatibility shims.

The framework tracks jax's public API, which moves: ``jax.shard_map``
graduated from ``jax.experimental.shard_map.shard_map``, and ``lax.pcast``
(the varying-manual-axes cast that the graduated shard_map's vma typing
requires) does not exist before the graduation. Every internal caller goes
through this module so the version probe happens exactly once, at import
time, instead of at every trace.
"""
from __future__ import annotations

import jax
from jax import lax as _lax

_HAS_NATIVE_SHARD_MAP = hasattr(jax, 'shard_map')

if _HAS_NATIVE_SHARD_MAP:
    _shard_map = jax.shard_map
else:                                   # pre-graduation jax (<= 0.4.x)
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
    """``jax.shard_map`` with a fallback to the experimental module.

    Signature intersection of both generations: (f, mesh, in_specs,
    out_specs). On the experimental fallback, replication checking is
    disabled — callers are written against the graduated API's vma typing
    (explicit ``pcast`` at every branch-merge point), which the old
    rep-checker does not understand.
    """
    if not _HAS_NATIVE_SHARD_MAP:
        kwargs.setdefault('check_rep', False)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


if hasattr(_lax, 'pcast'):
    pcast = _lax.pcast
else:
    def pcast(x, axis_name, to=None):
        """No-op stand-in: pre-vma jax has no varying/replicated type split,
        so the cast that keeps cond branches type-consistent under the
        graduated shard_map is vacuously satisfied."""
        return x
