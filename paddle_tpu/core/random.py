"""PRNG key plumbing.

TPU-first determinism story: one global seed → jax PRNG key tree. Static-graph
lowering folds (step_counter, op_index) into the base key so every random op
gets a distinct, reproducible stream; dygraph and initializers draw from a
global splitting generator. Replaces the reference's per-op `seed` attrs and
cuRAND states (ref: paddle/fluid/operators/dropout_op.cu seed handling).
"""
from __future__ import annotations

import jax


class KeyGenerator:
    def __init__(self, seed: int = 0):
        self.seed(seed)

    def seed(self, seed: int):
        self._base = jax.random.PRNGKey(int(seed))
        self._counter = 0

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self._base, self._counter)

    def base_key(self):
        return self._base


default_generator = KeyGenerator(0)


def seed(s: int):
    """Global seed entry point (ref: fluid.default_main_program().random_seed)."""
    from .. import framework
    framework.manual_seed(s)
    default_generator.seed(s)
    return default_generator
