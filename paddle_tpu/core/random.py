"""PRNG key plumbing.

TPU-first determinism story: one global seed → jax PRNG key tree. Static-graph
lowering folds (step_counter, op_index) into the base key so every random op
gets a distinct, reproducible stream; dygraph and initializers draw from a
global splitting generator. Replaces the reference's per-op `seed` attrs and
cuRAND states (ref: paddle/fluid/operators/dropout_op.cu seed handling).
"""
from __future__ import annotations

import contextlib

import jax


class KeyGenerator:
    """LAZY: building the PRNGKey initializes the jax backend, so it must
    not happen at construction — `import paddle_tpu` has to stay free of
    backend init (on a dead axon tunnel that first touch hangs forever,
    and it would land before any watchdog can be set up)."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._base = None
        self._counter = 0

    def seed(self, seed: int):
        self._seed = int(seed)
        self._base = None
        self._counter = 0

    @property
    def _key(self):
        if self._base is None:
            self._base = jax.random.PRNGKey(self._seed)
        return self._base

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self._key, self._counter)

    def state(self):
        """Resumable generator state (resilience checkpoints): the stream
        is fully determined by (seed, counter)."""
        return {'seed': self._seed, 'counter': self._counter}

    def set_state(self, state):
        """Restore a :meth:`state` snapshot — the next `next_key()` draws
        exactly what the captured process would have drawn."""
        self._seed = int(state['seed'])
        self._base = None            # lazily rebuilt from the seed
        self._counter = int(state['counter'])

    def base_key(self):
        return self._key

    @contextlib.contextmanager
    def bind_base(self, base_key):
        """Derive keys from `base_key` (possibly a jit tracer) inside the
        context. Used by `to_static` tracing so random ops fold counters into
        a per-call key argument instead of baking a host constant into the
        compiled program (which would freeze dropout masks across calls)."""
        old = self._base, self._counter
        self._base = base_key
        self._counter = 0
        try:
            yield
        finally:
            self._base, self._counter = old


default_generator = KeyGenerator(0)


def seed(s: int):
    """Global seed entry point (ref: fluid.default_main_program().random_seed)."""
    from .. import framework
    framework.manual_seed(s)
    default_generator.seed(s)
    return default_generator
