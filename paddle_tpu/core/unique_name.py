"""Unique name generator.

Parity with reference python/paddle/fluid/unique_name.py: generate(), guard(),
switch(). Used by LayerHelper to name parameters and temporaries.
"""
from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self, prefix=''):
        self.prefix = prefix
        self.ids = defaultdict(int)

    def __call__(self, key):
        tmp = self.ids[key]
        self.ids[key] += 1
        return f"{self.prefix}{key}_{tmp}"


generator = UniqueNameGenerator()


def generate(key):
    return generator(key)


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator if new_generator is not None else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
