"""Persistent cross-process XLA compilation cache.

The Executor/TrainStep in-process jit caches stop re-tracing within one
process, but every new process (a bench re-run after a tunnel drop, a second
fleet worker on the same host) still recompiled every program from scratch.
This module wires jax's persistent compilation cache underneath those jit
caches: compiled executables are serialized to a shared on-disk directory
keyed by (HLO, compile options, jax/XLA version), so a second cold process
deserializes instead of recompiling.

Environment knobs (documented in README):
- PADDLE_TPU_COMPILE_CACHE=0          disable entirely
- PADDLE_TPU_COMPILE_CACHE_DIR=<dir>  cache location
                                      (default ~/.cache/paddle_tpu/xla_cache)
- PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_SECS=<f>
                                      only persist compiles slower than this
                                      (default: jax's own 1.0s floor; set 0
                                      to persist everything, e.g. in tests)

Telemetry (PADDLE_TPU_TELEMETRY=1, docs/OBSERVABILITY.md): the Executor
reports its in-process program-cache lookups through record_program_cache
(compile_cache_hits / compile_cache_misses — a miss is a lower+compile), and
a best-effort jax monitoring listener maps the persistent layer's own events
onto persistent_cache_{hits,misses} plus a compile_cache_deserialize_seconds
histogram.
"""
from __future__ import annotations

import os

from .. import observability as _obs

_configured = None   # None = not attempted; False = disabled; str = cache dir
_listeners_installed = False


def record_program_cache(hit):
    """Executor program+shape jit-cache lookup result (a miss means the
    program gets lowered and XLA-compiled on its first execution)."""
    if _obs._ENABLED:
        if hit:
            _obs.inc('compile_cache_hits',
                     help='in-process program+shape step-cache hits')
        else:
            _obs.inc('compile_cache_misses',
                     help='in-process step-cache misses (lower + compile)')


def _install_jax_cache_listeners():
    """Best-effort: mirror jax's persistent-compilation-cache monitoring
    events into the metrics registry. jax internals — any failure is
    silently skipped (the in-process counters above still populate)."""
    global _listeners_installed
    if _listeners_installed:
        return
    _listeners_installed = True
    try:
        from jax._src import monitoring

        def on_event(event, **kw):
            if not _obs._ENABLED:
                return
            if event == '/jax/compilation_cache/cache_hits':
                _obs.inc('persistent_cache_hits',
                         help='persistent XLA cache deserializations')
            elif event == '/jax/compilation_cache/cache_misses':
                _obs.inc('persistent_cache_misses',
                         help='persistent XLA cache misses (full compile)')

        def on_duration(event, duration, **kw):
            if not _obs._ENABLED:
                return
            if event == '/jax/compilation_cache/cache_retrieval_time_sec':
                _obs.observe('compile_cache_deserialize_seconds', duration,
                             help='time deserializing a persisted executable')
            elif event == '/jax/compilation_cache/compile_time_saved_sec':
                _obs.observe('compile_cache_time_saved_seconds', duration,
                             help='compile seconds avoided by a cache hit')

        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
    except Exception:
        pass


def setup_persistent_cache():
    """Idempotently point jax at the on-disk compilation cache. Returns the
    cache dir, or None when disabled. Safe to call from every Executor /
    TrainStep constructor — only the first call does work."""
    global _configured
    _install_jax_cache_listeners()
    if _configured is not None:
        return _configured or None
    if os.environ.get('PADDLE_TPU_COMPILE_CACHE', '1') == '0':
        _configured = False
        return None
    import jax
    cache_dir = os.environ.get(
        'PADDLE_TPU_COMPILE_CACHE_DIR',
        os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu',
                     'xla_cache'))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        min_secs = os.environ.get('PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_SECS')
        if min_secs is not None:
            jax.config.update('jax_persistent_cache_min_compile_time_secs',
                              float(min_secs))
            jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)
    except Exception:
        _configured = False
        return None
    # jax latches cache eligibility on the FIRST compile of the process; if
    # anything compiled before we configured the dir (eager ops during
    # import, scope init), un-latch so our programs still reach the disk
    # cache. Best-effort: on jax versions without reset_cache, skip.
    try:
        from jax._src import compilation_cache as _cc
        if getattr(_cc, '_cache_checked', False) and \
                not getattr(_cc, '_cache_used', False):
            _cc.reset_cache()
    except Exception:
        pass
    _configured = cache_dir
    return cache_dir
