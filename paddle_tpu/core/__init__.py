from . import dtypes, places, unique_name, scope
from .dtypes import convert_dtype, to_jax_dtype
from .places import (CPUPlace, TPUPlace, CUDAPlace, XLAPlace, CUDAPinnedPlace,
                     Place, is_compiled_with_cuda, cuda_places, cpu_places,
                     tpu_places, _get_paddle_place)
from .scope import Scope, global_scope, scope_guard
