"""Non-blocking fetch handles + the bounded in-flight dispatch window.

The async train-loop pipeline (PERF.md §12): every `Executor.run` fetch ends
in `np.asarray`, a blocking device→host sync that serializes host feed prep,
device compute, and D2H — the per-step input/host-wait loss arXiv:1909.09756
identifies as the dominant non-compute cost at high step rates. Instead of
materializing eagerly, the executor (and `TrainStep(async_fetch=True)`) hands
back a :class:`FetchHandle` wrapping the still-on-device array; jax's async
dispatch keeps computing in the background while the host prepares and
dispatches the next step. `np.asarray(handle)` / `handle.numpy()` is the one
synchronization point, and :class:`InflightWindow` bounds how many dispatched
steps may be outstanding (default K=2, classic double buffering) so the
dispatch queue and fetch-buffer memory stay bounded.

Snapshot semantics: jax arrays are immutable, so holding the fetched array IS
a point-in-time snapshot — with one exception: buffer donation. A pending
handle whose fetch aliases a persistable would be overwritten in place when a
later step donates that state buffer, so the executor consults
:meth:`InflightWindow.protected_names` and keeps those names out of the
donated set until the handle materializes (or is dropped — handles are held
weakly, a dropped handle neither blocks admission nor pins its buffers).
"""
from __future__ import annotations

import collections
import os
import time
import weakref

import numpy as np

from .. import observability as _obs

__all__ = ['FetchHandle', 'InflightWindow', 'resolve_inflight_steps']


def resolve_inflight_steps(exec_strategy=None, default=0):
    """→ K, the max dispatched-but-unconsumed steps (0 = synchronous loop).

    Resolution order: ``PADDLE_TPU_ASYNC`` overrides everything — ``0``
    forces the synchronous loop (exact pre-pipeline behavior), ``1`` enables
    the default double-buffered window (K=2), any larger integer is K
    itself. With the env unset, ``ExecutionStrategy.num_inflight_steps > 1``
    enables the window at that depth; otherwise `default` applies."""
    env = os.environ.get('PADDLE_TPU_ASYNC', '').strip()
    if env:
        if env == '0':
            return 0
        try:
            k = int(env)
        except ValueError:
            return 2
        return 2 if k <= 1 else k
    if exec_strategy is not None:
        try:
            k = int(getattr(exec_strategy, 'num_inflight_steps', 1) or 1)
        except (TypeError, ValueError):
            k = 1
        if k > 1:
            return k
    return default


class FetchHandle:
    """A pending fetch: the on-device result of a dispatched step whose
    device→host materialization is deferred until the value is actually
    read. `numpy()` / `np.asarray(handle)` / `float(handle)` materialize
    (and cache) the host array; `block_until_ready()` waits for the device
    computation without a host copy. After materialization the device
    reference is dropped so a kept handle pins host memory only."""

    __slots__ = ('_value', '_host', '_name', '_check_nan', '__weakref__')

    def __init__(self, value, name=None, check_nan=False):
        self._value = value          # jax.Array, possibly still computing
        self._host = None            # cached np.ndarray once materialized
        self._name = name
        # FLAGS_check_nan_inf captured at dispatch: the scan runs at
        # materialization time instead of forcing a per-step sync
        # (docs/OBSERVABILITY.md "NaN/Inf wiring")
        self._check_nan = check_nan

    # -- metadata (never synchronizes) ---------------------------------
    @property
    def name(self):
        return self._name

    @property
    def shape(self):
        v = self._host if self._value is None else self._value
        return tuple(v.shape)

    @property
    def dtype(self):
        return (self._host if self._value is None else self._value).dtype

    @property
    def nbytes(self):
        v = self._host if self._value is None else self._value
        return getattr(v, 'nbytes', 0)

    @property
    def materialized(self):
        return self._host is not None

    @property
    def done(self):
        """True once the device computation finished (or the handle was
        materialized); never blocks."""
        if self._host is not None:
            return True
        try:
            return bool(self._value.is_ready())
        except (AttributeError, RuntimeError):
            return True          # non-jax value: nothing pending

    def device_array(self):
        """The wrapped value WITHOUT forcing a device→host copy: the
        still-on-device jax array while unmaterialized, the cached host
        array after. The supervisor's skip policy uses this to write a
        pre-step snapshot back into the scope as a device-to-device
        assignment instead of a D2H+H2D round trip."""
        return self._value if self._value is not None else self._host

    # -- synchronization -----------------------------------------------
    def block_until_ready(self):
        """Wait for the device computation; the value stays on device."""
        if self._host is None:
            try:
                self._value.block_until_ready()
            except AttributeError:
                pass
        return self

    def numpy(self):
        """Materialize (D2H copy), cache, and return the host array. The
        wait+copy is recorded as `fetch_materialize_seconds`; with
        FLAGS_check_nan_inf on at dispatch time, the non-finite scan runs
        here — once, on the host copy — instead of re-serializing the
        pipelined loop."""
        if self._host is None:
            t0 = time.perf_counter()
            arr = np.asarray(self._value)
            if _obs._ENABLED:
                _obs.observe(
                    'fetch_materialize_seconds', time.perf_counter() - t0,
                    help='device→host wait+copy per FetchHandle '
                         'materialization (the async loop\'s only sync '
                         'point)')
            self._host = arr
            self._value = None   # release the device buffer reference
            if self._check_nan:
                self._scan_finite(arr)
        return self._host

    def _scan_finite(self, arr):
        if arr.dtype.kind == 'f' and not np.isfinite(arr).all():
            _obs.inc('nonfinite_detections', 1,
                     help='fetched variables containing NaN/Inf '
                          '(FLAGS_check_nan_inf)')
            _obs.instant('nonfinite_detected',
                         variables=self._name or 'fetch')
            from ..debugging import check_numerics
            check_numerics(arr, self._name or 'fetch')

    # -- array protocol ------------------------------------------------
    def __array__(self, dtype=None, copy=None):
        a = self.numpy()
        if dtype is not None and a.dtype != np.dtype(dtype):
            return a.astype(dtype)
        return np.array(a) if copy else a

    def __float__(self):
        return float(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __len__(self):
        return self.shape[0] if self.shape else 0

    def __repr__(self):
        state = ('materialized' if self.materialized
                 else 'ready' if self.done else 'pending')
        return (f"FetchHandle({self._name or '?'}, shape={self.shape}, "
                f"dtype={self.dtype}, {state})")


class _InflightStep:
    """One dispatched step: weak refs to its fetch handles."""

    __slots__ = ('handles',)

    def __init__(self, handles):
        self.handles = [weakref.ref(h) for h in handles]

    def done(self):
        for r in self.handles:
            h = r()
            if h is not None and not h.done:
                return False
        return True

    def block(self):
        for r in self.handles:
            h = r()
            if h is not None:
                h.block_until_ready()


class InflightWindow:
    """FIFO of dispatched-but-unconsumed steps. `admit(k)` enforces the
    K-in-flight bound by blocking on the OLDEST pending step only when the
    window is full — so host-side work for step N+1 overlaps device
    execution of steps N..N-K+1. Entries whose handles are all ready,
    materialized, or garbage-collected retire for free.

    Window occupancy and snapshot protection have different lifetimes: a
    step leaves the WINDOW once its device computation finished (ready),
    but a persistable-aliasing handle stays donation-PROTECTED until the
    user actually materializes (or drops) it — whether XLA gives a fetch
    output its own buffer or aliases it with the state output is a backend
    detail the snapshot guarantee must not depend on."""

    def __init__(self):
        self._entries = collections.deque()
        self._snapshots = []      # weak refs to persistable-fetch handles

    def retire(self):
        while self._entries and self._entries[0].done():
            self._entries.popleft()
        return self

    def admit(self, k):
        """Call BEFORE dispatching a new step: waits until < k outstanding."""
        self.retire()
        while len(self._entries) >= max(1, int(k)):
            self._entries.popleft().block()

    def push(self, handles, protected=()):
        self._entries.append(_InflightStep(handles))
        for h in handles:
            if h.name in protected:
                self._snapshots.append(weakref.ref(h))
        if _obs._ENABLED:
            _obs.set_gauge(
                'executor_inflight_steps', len(self._entries),
                help='dispatched steps whose fetch handles are still '
                     'pending (async pipeline window occupancy)')

    def protect(self, handles):
        """Register snapshot protection WITHOUT occupying the dispatch
        window: each handle's named buffer stays out of the donated set
        until the handle materializes or is dropped. This is the zero-copy
        checkpoint capture path (resilience/state.py) — the handles are
        point-in-time state snapshots a background writer will materialize,
        not step outputs, so they must not gate `admit`."""
        for h in handles:
            self._snapshots.append(weakref.ref(h))

    def protected_names(self):
        """Persistable names snapshotted by a live, not-yet-materialized
        handle: the executor must not donate their buffers this step."""
        live, names = [], set()
        for r in self._snapshots:
            h = r()
            if h is not None and not h.materialized:
                live.append(r)
                names.add(h.name)
        self._snapshots = live
        return names

    def drain(self):
        while self._entries:
            self._entries.popleft().block()

    def __len__(self):
        self.retire()
        return len(self._entries)
