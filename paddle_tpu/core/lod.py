"""LoDTensor: the unified ragged-sequence container (SURVEY §2.1).

Parity with the reference's LoDTensor
(/root/reference/paddle/fluid/framework/lod_tensor.h and the pybind surface
python/paddle/fluid/lod_tensor.py: create_lod_tensor,
create_random_int_lodtensor, recursive_sequence_lengths). The TPU
formulation is the (padded data, lengths) pair the masked sequence ops
already consume — this class packages it with the reference's LoD
accessors so ragged batches travel as ONE object:

    t = fluid.create_lod_tensor([[1, 2], [3, 4, 5]], [[2, 3]], place)
    exe.run(feed={'words': t}, ...)         # Executor unpacks data+lengths

Level-1 LoD (batch of sequences) maps exactly; deeper nesting is stored as
the reference does (recursive lengths) with the innermost level padded.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ['LoDTensor', 'create_lod_tensor', 'create_random_int_lodtensor']


class LoDTensor:
    """Padded dense data + per-row valid lengths (+ full recursive lengths
    for API parity). `data` is (B, T, ...) with rows padded to T."""

    def __init__(self, data=None, recursive_seq_lens=None):
        self._data = None if data is None else np.asarray(data)
        self._recursive_seq_lens: List[List[int]] = \
            [list(l) for l in (recursive_seq_lens or [])]

    # ---- reference API surface ----
    def set(self, data, place=None):
        self._data = np.asarray(data)

    def set_recursive_sequence_lengths(self, lengths):
        self._recursive_seq_lens = [list(l) for l in lengths]

    def recursive_sequence_lengths(self):
        return [list(l) for l in self._recursive_seq_lens]

    def set_lod(self, lod):
        """Legacy offset-style LoD ([[0, 2, 5]] ≡ lengths [[2, 3]])."""
        self._recursive_seq_lens = [
            [int(level[i + 1] - level[i]) for i in range(len(level) - 1)]
            for level in lod]

    def lod(self):
        out = []
        for lengths in self._recursive_seq_lens:
            offs = [0]
            for n in lengths:
                offs.append(offs[-1] + int(n))
            out.append(offs)
        return out

    def has_valid_recursive_sequence_lengths(self):
        if not self._recursive_seq_lens:
            return self._data is not None
        n = sum(self._recursive_seq_lens[-1])
        flat_rows = int(np.prod(self._data.shape[:2])) \
            if self._data is not None and self._data.ndim >= 2 else None
        return flat_rows is None or n <= flat_rows

    def shape(self):
        return tuple(self._data.shape) if self._data is not None else ()

    # ---- TPU pair view ----
    @property
    def data(self):
        """Padded (B, T, ...) array."""
        return self._data

    @property
    def lengths(self):
        """(B,) int64 valid lengths of the innermost level."""
        if not self._recursive_seq_lens:
            if self._data is None:
                return np.zeros((0,), np.int64)
            return np.full((self._data.shape[0],), self._data.shape[1],
                           np.int64)
        return np.asarray(self._recursive_seq_lens[-1], np.int64)

    def to_rows(self):
        """Back to a python list of per-sequence arrays (unpadded)."""
        return [np.asarray(self._data[i, :n])
                for i, n in enumerate(self.lengths)]

    def __array__(self, dtype=None):
        a = self._data
        return a if dtype is None else a.astype(dtype)

    def __repr__(self):
        return (f"LoDTensor(shape={self.shape()}, "
                f"recursive_seq_lens={self._recursive_seq_lens})")


def _pad_rows(rows, dtype=None):
    rows = [np.atleast_1d(np.asarray(r, dtype)) for r in rows]
    maxlen = max((r.shape[0] for r in rows), default=0)
    tail = rows[0].shape[1:] if rows else ()
    out = np.zeros((len(rows), maxlen) + tail,
                   rows[0].dtype if rows else np.float32)
    for i, r in enumerate(rows):
        out[i, :r.shape[0]] = r
    return out


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """ref: python/paddle/fluid/lod_tensor.py:create_lod_tensor. Accepts a
    list of per-sequence rows, a flat (sum_len, ...) array + lengths, or an
    existing LoDTensor (copied with new lengths)."""
    if isinstance(data, LoDTensor):
        return LoDTensor(data.data, recursive_seq_lens)
    lengths = list(recursive_seq_lens[-1]) if recursive_seq_lens else []
    if isinstance(data, (list, tuple)):
        return LoDTensor(_pad_rows(list(data)), recursive_seq_lens)
    arr = np.asarray(data)
    if lengths and arr.shape[0] == int(np.sum(lengths)):
        # flat ragged layout (the reference's storage): split + pad
        rows, off = [], 0
        for n in lengths:
            rows.append(arr[off:off + int(n)])
            off += int(n)
        return LoDTensor(_pad_rows(rows), recursive_seq_lens)
    return LoDTensor(arr, recursive_seq_lens)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=10):
    """ref: lod_tensor.py:create_random_int_lodtensor."""
    lengths = list(recursive_seq_lens[-1])
    rows = [np.random.randint(low, high + 1,
                              (int(n),) + tuple(base_shape)).astype(np.int64)
            for n in lengths]
    return LoDTensor(_pad_rows(rows), recursive_seq_lens)
