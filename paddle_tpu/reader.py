"""DataLoader + reader decorators (ref: python/paddle/fluid/reader.py and
python/paddle/reader/decorator.py).

TPU design: a background thread pipelines host batching and `jax.device_put`
into a depth-k ring so host→HBM DMA overlaps device compute (the analogue of
the reference's BufferedReader + CUDAPinnedPlace staging,
paddle/fluid/operators/reader/buffered_reader.cc).
"""
from __future__ import annotations

import itertools
import queue
import random as pyrandom
import threading
import time

import numpy as np
import jax

from . import observability as _obs

__all__ = ['DataLoader', 'batch', 'shuffle', 'buffered', 'map_readers',
           'xmap_readers', 'chain', 'compose', 'firstn', 'cache',
           'multiprocess_reader']


# ---------------------------------------------------------------------------
# reader decorators (paddle.reader.* parity)
# ---------------------------------------------------------------------------

def batch(reader, batch_size, drop_last=False):
    def r():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return r


def shuffle(reader, buf_size):
    def r():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                pyrandom.shuffle(buf)
                yield from buf
                buf = []
        pyrandom.shuffle(buf)
        yield from buf
    return r


def buffered(reader, size):
    def r():
        q = queue.Queue(maxsize=size)
        end = object()

        def fill():
            for item in reader():
                q.put(item)
            q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                break
            yield item
    return r


def map_readers(func, *readers):
    def r():
        its = [rd() for rd in readers]
        for items in zip(*its):
            yield func(*items)
    return r


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool mapped reader (ref uses processes; threads suffice since
    the heavy lifting is numpy releasing the GIL)."""
    def r():
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(process_num) as pool:
            window = []
            for item in reader():
                window.append(pool.submit(mapper, item))
                if len(window) >= buffer_size:
                    yield window.pop(0).result()
            for f in window:
                yield f.result()
    return r


def chain(*readers):
    def r():
        for rd in readers:
            yield from rd()
    return r


def compose(*readers, check_alignment=True):
    def r():
        for items in zip(*[rd() for rd in readers]):
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)
    return r


def firstn(reader, n):
    def r():
        return itertools.islice(reader(), n)
    return r


def cache(reader):
    data = []

    def r():
        if not data:
            data.extend(reader())
        return iter(data)
    return r


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Parity shim: fans readers out over threads (process isolation is not
    needed without the GIL-bound C++ feed path)."""
    return buffered(chain(*readers), queue_size)


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------

class _GeneratorLoader:
    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False, use_multiprocess=False,
                 drop_last=True):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._batch_reader = None
        self._places = None
        self._feeder = None
        self._drop_last = drop_last

    # -- configuration (ref API) --
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        from .data_feeder import DataFeeder
        feeder = DataFeeder(self._feed_list)

        def batch_reader():
            """Batch in the native C++ pipeline core when samples are
            fixed-shape numeric tuples; fall back to the python batcher."""
            import itertools
            from . import native
            it = iter(reader())
            try:
                first = next(it)
            except StopIteration:
                return
            fields = first if isinstance(first, (list, tuple)) else (first,)
            arrs = [np.asarray(f) for f in fields]
            stream = itertools.chain([first], it)
            if native.is_native() and all(a.dtype.kind in 'fiub'
                                          for a in arrs):
                pipe = native.TupleDataPipeline(
                    [a.shape for a in arrs], [a.dtype for a in arrs],
                    batch_size, drop_last=drop_last)
                pipe.feed(stream)
                for batch_fields in pipe:
                    yield feeder.feed_batch(batch_fields)
            else:
                for rows in batch(lambda: stream, batch_size, drop_last)():
                    yield feeder.feed(rows)
        self._batch_reader = batch_reader
        self._places = places
        return self

    def set_sample_list_generator(self, reader, places=None):
        from .data_feeder import DataFeeder
        feeder = DataFeeder(self._feed_list)

        def batch_reader():
            for rows in reader():
                yield feeder.feed(rows)
        self._batch_reader = batch_reader
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        def batch_reader():
            for arrs in reader():
                if isinstance(arrs, dict):
                    yield arrs
                else:
                    yield {
                        (v.name if hasattr(v, 'name') else f'feed_{i}'): a
                        for i, (v, a) in enumerate(
                            zip(self._feed_list, arrs))}
        self._batch_reader = batch_reader
        self._places = places
        return self

    # -- iteration: background prefetch of device arrays --
    # py_reader-era method names (ref layers/io.py:549 decorate_*)
    decorate_sample_generator = set_sample_generator
    decorate_sample_list_generator = set_sample_list_generator
    decorate_batch_generator = set_batch_generator
    decorate_tensor_provider = set_batch_generator
    decorate_paddle_reader = set_sample_list_generator

    def __iter__(self):
        q = queue.Queue(maxsize=self._capacity)
        end = object()
        err_box = []

        def producer():
            from .core.lod import LoDTensor
            try:
                for feed in self._batch_reader():
                    # LoDTensors pass through intact (the Executor unpacks
                    # data + lengths); dense arrays stage onto the device
                    staged = {k: (v if isinstance(v, LoDTensor) else
                                  jax.device_put(np.ascontiguousarray(v)))
                              for k, v in feed.items()}
                    if _obs._ENABLED:
                        _obs.inc('dataloader_staged_bytes',
                                 sum(getattr(v, 'nbytes', 0)
                                     for v in staged.values()),
                                 help='bytes staged host→device by the '
                                      'DataLoader producer thread')
                    q.put(staged)
            except BaseException as e:   # surface in the consumer, not stderr
                err_box.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            if _obs._ENABLED:
                # consumer-side input starvation: time blocked on the ring.
                # A well-fed loop keeps this near zero; a starved one makes
                # the device wait on the host (arXiv:1909.09756's per-step
                # input-wait signal). wait_seconds_total / wall time is the
                # starvation fraction telemetry_report.py prints.
                t0 = time.perf_counter()
                item = q.get()
                wait = time.perf_counter() - t0
                _obs.observe('dataloader_wait_seconds', wait,
                             help='consumer wait per batch on the prefetch '
                                  'ring (input starvation)')
                _obs.inc('dataloader_wait_seconds_total', wait,
                         help='cumulative consumer input-starvation wait')
                _obs.set_gauge('dataloader_last_wait_seconds', wait,
                               help='most recent per-batch input wait')
                if item is not end:
                    _obs.inc('dataloader_batches',
                             help='batches yielded by DataLoader')
            else:
                item = q.get()
            if item is end:
                if err_box:
                    raise err_box[0]
                break
            if self._return_list:
                yield [item[k] for k in item]
            else:
                yield item

    def __call__(self):
        return iter(self)

    def start(self):
        pass

    def reset(self):
        pass


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        return _GeneratorLoader(feed_list, capacity, use_double_buffer,
                                iterable, return_list, use_multiprocess,
                                drop_last)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        loader = _GeneratorLoader()
        loader.set_batch_generator(lambda: iter(dataset))
        return loader
