"""DataLoader + reader decorators (ref: python/paddle/fluid/reader.py and
python/paddle/reader/decorator.py).

TPU design: a background thread pipelines host batching and `jax.device_put`
into a depth-k ring so host→HBM DMA overlaps device compute (the analogue of
the reference's BufferedReader + CUDAPinnedPlace staging,
paddle/fluid/operators/reader/buffered_reader.cc).
"""
from __future__ import annotations

import itertools
import queue
import random as pyrandom
import threading
import time

import numpy as np
import jax

from . import observability as _obs
from .resilience import watchdog as _watchdog

__all__ = ['DataLoader', 'batch', 'shuffle', 'buffered', 'map_readers',
           'xmap_readers', 'chain', 'compose', 'firstn', 'cache',
           'multiprocess_reader']


# ---------------------------------------------------------------------------
# reader decorators (paddle.reader.* parity)
# ---------------------------------------------------------------------------

def batch(reader, batch_size, drop_last=False):
    def r():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return r


def shuffle(reader, buf_size):
    def r():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                pyrandom.shuffle(buf)
                yield from buf
                buf = []
        pyrandom.shuffle(buf)
        yield from buf
    return r


def buffered(reader, size):
    def r():
        q = queue.Queue(maxsize=size)
        end = object()
        err_box = []

        def fill():
            # an exception in the fill thread must still enqueue the `end`
            # sentinel and surface in the CONSUMER (as _GeneratorLoader's
            # producer does) — dying silently leaves the consumer blocked
            # on q.get() forever
            try:
                for item in reader():
                    q.put(item)
            except BaseException as e:
                err_box.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                if err_box:
                    raise err_box[0]
                break
            yield item
    return r


def map_readers(func, *readers):
    def r():
        its = [rd() for rd in readers]
        for items in zip(*its):
            yield func(*items)
    return r


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool mapped reader (ref uses processes; threads suffice since
    the heavy lifting is numpy releasing the GIL)."""
    def r():
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(process_num) as pool:
            window = []
            for item in reader():
                window.append(pool.submit(mapper, item))
                if len(window) >= buffer_size:
                    yield window.pop(0).result()
            for f in window:
                yield f.result()
    return r


def chain(*readers):
    def r():
        for rd in readers:
            yield from rd()
    return r


def compose(*readers, check_alignment=True):
    def r():
        for items in zip(*[rd() for rd in readers]):
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)
    return r


def firstn(reader, n):
    def r():
        return itertools.islice(reader(), n)
    return r


def cache(reader):
    data = []

    def r():
        if not data:
            data.extend(reader())
        return iter(data)
    return r


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Parity shim: fans readers out over threads (process isolation is not
    needed without the GIL-bound C++ feed path)."""
    return buffered(chain(*readers), queue_size)


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------

class _GeneratorLoader:
    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False, use_multiprocess=False,
                 drop_last=True):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._batch_reader = None
        self._places = None
        self._feeder = None
        self._drop_last = drop_last
        # resume cursor (paddle_tpu/resilience/): epoch = completed passes,
        # consumed = batches YIELDED to the consumer this epoch (batches
        # staged in the prefetch ring but never consumed don't count — a
        # resumed run replays them). Assumes one active iteration at a time.
        self._epoch = 0
        self._consumed = 0
        self._skip = 0
        # fleet sharding (fleet_runtime/): rows this host keeps of every
        # batch — None means unsharded
        self._shard_n = None
        self._shard_id = None

    # -- configuration (ref API) --
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        from .data_feeder import DataFeeder
        feeder = DataFeeder(self._feed_list)

        def batch_reader():
            """Batch in the native C++ pipeline core when samples are
            fixed-shape numeric tuples; fall back to the python batcher."""
            import itertools
            from . import native
            it = iter(reader())
            try:
                first = next(it)
            except StopIteration:
                return
            fields = first if isinstance(first, (list, tuple)) else (first,)
            arrs = [np.asarray(f) for f in fields]
            stream = itertools.chain([first], it)
            if native.is_native() and all(a.dtype.kind in 'fiub'
                                          for a in arrs):
                pipe = native.TupleDataPipeline(
                    [a.shape for a in arrs], [a.dtype for a in arrs],
                    batch_size, drop_last=drop_last)
                pipe.feed(stream)
                for batch_fields in pipe:
                    yield feeder.feed_batch(batch_fields)
            else:
                for rows in batch(lambda: stream, batch_size, drop_last)():
                    yield feeder.feed(rows)
        self._batch_reader = batch_reader
        self._places = places
        return self

    def set_sample_list_generator(self, reader, places=None):
        from .data_feeder import DataFeeder
        feeder = DataFeeder(self._feed_list)

        def batch_reader():
            for rows in reader():
                yield feeder.feed(rows)
        self._batch_reader = batch_reader
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        def batch_reader():
            for arrs in reader():
                if isinstance(arrs, dict):
                    yield arrs
                else:
                    yield {
                        (v.name if hasattr(v, 'name') else f'feed_{i}'): a
                        for i, (v, a) in enumerate(
                            zip(self._feed_list, arrs))}
        self._batch_reader = batch_reader
        self._places = places
        return self

    # -- iteration: background prefetch of device arrays --
    # py_reader-era method names (ref layers/io.py:549 decorate_*)
    decorate_sample_generator = set_sample_generator
    decorate_sample_list_generator = set_sample_list_generator
    decorate_batch_generator = set_batch_generator
    decorate_tensor_provider = set_batch_generator
    decorate_paddle_reader = set_sample_list_generator

    def _stage(self, feed):
        """Commit one batch to the device on the PRODUCER thread, so H2D is
        off the consumer's critical path and the Executor's zero-copy feed
        passthrough can use the arrays as-is. int64 bounds are checked here,
        host-side, for the same reason: checking a committed device array
        later would force a device→host sync per step."""
        from .core.lod import LoDTensor
        from .core.dtypes import check_int32_bounds
        staged = {}
        for k, v in feed.items():
            if isinstance(v, LoDTensor):
                # ragged id batches stage like dense feeds: the padded
                # payload commits H2D here (producer thread) with the
                # int64 bounds check host-side, and the Executor's
                # zero-copy passthrough consumes the device array as-is;
                # the lengths stay host-resident (they bind the @LEN var)
                data = np.ascontiguousarray(v.data)
                if data.dtype == np.int64:
                    check_int32_bounds(data, k)
                st = LoDTensor.__new__(LoDTensor)
                st._data = jax.device_put(data)
                st._recursive_seq_lens = v.recursive_sequence_lengths()
                staged[k] = st
                continue
            a = np.ascontiguousarray(v)
            if a.dtype == np.int64:
                check_int32_bounds(a, k)
            staged[k] = jax.device_put(a)
        return staged

    # -- fleet sharding (docs/DISTRIBUTED.md "Multi-host runtime") --
    def shard_for_fleet(self, num_shards=None, shard_id=None):
        """Per-host input sharding: every batch the reader produces is
        row-sliced ``[shard_id::num_shards]`` on its leading dim BEFORE
        device staging, so each host reads (and stages) only its own
        ``process_index``-strided slice of the global batch — the
        per-host input pipeline of arXiv 1909.09756 §3. Defaults come
        from the bootstrapped fleet (``jax.process_count/index``); a
        1-host fleet is a no-op. The resume cursor stays in GLOBAL batch
        indices (all hosts consume batch i in lockstep), so per-host
        cursors restored from a host's own shard manifest agree across
        the fleet. Returns self (chainable)."""
        import jax as _jax
        n = int(num_shards if num_shards is not None
                else _jax.process_count())
        i = int(shard_id if shard_id is not None else _jax.process_index())
        if n < 1 or not (0 <= i < n):
            raise ValueError(
                f'shard_for_fleet: shard_id {i} outside [0, {n})')
        self._shard_n = None if n == 1 else n
        self._shard_id = None if n == 1 else i
        return self

    def _shard_feed(self, feed):
        """Slice every array row-strided for this host. LoDTensors are
        rejected: a ragged batch has no row-aligned stride slicing (shard
        upstream in the reader instead)."""
        if self._shard_n is None:
            return feed
        from .core.lod import LoDTensor
        out = {}
        for k, v in feed.items():
            if isinstance(v, LoDTensor):
                raise ValueError(
                    'DataLoader fleet sharding cannot row-slice LoDTensor '
                    f'feed {k!r}; shard the reader itself for ragged data')
            a = np.asarray(v)
            if a.ndim == 0:
                out[k] = a
                continue
            if a.shape[0] < self._shard_n:
                raise ValueError(
                    f'DataLoader fleet sharding: batch dim of {k!r} is '
                    f'{a.shape[0]}, smaller than the {self._shard_n}-host '
                    f'fleet')
            out[k] = a[self._shard_id::self._shard_n]
        return out

    # -- resume cursor (docs/RESILIENCE.md) --
    @property
    def epoch(self):
        """Completed passes over the reader (0-based current epoch).
        Readable from inside a batch generator closure, so per-epoch data
        (shuffles, shards) can key off it and stay resume-deterministic."""
        return self._epoch

    def state_dict(self):
        """Checkpointable cursor: where the CONSUMER is in the data
        stream."""
        return {'epoch': self._epoch, 'batch': self._consumed}

    def set_state_dict(self, state):
        """Restore a :meth:`state_dict`. The next iteration re-runs the
        (deterministic) reader for `epoch` and skips the first `batch`
        batches on the producer side — before any device staging — so the
        consumer resumes exactly where the checkpointed run stood."""
        self._epoch = int(state['epoch'])
        self._consumed = int(state['batch'])
        self._skip = int(state['batch'])

    def __iter__(self):
        q = queue.Queue(maxsize=self._capacity)
        end = object()
        err_box = []
        stop = threading.Event()   # consumer abandoned iteration
        skip = self._skip          # latch the resume skip for this pass
        self._skip = 0

        def producer():
            try:
                it = enumerate(self._batch_reader())
                while True:
                    # hang watchdog: a wedged reader / device_put breaches
                    # the producer's IO lease (resilience/watchdog.py; free
                    # when no process watchdog is armed). Blocking on a FULL
                    # ring below is the consumer's pace, not a hang — the
                    # lease is released before the put.
                    lease = _watchdog.arm_io('dataloader_producer')
                    try:
                        try:
                            i, feed = next(it)
                        except StopIteration:
                            return
                        if stop.is_set():
                            return
                        if i < skip:   # resume fast-forward: no staging cost
                            continue
                        staged = self._stage(self._shard_feed(feed))
                    finally:
                        _watchdog.disarm(lease)
                    if _obs._ENABLED:
                        _obs.inc('dataloader_staged_bytes',
                                 sum(getattr(v, 'nbytes', 0)
                                     for v in staged.values()),
                                 help='bytes staged host→device by the '
                                      'DataLoader producer thread')
                    # bounded put that notices abandonment: a consumer that
                    # broke out of iteration early must not leave this
                    # thread blocked on a full ring holding staged device
                    # buffers forever
                    while True:
                        try:
                            q.put(staged, timeout=0.05)
                            break
                        except queue.Full:
                            if stop.is_set():
                                return
            except BaseException as e:   # surface in the consumer, not stderr
                err_box.append(e)
            finally:
                # the `end` sentinel must reach a still-listening consumer
                # even after an exception (never deadlock its q.get());
                # with the consumer gone, stop is set and we just exit
                while not stop.is_set():
                    try:
                        q.put(end, timeout=0.05)
                        break
                    except queue.Full:
                        pass

        t = threading.Thread(target=producer, daemon=True,
                             name='paddle_tpu_dataloader_producer')
        t.start()
        try:
            while True:
                if _obs._ENABLED:
                    # consumer-side input starvation: time blocked on the
                    # ring. A well-fed loop keeps this near zero; a starved
                    # one makes the device wait on the host
                    # (arXiv:1909.09756's per-step input-wait signal).
                    # wait_seconds_total / wall time is the starvation
                    # fraction telemetry_report.py prints.
                    t0 = time.perf_counter()
                    item = q.get()
                    wait = time.perf_counter() - t0
                    _obs.observe('dataloader_wait_seconds', wait,
                                 help='consumer wait per batch on the '
                                      'prefetch ring (input starvation)')
                    _obs.inc('dataloader_wait_seconds_total', wait,
                             help='cumulative consumer input-starvation wait')
                    _obs.set_gauge('dataloader_last_wait_seconds', wait,
                                   help='most recent per-batch input wait')
                    if item is not end:
                        _obs.inc('dataloader_batches',
                                 help='batches yielded by DataLoader')
                else:
                    item = q.get()
                if item is end:
                    if err_box:
                        raise err_box[0]
                    # clean exhaustion: advance the resume cursor one epoch
                    self._epoch += 1
                    self._consumed = 0
                    break
                # count BEFORE yielding: while the consumer processes batch
                # i the cursor already reads i+1, so a checkpoint taken at
                # that step's boundary resumes AFTER the batch whose effects
                # are in the state — never replaying it
                self._consumed += 1
                if self._return_list:
                    yield [item[k] for k in item]
                else:
                    yield item
        finally:
            # normal exhaustion, an exception, or GeneratorExit (consumer
            # broke early): signal the producer and drain the ring so its
            # staged buffers free and the thread exits promptly
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    def __call__(self):
        return iter(self)

    def start(self):
        pass

    def reset(self):
        pass


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        return _GeneratorLoader(feed_list, capacity, use_double_buffer,
                                iterable, return_list, use_multiprocess,
                                drop_last)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        loader = _GeneratorLoader()
        loader.set_batch_generator(lambda: iter(dataset))
        return loader
