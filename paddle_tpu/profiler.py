"""Profiler (ref: python/paddle/fluid/profiler.py) — wired to jax.profiler:
start_profiler/stop_profiler emit an XLA trace viewable in TensorBoard /
Perfetto instead of the reference's chrome-tracing timeline.
"""
from __future__ import annotations

import contextlib
import logging
import os
import time

import jax

from . import observability as _obs
from .log_helper import get_logger

_logger = get_logger(__name__, logging.INFO,
                     fmt='%(asctime)s-%(levelname)s: %(message)s')

_trace_dir = None
_op_times = {}


def start_profiler(state='All', tracer_option='Default',
                   output_dir='/tmp/paddle_tpu_profile'):
    global _trace_dir
    _trace_dir = output_dir
    os.makedirs(output_dir, exist_ok=True)
    jax.profiler.start_trace(output_dir)


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    # logger, not print(): headless/captured runs keep the profiler output
    # (log_helper handler, application logging config untouched)
    jax.profiler.stop_trace()
    _logger.info("trace written to %s (open with TensorBoard or "
                 "ui.perfetto.dev)", _trace_dir)
    if _op_times:
        _logger.info("\n%s", summary_table(sorted_key))
        _op_times.clear()     # per-session table, like the reference
    stats = eager_kernel_cache_stats()
    if stats['hits'] or stats['misses'] or stats['bypasses']:
        _logger.info("eager kernel cache: %s", stats)


def summary_table(sorted_key=None):
    """Per-event summary like the reference's profiler table
    (ref python/paddle/fluid/profiler.py:196 — Event/Calls/Total/Min/Max/Ave
    sorted by `sorted_key` in {'calls','total','max','min','ave'})."""
    rows = []
    for name, ts in _op_times.items():
        n = len(ts)
        tot = sum(ts)
        rows.append((name, n, tot, min(ts), max(ts), tot / n))
    key_idx = {'calls': 1, 'total': 2, 'min': 3, 'max': 4, 'ave': 5}
    if sorted_key in key_idx:
        rows.sort(key=lambda r: -r[key_idx[sorted_key]])
    head = f"{'Event':<32}{'Calls':>8}{'Total(ms)':>12}" \
           f"{'Min(ms)':>10}{'Max(ms)':>10}{'Ave(ms)':>10}"
    lines = ['-' * len(head), head, '-' * len(head)]
    for name, n, tot, mn, mx, ave in rows:
        lines.append(f"{name[:32]:<32}{n:>8}{tot * 1e3:>12.3f}"
                     f"{mn * 1e3:>10.3f}{mx * 1e3:>10.3f}{ave * 1e3:>10.3f}")
    lines.append('-' * len(head))
    return '\n'.join(lines)


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile',
             tracer_option='Default'):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def record_event(name):
    """Host-side named span; device-side annotation via TraceAnnotation.
    With PADDLE_TPU_TELEMETRY on the region also lands in the telemetry
    trace/metrics (span `user/<name>`, histogram user_event_seconds)."""
    with jax.profiler.TraceAnnotation(name), _obs.span(f'user/{name}'):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            _op_times.setdefault(name, []).append(dt)
            _obs.observe('user_event_seconds', dt,
                         help='profiler.record_event region durations',
                         event=name)


def eager_kernel_cache_stats():
    """Counters of the dygraph eager per-op jitted-kernel cache
    (dygraph/tape.py): {enabled, size, maxsize, hits, misses, evictions,
    bypasses}. A healthy training loop converges to ~100% hits after the
    first step; `bypasses` counts ops whose attrs/body cannot be jitted."""
    from .dygraph.tape import kernel_cache_stats
    return kernel_cache_stats()


def reset_eager_kernel_cache_stats():
    """Zero the hits/misses/evictions/bypasses counters WITHOUT dropping the
    compiled kernels: two back-to-back profiled runs each report their own
    hit rate, and the second run stays warm (clear() would force every
    signature to recompile and read as a miss storm)."""
    from .dygraph.tape import kernel_cache
    kernel_cache.reset_stats()


def reset_profiler():
    _op_times.clear()


def get_op_times():
    return {k: (len(v), sum(v)) for k, v in _op_times.items()}


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """compat shim (ref: profiler.py:cuda_profiler)."""
    yield
