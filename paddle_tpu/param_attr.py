"""ParamAttr / WeightNormParamAttr.

Parity with reference python/paddle/fluid/param_attr.py.
"""
from __future__ import annotations

from .initializer import Initializer, ConstantInitializer


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 gradient_clip=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.gradient_clip = gradient_clip

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else False
        if isinstance(arg, (int, float)):
            return ParamAttr(initializer=ConstantInitializer(float(arg)))
        raise TypeError(f"cannot make ParamAttr from {arg!r}")

    def _to_kwargs(self, with_initializer=False):
        kw = {
            'name': self.name,
            'learning_rate': self.learning_rate,
            'regularizer': self.regularizer,
            'trainable': self.trainable,
            'do_model_average': self.do_model_average,
        }
        if with_initializer:
            kw['initializer'] = self.initializer
        return kw


class WeightNormParamAttr(ParamAttr):
    """Weight-normalized parameter (ref: param_attr.py WeightNormParamAttr)."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
