"""Op introspection (ref: python/paddle/fluid/op.py).

The reference builds OpDesc protobufs from C++ op protos; here the registry
of jax functionals IS the op universe, so the "protos" are derived from the
registered OpDefs (input/output slots + attr names from the functional's
keyword-only signature).
"""
import inspect

from .ops.registry import all_ops, get_op, has_op

__all__ = ['get_all_op_protos', 'OpInfo', 'OpDescCreationMethod',
           'OperatorFactory', 'create_op_creation_method', 'is_str',
           'Operator']


def is_str(s):
    return isinstance(s, str)


class OpProto:
    """Lightweight stand-in for the reference's framework.proto OpProto."""

    def __init__(self, opdef):
        self.type = opdef.name
        self.inputs = list(opdef.input_slots)
        self.outputs = list(opdef.output_slots)
        sig = inspect.signature(opdef.fn)
        self.attrs = [p.name for p in sig.parameters.values()
                      if p.kind == inspect.Parameter.KEYWORD_ONLY
                      and p.name != 'key']

    def __repr__(self):
        return (f'OpProto({self.type}, inputs={self.inputs}, '
                f'outputs={self.outputs}, attrs={self.attrs})')


def get_all_op_protos():
    """ref op.py:get_all_op_protos — one proto per registered op."""
    return [OpProto(get_op(name)) for name in sorted(all_ops())]


class OpInfo:
    """ref op.py:OpInfo — method + proto pair for one op type."""

    def __init__(self, name):
        if not has_op(name):
            raise ValueError(f'unknown op type {name!r}')
        self.name = name
        self.op_def = get_op(name)
        self.proto = OpProto(self.op_def)
        self.method = self.op_def.fn


class OpDescCreationMethod:
    """ref op.py:OpDescCreationMethod — callable producing an op descriptor
    dict (the JSON-IR analogue of an OpDesc protobuf)."""

    def __init__(self, op_proto):
        self.proto = op_proto

    def __call__(self, **kwargs):
        inputs = {k: kwargs[k] for k in self.proto.inputs if k in kwargs}
        attrs = {k: kwargs[k] for k in self.proto.attrs if k in kwargs}
        outputs = {k: kwargs.get(k) for k in self.proto.outputs}
        return {'type': self.proto.type, 'inputs': inputs,
                'outputs': outputs, 'attrs': attrs}


def create_op_creation_method(op_proto):
    """ref op.py:create_op_creation_method."""
    method = OpDescCreationMethod(op_proto)

    def creator(**kwargs):
        return method(**kwargs)
    creator.__name__ = op_proto.type
    return creator


class OperatorFactory:
    """ref op.py:OperatorFactory — lazy name → creation-method table."""

    def __init__(self):
        self.op_methods = {}

    def __call__(self, *args, **kwargs):
        if 'type' in kwargs:
            if args:
                raise ValueError("all parameters should be keyword when "
                                 "'type' is given")
            t = kwargs.pop('type')
        else:
            if len(args) != 1:
                raise ValueError('the first positional argument must be '
                                 'the op type')
            t = args[0]
        return self.get_op_creation_info(t)(**kwargs)

    def get_op_creation_info(self, t):
        if t not in self.op_methods:
            info = OpInfo(t)
            self.op_methods[t] = create_op_creation_method(info.proto)
        return self.op_methods[t]

    def types(self):
        return sorted(all_ops())


Operator = OperatorFactory()
