"""paddle.distributed parity namespace (ref: python/paddle/distributed/):
launch utilities + collective API re-exports."""
from ..parallel import (fleet, Fleet, DistributedStrategy, make_mesh,
                        set_default_mesh, get_default_mesh, topology)
from ..parallel.collective import (allreduce_sum, allreduce_mean,
                                   allreduce_max, allreduce_min, allgather,
                                   reduce_scatter, broadcast, alltoall,
                                   ppermute, barrier)
from .launch import launch, init_parallel_env, get_rank, get_world_size
