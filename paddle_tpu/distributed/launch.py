"""Multi-host launch (ref: python/paddle/distributed/launch.py).

The reference spawns one process per GPU and wires NCCL via env vars. On TPU
pods each host already runs one process per slice-host; initialization is
jax.distributed.initialize() with coordinator discovery from env (TPU metadata
provides it automatically on Cloud TPU).
"""
from __future__ import annotations

import os

import jax


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Initialize the multi-host jax runtime. Delegates to the strict-parse
    fleet bootstrap (fleet_runtime/bootstrap.py): env discovery +
    jax.distributed init + partitioner mesh from the global devices +
    fleet sentinel. No-op on a single host. Explicit arguments override
    the environment."""
    from ..fleet_runtime.bootstrap import FleetSpec, bootstrap
    spec = None
    if num_processes is not None or coordinator_address is not None:
        if num_processes is None:
            num_processes = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
        spec = FleetSpec(
            num_processes,
            process_id if process_id is not None
            else int(os.environ.get('PADDLE_TRAINER_ID', '0')),
            coordinator_address=coordinator_address)
    return bootstrap(spec=spec)


def get_rank():
    return jax.process_index()


def get_world_size():
    return jax.process_count()


def launch(training_script, args=(), nproc_per_node=None):
    """Single-host convenience: on TPU the runtime owns all local chips in one
    process, so `launch` execs the script directly (ref behavior of spawning
    per-GPU workers is unnecessary)."""
    import runpy
    import sys
    old_argv = sys.argv
    sys.argv = [training_script] + list(args)
    try:
        runpy.run_path(training_script, run_name='__main__')
    finally:
        sys.argv = old_argv
