"""Multi-host launch (ref: python/paddle/distributed/launch.py).

The reference spawns one process per GPU and wires NCCL via env vars. On TPU
pods each host already runs one process per slice-host; initialization is
jax.distributed.initialize() with coordinator discovery from env (TPU metadata
provides it automatically on Cloud TPU).
"""
from __future__ import annotations

import os

import jax


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Initialize multi-host jax runtime. No-op on single host."""
    if num_processes is None:
        num_processes = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
    if num_processes <= 1:
        return
    kwargs = {}
    if coordinator_address:
        kwargs['coordinator_address'] = coordinator_address
        kwargs['num_processes'] = num_processes
        kwargs['process_id'] = process_id or int(
            os.environ.get('PADDLE_TRAINER_ID', '0'))
    jax.distributed.initialize(**kwargs)


def get_rank():
    return jax.process_index()


def get_world_size():
    return jax.process_count()


def launch(training_script, args=(), nproc_per_node=None):
    """Single-host convenience: on TPU the runtime owns all local chips in one
    process, so `launch` execs the script directly (ref behavior of spawning
    per-GPU workers is unnecessary)."""
    import runpy
    import sys
    old_argv = sys.argv
    sys.argv = [training_script] + list(args)
    try:
        runpy.run_path(training_script, run_name='__main__')
    finally:
        sys.argv = old_argv
