"""Program graph drawing helpers (ref: python/paddle/fluid/net_drawer.py).

The reference walks ProgramDesc protobufs into a graphviz Graph; here the
same traversal runs over the op-list IR, emitting .dot text (shared renderer
with debugger.draw_block_graphviz — no graphviz binary needed).
"""
from .framework import default_main_program

__all__ = ['draw_graph', 'parse_graph', 'draw_node', 'draw_edge', 'unique_id']

OP_STYLE = {'shape': 'oval', 'color': '#0F9D58', 'style': 'filled'}
VAR_STYLE = {'shape': 'box'}

_counter = [0]


def unique_id():
    """ref net_drawer.py:unique_id — monotonically increasing node ids."""
    _counter[0] += 1
    return _counter[0]


def draw_node(op, node_id):
    """One graphviz node line for an op (ref net_drawer.py:draw_node)."""
    style = ', '.join(f'{k}="{v}"' for k, v in OP_STYLE.items())
    return f'op_{node_id} [label="{op.type}", {style}];'


def draw_edge(var_name, op_node_id, into_op=True):
    """One graphviz edge line var<->op (ref net_drawer.py:draw_edge)."""
    v = f'"{var_name}"'
    return (f'{v} -> op_{op_node_id};' if into_op
            else f'op_{op_node_id} -> {v};')


def parse_graph(program, graph_lines, var_dict=None):
    """Append node/edge lines for every op of `program`'s global block
    (ref net_drawer.py:parse_graph)."""
    var_dict = var_dict if var_dict is not None else {}
    for op in program.global_block().ops:
        nid = unique_id()
        graph_lines.append(draw_node(op, nid))
        for name in op.input_names():
            graph_lines.append(draw_edge(name, nid, into_op=True))
        for name in op.output_names():
            graph_lines.append(draw_edge(name, nid, into_op=False))
            var_dict[name] = nid
    return var_dict


def draw_graph(startup_program=None, main_program=None, path='graph.dot',
               graph_attr=None):
    """Emit a .dot file covering startup+main programs
    (ref net_drawer.py:draw_graph)."""
    main_program = main_program or default_main_program()
    lines = ['digraph G {']
    if graph_attr:
        lines += [f'  {k}="{v}";' for k, v in graph_attr.items()]
    var_dict = {}
    if startup_program is not None:
        parse_graph(startup_program, lines, var_dict)
    parse_graph(main_program, lines, var_dict)
    lines.append('}')
    text = '\n'.join(lines)
    with open(path, 'w') as f:
        f.write(text)
    return text
