"""CompiledProgram (ref: python/paddle/fluid/compiler.py).

The reference's with_data_parallel clones the graph per GPU and inserts NCCL
allreduce. TPU redesign: the program is unchanged; data parallelism = shard
the feed batch over the mesh 'dp' axis, replicate params, and let XLA insert
AllReduce over ICI inside the already-jitted step.

BuildStrategy knobs fall in three groups on TPU:
- `fuse_elewise_add_act_ops` / `fuse_all_optimizer_ops` /
  `fuse_all_reduce_ops` drive the program-level IR pass pipeline
  (paddle_tpu/ir/): the Program's op list is rewritten BEFORE the
  Executor traces it — op fusion cuts trace/lower time and jaxpr size,
  and the allreduce bucketing pass regroups gradient sync for
  comm/compute overlap (ir/bucket_allreduce.py);
- `enable_inplace` / `memory_optimize` map onto XLA buffer donation of
  the training state (executor.py);
- the rest (reduce_strategy, …) are subsumed by XLA/GSPMD and accepted
  for API compat only.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec


class BuildStrategy:
    """ref: framework/details/build_strategy.h knobs.

    Live on TPU:
    - `fuse_elewise_add_act_ops`: IR pass collapsing elementwise_add +
      relu/sigmoid/tanh pairs into one fused op before tracing
      (ir/fuse_act.py);
    - `fuse_all_optimizer_ops`: IR pass coalescing the per-param
      sgd/momentum/adam update ops into one multi-tensor op over a
      flattened param bundle (ir/fuse_optimizer.py) — traced op count and
      jaxpr size drop by O(#params);
    - `fuse_all_reduce_ops` (default True): IR pass splitting the
      per-gradient `c_allreduce_sum` ops fleet's minimize emits into
      size-capped buckets (`PADDLE_TPU_ALLREDUCE_BUCKET_MB`, one fused
      collective per bucket dispatched right after its gradients exist,
      ir/bucket_allreduce.py) so bucket comm overlaps the remaining
      backward compute instead of one tail-synchronous reduction;
      bitwise-identical to the unbucketed ops at `comm_dtype=f32`;
    - `enable_inplace` / `memory_optimize`, which map onto XLA buffer
      donation as described below.
    reduce_strategy etc. are XLA's job and remain accepted-for-compat
    no-ops.

    `enable_inplace` and `memory_optimize` map
    onto XLA buffer donation of the training state. The default (None) lets
    the Executor donate parameter/optimizer-state buffers into the jitted
    step (in-place HBM update, no transient 2× parameter footprint);
    setting either to False runs the step copy-in/copy-out — pre-step
    buffers stay valid, at the cost of peak memory. Fetch-aliased
    persistables are always excluded from donation regardless of the knob
    (the Executor guards them; see executor.py)."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_all_optimizer_ops = False
        self.memory_optimize = None
        self.enable_inplace = None
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """ref: framework/details/execution_strategy.h knobs.

    Live on TPU: `num_inflight_steps` — setting it > 1 turns the
    Executor's training loop into the async pipeline (executor.py): up to
    that many dispatched steps stay outstanding, fetches come back as
    non-blocking :class:`~paddle_tpu.core.fetch_handle.FetchHandle` s, and
    the executor blocks on the oldest handle only when the window is full.
    `2` is classic double buffering (host feed prep + dispatch of step N+1
    overlap device execution of step N — PERF.md §12). The
    `PADDLE_TPU_ASYNC` env var overrides it either way; `num_threads` /
    `num_iteration_per_drop_scope` stay accepted-for-compat no-ops (the
    step is one XLA program; scopes hold no transient kernels)."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = False
        self.num_inflight_steps = 1


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None,
                 exec_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy
        self._data_sharding = None
        self._places = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        """Shard feeds over the partitioner's data axes (the 'batch'
        logical axis — 'dp', or dp×fsdp on a composed mesh); without a
        configured mesh, a flat all-device 'dp' mesh is built."""
        from .partition import get_partitioner, make_mesh
        if build_strategy is not None:
            self._build_strategy = build_strategy
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        sharding = get_partitioner().data_sharding()
        if sharding is None:
            n = len(jax.devices())
            sharding = NamedSharding(make_mesh({'dp': n}),
                                     PartitionSpec('dp'))
        self._data_sharding = sharding
        self._places = places
        return self

    def _compile(self, *a, **k):
        return self
