"""DataFeedDesc (ref: python/paddle/fluid/data_feed_desc.py) — describes the
MultiSlot text format consumed by fluid.dataset readers.

The reference wraps a data_feed.proto message; here the same fields live in
a plain dict parsed from the protobuf TEXT format (a small indentation-free
`key: value` / `block { }` grammar), so existing .proto text files work
unchanged. `fluid.dataset` uses the slot list to parse data files.
"""

__all__ = ['DataFeedDesc']


def _parse_text_proto(text):
    """Minimal text-format protobuf reader → nested dict (repeated fields
    become lists)."""
    root = {}
    stack = [root]
    for raw in text.splitlines():
        line = raw.split('#', 1)[0].strip()
        if not line:
            continue
        if line.endswith('{'):
            child = {}
            key = line[:-1].strip()
            cur = stack[-1]
            if key in cur:
                if not isinstance(cur[key], list):
                    cur[key] = [cur[key]]
                cur[key].append(child)
            else:
                cur[key] = child
            stack.append(child)
        elif line == '}':
            stack.pop()
        elif ':' in line:
            key, val = (s.strip() for s in line.split(':', 1))
            if val.startswith('"') and val.endswith('"'):
                val = val[1:-1]
            elif val in ('true', 'false'):
                val = val == 'true'
            else:
                try:
                    val = int(val)
                except ValueError:
                    try:
                        val = float(val)
                    except ValueError:
                        pass
            cur = stack[-1]
            if key in cur:
                if not isinstance(cur[key], list):
                    cur[key] = [cur[key]]
                cur[key].append(val)
            else:
                cur[key] = val
    return root


def _to_text_proto(d, indent=0):
    pad = '  ' * indent
    out = []
    for k, v in d.items():
        vals = v if isinstance(v, list) else [v]
        for item in vals:
            if isinstance(item, dict):
                out.append(f'{pad}{k} {{')
                out.append(_to_text_proto(item, indent + 1))
                out.append(f'{pad}}}')
            elif isinstance(item, bool):
                out.append(f'{pad}{k}: {"true" if item else "false"}')
            elif isinstance(item, str):
                out.append(f'{pad}{k}: "{item}"')
            else:
                out.append(f'{pad}{k}: {item}')
    return '\n'.join(out)


class DataFeedDesc:
    """ref data_feed_desc.py:DataFeedDesc — load from a text-proto file."""

    def __init__(self, proto_file):
        with open(proto_file) as f:
            self.proto_desc = _parse_text_proto(f.read())
        self.proto_desc.setdefault('pipe_command', 'cat')
        self._name_to_idx = {}
        for i, slot in enumerate(self._slots()):
            self._name_to_idx[slot.get('name')] = i

    def _slots(self):
        msd = self.proto_desc.get('multi_slot_desc', {})
        slots = msd.get('slots', [])
        return slots if isinstance(slots, list) else [slots]

    def set_batch_size(self, batch_size):
        """ref :set_batch_size."""
        self.proto_desc['batch_size'] = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        """ref :set_dense_slots — mark named slots dense."""
        slots = self._slots()
        for name in dense_slots_name:
            slots[self._name_to_idx[name]]['is_dense'] = True

    def set_use_slots(self, use_slots_name):
        """ref :set_use_slots — mark named slots used (fed to the model)."""
        slots = self._slots()
        for name in use_slots_name:
            slots[self._name_to_idx[name]]['is_used'] = True

    def desc(self):
        """ref :desc — text-proto string of the current description."""
        return _to_text_proto(self.proto_desc)
