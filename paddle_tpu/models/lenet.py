"""MNIST LeNet — the e2e smoke model (ref: the reference's book chapter
recognize_digits + python/paddle/fluid/tests/unittests/test_mnist* models).
Provided in both modes: build_static_lenet() for Program/Executor and the
dygraph LeNet Layer.
"""
from __future__ import annotations

from .. import layers, nets
from ..dygraph import Layer, Linear, Conv2D, Pool2D
from ..dygraph.tape import dispatch_op


def build_static_lenet(img, label):
    """img: data var (N,1,28,28); label: (N,1) int64. Returns (loss, acc,
    prediction)."""
    conv1 = nets.simple_img_conv_pool(img, num_filters=20, filter_size=5,
                                      pool_size=2, pool_stride=2, act='relu')
    conv2 = nets.simple_img_conv_pool(conv1, num_filters=50, filter_size=5,
                                      pool_size=2, pool_stride=2, act='relu')
    fc = layers.fc(conv2, size=500, act='relu')
    logits = layers.fc(fc, size=10)
    loss = layers.reduce_mean(
        layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc, logits


class LeNet(Layer):
    """Dygraph LeNet."""

    def __init__(self):
        super().__init__()
        self.conv1 = Conv2D(1, 20, 5, act='relu')
        self.pool1 = Pool2D(2, 'max', 2)
        self.conv2 = Conv2D(20, 50, 5, act='relu')
        self.pool2 = Pool2D(2, 'max', 2)
        self.fc1 = Linear(50 * 4 * 4, 500, act='relu')
        self.fc2 = Linear(500, 10)

    def forward(self, x):
        x = self.pool1(self.conv1(x))
        x = self.pool2(self.conv2(x))
        x = dispatch_op('reshape', {'x': x}, {'shape': [0, 50 * 4 * 4]})
        return self.fc2(self.fc1(x))
