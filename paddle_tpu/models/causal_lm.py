"""Decoder-only causal transformer LM — the workload the stateful decode
engine (serving/decode/, docs/SERVING.md "Stateful decode") serves.

Built from the BERT building blocks (models/bert.py TransformerLayer /
MultiHeadAttention) with a causal mask and a weight-tied LM head, so the
incremental-decode cache path added to MultiHeadAttention is exercised by a
real model rather than a bespoke one. Two execution modes share every
parameter and (on CPU) every bit of arithmetic:

- **whole-sequence** (``cache=None``): the full (B, L) padded sequence in
  one forward — training, and the uncached reference that
  :func:`greedy_generate` uses;
- **incremental** (``cache=`` a serving/decode CacheContext): prefill
  writes the prompt's K/V into paged cache blocks, decode steps run at
  fixed (S, 1) shape reading K/V through per-slot block tables.

Bitwise-parity contract (the decode engine's acceptance bar): on CPU, a
decode step's logits row is `np.array_equal` to the matching row of a
whole-sequence forward padded to the SAME context extent (the engine's
``padded_context``). This needs the unfused matmul attention path — XLA
CPU keeps matmul rows bitwise stable across the sequence extent, while the
einsum in fused_attention's fallback does not (measured; see
ops/nn_ops.py:paged_attention) — so ``use_fused_attention`` defaults off
here and the config asserts it stays off when parity matters.
"""
from __future__ import annotations

import numpy as np

from ..dygraph import Layer, Embedding, LayerNorm, Dropout, LayerList
from ..dygraph.tape import Tensor, dispatch_op, no_grad_guard
from .bert import TransformerLayer, _init


class CausalLMConfig:
    """Duck-types the BertConfig fields TransformerLayer reads, plus LM
    bits. ``attention_probs_dropout_prob`` is pinned to 0 (the fused and
    cached attention paths both skip attention-prob dropout)."""

    def __init__(self, vocab_size=32000, hidden_size=512,
                 num_hidden_layers=6, num_attention_heads=8,
                 intermediate_size=2048, hidden_act='gelu',
                 hidden_dropout_prob=0.1, max_position_embeddings=512,
                 initializer_range=0.02, use_fused_attention=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = 0.0
        self.max_position_embeddings = max_position_embeddings
        self.initializer_range = initializer_range
        self.use_fused_attention = use_fused_attention

    @staticmethod
    def tiny():
        """Test/bench scale."""
        return CausalLMConfig(vocab_size=128, hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=2,
                              intermediate_size=64,
                              max_position_embeddings=128)


class TransformerLM(Layer):
    def __init__(self, cfg: CausalLMConfig):
        super().__init__()
        self.cfg = cfg
        self.word_emb = Embedding([cfg.vocab_size, cfg.hidden_size],
                                  param_attr=_init(cfg))
        self.pos_emb = Embedding([cfg.max_position_embeddings,
                                  cfg.hidden_size], param_attr=_init(cfg))
        self.emb_ln = LayerNorm(cfg.hidden_size)
        self.emb_drop = Dropout(cfg.hidden_dropout_prob,
                                dropout_implementation='upscale_in_train')
        self.blocks = LayerList([TransformerLayer(cfg)
                                 for _ in range(cfg.num_hidden_layers)])

    @property
    def num_cache_layers(self):
        return self.cfg.num_hidden_layers

    def forward(self, input_ids, pos_ids=None, cache=None):
        """``input_ids`` (B, S) → logits (B, S, V). ``pos_ids`` defaults to
        0..S-1 per row; the decode engine passes each slot's context
        position explicitly. ``cache`` routes attention through the paged
        KV cache (see module docstring)."""
        b, s = input_ids.shape
        if pos_ids is None:
            pos_ids = Tensor(
                np.arange(s, dtype=np.int64)[None, :].repeat(b, 0),
                stop_gradient=True)
        x = self.word_emb(input_ids) + self.pos_emb(pos_ids)
        # lookup_table squeezes (B, 1) id columns (LoD convention) — the
        # decode step feeds exactly that shape; restore (B, S, H)
        x = dispatch_op('reshape', {'x': x},
                        {'shape': [b, s, self.cfg.hidden_size]})
        x = self.emb_drop(self.emb_ln(x))
        for blk in self.blocks:
            x = blk(x, None, causal=True, cache=cache)
        # weight-tied LM head (same matrix as word_emb, transposed)
        return dispatch_op('matmul', {'x': x, 'y': self.word_emb.weight},
                           {'transpose_y': True})


def lm_loss(logits, labels, pad_id=0):
    """Next-token CE: logits (B, S, V) vs labels (B, S) shifted left by the
    caller; pad positions masked out (same scheme as transformer_loss)."""
    V = logits.shape[-1]
    flat = dispatch_op('reshape', {'x': logits}, {'shape': [-1, V]})
    lbl = dispatch_op('reshape', {'x': labels}, {'shape': [-1, 1]})
    raw, _ = dispatch_op('softmax_with_cross_entropy',
                         {'logits': flat, 'label': lbl}, {})
    mask = dispatch_op('cast', {'x': dispatch_op(
        'not_equal', {'x': lbl,
                      'y': Tensor(np.array([pad_id], np.int64),
                                  stop_gradient=True)}, {})},
        {'dtype': 'float32'})
    raw = dispatch_op('reshape', {'x': raw}, {'shape': [-1, 1]}) * mask
    total = dispatch_op('reduce_sum', {'x': raw}, {})
    denom = dispatch_op('reduce_sum', {'x': mask}, {})
    return total / (denom + 1e-9)


def greedy_generate(model, prompt_ids, max_new_tokens, eos_id=None,
                    pad_len=None):
    """Uncached whole-sequence greedy decode at ONE fixed padded shape.

    Every step re-runs the full (1, pad_len) sequence and reads the logits
    row of the last real position — O(L²) work, but a single compile for
    the whole generation (the fixed-shape discipline that also fixed
    models/transformer.py's decode retracing). This is the bitwise
    REFERENCE the decode engine is tested against: run it with
    ``pad_len == engine.padded_context`` and the streamed tokens must be
    identical (tools/bench_decode.py asserts it on every request).

    Returns the generated token ids (list, ≤ max_new_tokens; stops at
    ``eos_id``).
    """
    prompt = [int(t) for t in prompt_ids]
    P = len(prompt)
    if P < 1:
        raise ValueError('empty prompt')
    L = int(pad_len) if pad_len else P + int(max_new_tokens)
    if L < P + int(max_new_tokens):
        raise ValueError(
            f'pad_len={L} cannot hold prompt({P}) + {max_new_tokens} new '
            f'tokens')
    buf = np.zeros((1, L), np.int64)
    buf[0, :P] = prompt
    out = []
    with no_grad_guard():
        for i in range(int(max_new_tokens)):
            c = P + i
            logits = model(Tensor(buf, stop_gradient=True))
            nxt = int(np.asarray(logits.numpy())[0, c - 1].argmax())
            out.append(nxt)
            buf[0, c] = nxt
            if eos_id is not None and nxt == int(eos_id):
                break
    return out


def sampled_generate(model, prompt_ids, max_new_tokens, sampler, eos_id=None,
                     pad_len=None):
    """Uncached whole-sequence SAMPLED decode — :func:`greedy_generate`'s
    loop with the argmax replaced by ``sampler(row, index)``, where ``row``
    is the float logits row of the last real position and ``index`` the
    0-based generated-token index. Pair it with a
    ``serving.decode.TokenSampler`` bound to the same request_id/params and
    ``pad_len == engine.padded_context`` to get the bitwise replay
    reference for the engine's sampled path (the per-token fold_in key
    depends only on (seed, index), so cached and uncached loops draw the
    same stream).

    Returns the generated token ids (list, ≤ max_new_tokens; stops at
    ``eos_id``).
    """
    prompt = [int(t) for t in prompt_ids]
    P = len(prompt)
    if P < 1:
        raise ValueError('empty prompt')
    L = int(pad_len) if pad_len else P + int(max_new_tokens)
    if L < P + int(max_new_tokens):
        raise ValueError(
            f'pad_len={L} cannot hold prompt({P}) + {max_new_tokens} new '
            f'tokens')
    buf = np.zeros((1, L), np.int64)
    buf[0, :P] = prompt
    out = []
    with no_grad_guard():
        for i in range(int(max_new_tokens)):
            c = P + i
            logits = model(Tensor(buf, stop_gradient=True))
            nxt = int(sampler(np.asarray(logits.numpy())[0, c - 1], i))
            out.append(nxt)
            buf[0, c] = nxt
            if eos_id is not None and nxt == int(eos_id):
                break
    return out
