"""BERT (base/large) pretraining — the reference's flagship NLP benchmark
(BASELINE.json: BERT-base seq/s; ref model: LARK/PaddleLARK BERT as driven by
the ref's Fleet collective configs).

TPU design: pure Layer composition over batched matmuls (MXU-shaped:
[B*S, H] GEMMs), fused under dygraph.jit.TrainStep; attention is the
softmax(QK^T/√d)V composition that XLA fuses; sequence parallelism hooks live
in parallel/ring_attention.py.
"""
from __future__ import annotations

import math

import numpy as np

from ..dygraph import Layer, Linear, LayerNorm, Embedding, Dropout, LayerList
from ..dygraph.tape import Tensor, dispatch_op
from ..initializer import TruncatedNormalInitializer
from ..param_attr import ParamAttr


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act='gelu',
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, use_fused_attention=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        # lower attention to the fused op (pallas flash kernel on TPU);
        # bypasses attention-prob dropout, so use for p_drop=0 or eval
        self.use_fused_attention = use_fused_attention

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def large():
        return BertConfig(hidden_size=1024, num_hidden_layers=24,
                          num_attention_heads=16, intermediate_size=4096)

    @staticmethod
    def tiny():
        """For tests / dryruns."""
        return BertConfig(vocab_size=1024, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=128, max_position_embeddings=128)


def _init(cfg):
    return ParamAttr(initializer=TruncatedNormalInitializer(
        0.0, cfg.initializer_range))


class MultiHeadAttention(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        h = cfg.hidden_size
        self.q = Linear(h, h, param_attr=_init(cfg))
        self.k = Linear(h, h, param_attr=_init(cfg))
        self.v = Linear(h, h, param_attr=_init(cfg))
        self.out = Linear(h, h, param_attr=_init(cfg))
        self.drop = Dropout(cfg.attention_probs_dropout_prob,
                            dropout_implementation='upscale_in_train')
        self.n_heads = cfg.num_attention_heads
        self.d_head = h // cfg.num_attention_heads
        self._fused = cfg.use_fused_attention
        if self._fused and cfg.attention_probs_dropout_prob > 0:
            import warnings
            warnings.warn(
                "use_fused_attention bypasses attention-probability "
                "dropout (attention_probs_dropout_prob="
                f"{cfg.attention_probs_dropout_prob} is ignored); set it "
                "to 0 to silence this warning", stacklevel=2)

    def forward(self, x, attn_bias=None, causal=False, cache=None):
        """``causal``: additive upper-triangular mask (decoder-only LMs —
        models/causal_lm.py). ``cache``: a duck-typed paged-KV-cache context
        (serving/decode/kv_cache.py) whose ``attend(q, k, v, sm_scale=...)``
        writes this layer's K/V into cache blocks and attends through the
        block table — prefill writes the whole prompt, decode steps run at
        fixed single-token shape, so generation never re-runs the prefix."""
        b, s, h = x.shape

        def heads(t):
            t = dispatch_op('reshape', {'x': t},
                            {'shape': [b, s, self.n_heads, self.d_head]})
            return dispatch_op('transpose', {'x': t}, {'perm': [0, 2, 1, 3]})

        q = heads(self.q(x))
        k = heads(self.k(x))
        v = heads(self.v(x))
        if cache is not None:
            # incremental-decode path: K/V land in the paged cache; the
            # cache context picks prefill vs decode attention (causal is
            # implied by the cache's context lengths)
            ctx = cache.attend(q, k, v,
                               sm_scale=1.0 / math.sqrt(self.d_head))
        elif self._fused:
            # one fused kernel (ops/nn_ops.py:fused_attention — pallas
            # flash attention on TPU); attention-prob dropout is skipped
            ctx = dispatch_op('fused_attention',
                              {'q': q, 'k': k, 'v': v, 'bias': attn_bias},
                              {'sm_scale': 1.0 / math.sqrt(self.d_head),
                               'causal': causal})
        else:
            scores = dispatch_op('matmul', {'x': q, 'y': k},
                                 {'transpose_y': True,
                                  'alpha': 1.0 / math.sqrt(self.d_head)})
            if attn_bias is not None:
                scores = scores + attn_bias
            if causal:
                mask = np.triu(np.full((s, s), -1e9, 'float32'), 1)
                scores = scores + Tensor(mask[None, None],
                                         stop_gradient=True)
            probs = dispatch_op('softmax', {'x': scores}, {})
            probs = self.drop(probs)
            ctx = dispatch_op('matmul', {'x': probs, 'y': v}, {})
        ctx = dispatch_op('transpose', {'x': ctx}, {'perm': [0, 2, 1, 3]})
        ctx = dispatch_op('reshape', {'x': ctx}, {'shape': [b, s, h]})
        return self.out(ctx)


class TransformerLayer(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        h = cfg.hidden_size
        self.attn = MultiHeadAttention(cfg)
        self.attn_ln = LayerNorm(h)
        self.ffn1 = Linear(h, cfg.intermediate_size, param_attr=_init(cfg),
                           act=cfg.hidden_act)
        self.ffn2 = Linear(cfg.intermediate_size, h, param_attr=_init(cfg))
        self.ffn_ln = LayerNorm(h)
        self.drop = Dropout(cfg.hidden_dropout_prob,
                            dropout_implementation='upscale_in_train')

    def forward(self, x, attn_bias=None, causal=False, cache=None):
        a = self.attn(x, attn_bias, causal=causal, cache=cache)
        x = self.attn_ln(x + self.drop(a))
        f = self.ffn2(self.ffn1(x))
        return self.ffn_ln(x + self.drop(f))


class BertModel(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.word_emb = Embedding([cfg.vocab_size, cfg.hidden_size],
                                  param_attr=_init(cfg))
        self.pos_emb = Embedding([cfg.max_position_embeddings,
                                  cfg.hidden_size], param_attr=_init(cfg))
        self.type_emb = Embedding([cfg.type_vocab_size, cfg.hidden_size],
                                  param_attr=_init(cfg))
        self.emb_ln = LayerNorm(cfg.hidden_size)
        self.emb_drop = Dropout(cfg.hidden_dropout_prob,
                                dropout_implementation='upscale_in_train')
        self.encoder = LayerList([TransformerLayer(cfg)
                                  for _ in range(cfg.num_hidden_layers)])
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size,
                             param_attr=_init(cfg), act='tanh')

    def forward(self, input_ids, token_type_ids, attention_mask=None):
        b, s = input_ids.shape
        pos_ids = Tensor(np.arange(s, dtype=np.int64)[None, :].repeat(b, 0),
                         stop_gradient=True)
        emb = self.word_emb(input_ids) + self.pos_emb(pos_ids) + \
            self.type_emb(token_type_ids)
        x = self.emb_drop(self.emb_ln(emb))
        attn_bias = None
        if attention_mask is not None:
            # (B,S) 1/0 → additive bias (B,1,1,S)
            m = dispatch_op('cast', {'x': attention_mask},
                            {'dtype': 'float32'})
            m = dispatch_op('reshape', {'x': m}, {'shape': [b, 1, 1, s]})
            # additive bias: 0 where attended, -1e4 where masked
            attn_bias = dispatch_op('scale', {'x': m},
                                    {'scale': 10000.0, 'bias': -10000.0})
        for layer in self.encoder:
            x = layer(x, attn_bias)
        first_tok = x[:, 0]
        pooled = self.pooler(first_tok)
        return x, pooled


class BertPretrainHeads(Layer):
    """MLM + NSP heads. The MLM decoder is TIED to the word-embedding matrix
    (as in the reference BERT: mask_lm_out_fc reuses word_embedding with
    transpose), so it owns only the decoder bias — the embedding weight is
    passed in at forward time and its gradient flows to the shared
    parameter."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                param_attr=_init(cfg), act=cfg.hidden_act)
        self.transform_ln = LayerNorm(cfg.hidden_size)
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], None, 'float32', is_bias=True)
        self.nsp = Linear(cfg.hidden_size, 2, param_attr=_init(cfg))

    def forward(self, seq_out, pooled, word_emb_weight):
        h = self.transform_ln(self.transform(seq_out))
        mlm_logits = dispatch_op('matmul', {'x': h, 'y': word_emb_weight},
                                 {'transpose_y': True})
        mlm_logits = dispatch_op('elementwise_add',
                                 {'x': mlm_logits, 'y': self.decoder_bias},
                                 {'axis': -1})
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BertForPretraining(Layer):
    def __init__(self, cfg: BertConfig = None):
        super().__init__()
        self.cfg = cfg or BertConfig.base()
        self.bert = BertModel(self.cfg)
        self.heads = BertPretrainHeads(self.cfg)

    def forward(self, input_ids, token_type_ids, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.heads(seq, pooled, self.bert.word_emb.weight)


def pretrain_loss(model, input_ids, token_type_ids, mlm_labels, nsp_labels):
    """MLM + NSP loss; mlm_labels uses -1 for unmasked positions."""
    mlm_logits, nsp_logits = model(input_ids, token_type_ids)
    b, s, v = mlm_logits.shape
    flat_logits = dispatch_op('reshape', {'x': mlm_logits},
                              {'shape': [b * s, v]})
    flat_labels = dispatch_op('reshape', {'x': mlm_labels},
                              {'shape': [b * s, 1]})
    mlm_raw, _ = dispatch_op('softmax_with_cross_entropy',
                             {'logits': flat_logits, 'label': flat_labels},
                             {'ignore_index': -1})
    mask = dispatch_op('cast', {'x': dispatch_op(
        'greater_equal', {'x': flat_labels,
                          'y': Tensor(np.zeros((1, 1), np.int64),
                                      stop_gradient=True)}, {})},
        {'dtype': 'float32'})
    denom = dispatch_op('reduce_sum', {'x': mask}, {})
    mlm_loss = dispatch_op('reduce_sum', {'x': mlm_raw * mask}, {}) / \
        (denom + 1e-6)
    nsp_raw, _ = dispatch_op('softmax_with_cross_entropy',
                             {'logits': nsp_logits, 'label': nsp_labels}, {})
    nsp_loss = dispatch_op('reduce_mean', {'x': nsp_raw}, {})
    return mlm_loss + nsp_loss
