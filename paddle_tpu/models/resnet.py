"""ResNet-18/34/50/101/152 for ImageNet — the reference's flagship vision
benchmark (BASELINE.json: ResNet-50 images/sec/chip; model definition parity:
PaddlePaddle/models image_classification/models/resnet.py as exercised by the
ref's test_resnet unittests).

TPU notes: bottleneck convs run in NCHW for API parity; under jit XLA
re-lays-out for the MXU. bf16 activations via models.bf16 wrapper in bench.
"""
from __future__ import annotations

from ..dygraph import Layer, Conv2D, Pool2D, BatchNorm, Linear
from ..dygraph.tape import dispatch_op
from ..param_attr import ParamAttr
from ..initializer import UniformInitializer
import math


class ConvBNLayer(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 groups=1, act=None, data_format='NCHW',
                 space_to_depth=False):
        super().__init__()
        self._conv = Conv2D(num_channels, num_filters, filter_size,
                            stride=stride, padding=(filter_size - 1) // 2,
                            groups=groups, bias_attr=False,
                            data_format=data_format)
        self._bn = BatchNorm(num_filters, act=None, data_layout=data_format)
        self._act = act
        # s2d stem (ops/pallas_conv.py): same 7×7 weight param (checkpoint
        # compatible), re-laid-out as 4×4/s1 on the 2×2 s2d grid so the MXU
        # sees 12 input channels instead of 3
        self._s2d = space_to_depth
        if space_to_depth and (filter_size != 7 or stride != 2
                               or data_format != 'NHWC'):
            raise ValueError('space_to_depth stem requires the 7x7/s2 '
                             'NHWC stem conv')

    def forward(self, x):
        if self._s2d:
            y = dispatch_op('conv2d_stem_s2d',
                            {'x': x, 'weight': self._conv.weight},
                            {'data_format': 'NHWC'})
        else:
            y = self._conv(x)
        y = self._bn(y)
        if self._act:
            y = dispatch_op(self._act, {'x': y}, {})
        return y


class BottleneckBlock(Layer):
    def __init__(self, num_channels, num_filters, stride, shortcut=True,
                 data_format='NCHW'):
        super().__init__()
        df = data_format
        self.conv0 = ConvBNLayer(num_channels, num_filters, 1, act='relu',
                                 data_format=df)
        self.conv1 = ConvBNLayer(num_filters, num_filters, 3, stride=stride,
                                 act='relu', data_format=df)
        self.conv2 = ConvBNLayer(num_filters, num_filters * 4, 1, act=None,
                                 data_format=df)
        if not shortcut:
            self.short = ConvBNLayer(num_channels, num_filters * 4, 1,
                                     stride=stride, act=None, data_format=df)
        self.shortcut = shortcut

    def forward(self, x):
        y = self.conv2(self.conv1(self.conv0(x)))
        short = x if self.shortcut else self.short(x)
        return dispatch_op('relu', {'x': short + y}, {})


class BasicBlock(Layer):
    def __init__(self, num_channels, num_filters, stride, shortcut=True,
                 data_format='NCHW'):
        super().__init__()
        df = data_format
        self.conv0 = ConvBNLayer(num_channels, num_filters, 3, stride=stride,
                                 act='relu', data_format=df)
        self.conv1 = ConvBNLayer(num_filters, num_filters, 3, act=None,
                                 data_format=df)
        if not shortcut:
            self.short = ConvBNLayer(num_channels, num_filters, 1,
                                     stride=stride, act=None, data_format=df)
        self.shortcut = shortcut

    def forward(self, x):
        y = self.conv1(self.conv0(x))
        short = x if self.shortcut else self.short(x)
        return dispatch_op('relu', {'x': short + y}, {})


_DEPTH_CFG = {
    18: ([2, 2, 2, 2], BasicBlock, 1),
    34: ([3, 4, 6, 3], BasicBlock, 1),
    50: ([3, 4, 6, 3], BottleneckBlock, 4),
    101: ([3, 4, 23, 3], BottleneckBlock, 4),
    152: ([3, 8, 36, 3], BottleneckBlock, 4),
}


class ResNet(Layer):
    def __init__(self, layers_depth=50, class_dim=1000, data_format='NCHW',
                 stem_space_to_depth=False):
        super().__init__()
        depth, block_cls, expansion = _DEPTH_CFG[layers_depth]
        num_filters = [64, 128, 256, 512]
        df = data_format
        self.conv = ConvBNLayer(3, 64, 7, stride=2, act='relu',
                                data_format=df,
                                space_to_depth=stem_space_to_depth)
        self.pool = Pool2D(3, 'max', 2, 1, data_format=df)
        from ..dygraph import LayerList
        self.blocks = LayerList()
        num_channels = 64
        for i, n in enumerate(depth):
            for b in range(n):
                shortcut = not (b == 0)
                stride = 2 if b == 0 and i != 0 else 1
                blk = block_cls(num_channels, num_filters[i], stride,
                                shortcut, data_format=df)
                num_channels = num_filters[i] * expansion
                self.blocks.append(blk)
        self.global_pool = Pool2D(pool_type='avg', global_pooling=True,
                                  data_format=df)
        stdv = 1.0 / math.sqrt(num_channels)
        self.out = Linear(
            num_channels, class_dim,
            param_attr=ParamAttr(initializer=UniformInitializer(-stdv, stdv)))
        self._feat_dim = num_channels

    def forward(self, x):
        y = self.pool(self.conv(x))
        for blk in self.blocks:
            y = blk(y)
        y = self.global_pool(y)
        y = dispatch_op('reshape', {'x': y}, {'shape': [0, self._feat_dim]})
        return self.out(y)


def ResNet50(class_dim=1000, data_format='NCHW', stem_space_to_depth=False):
    return ResNet(50, class_dim, data_format=data_format,
                  stem_space_to_depth=stem_space_to_depth)


def ResNet18(class_dim=1000):
    return ResNet(18, class_dim)


def ResNet34(class_dim=1000):
    return ResNet(34, class_dim)


def ResNet101(class_dim=1000):
    return ResNet(101, class_dim)


def ResNet152(class_dim=1000):
    return ResNet(152, class_dim)
