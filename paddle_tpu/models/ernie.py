"""ERNIE-style finetuning (SURVEY §2.10): BERT-architecture backbone with a
task head, mixed-precision + gradient-merge training configuration.

Parity target: the reference's ERNIE finetune recipes (PaddlePaddle/ERNIE
classification finetune with AMP + gradient accumulation). ERNIE 1.0 shares
the BERT architecture; the pretraining difference (entity masking) lives in
the data pipeline, so the model reuses BertModel directly.
"""
from __future__ import annotations

from ..dygraph import Layer
from ..dygraph.nn import Linear, Dropout
from ..dygraph.tape import dispatch_op
from .bert import BertConfig, BertModel


class ErnieConfig(BertConfig):
    @classmethod
    def base(cls, **kw):
        kw.setdefault('vocab_size', 18000)
        kw.setdefault('hidden_size', 768)
        kw.setdefault('num_hidden_layers', 12)
        kw.setdefault('num_attention_heads', 12)
        kw.setdefault('intermediate_size', 3072)
        return cls(**kw)


class ErnieForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig, num_labels=2, dropout=0.1):
        super().__init__()
        self.backbone = BertModel(cfg)
        self.drop = Dropout(dropout,
                            dropout_implementation='upscale_in_train')
        self.classifier = Linear(cfg.hidden_size, num_labels)

    def forward(self, input_ids, token_type_ids=None):
        if token_type_ids is None:
            import numpy as np
            from ..dygraph.tape import Tensor
            token_type_ids = Tensor(
                np.zeros(tuple(input_ids.shape), np.int64),
                stop_gradient=True)
        seq_out, pooled = self.backbone(input_ids, token_type_ids)
        return self.classifier(self.drop(pooled))


def finetune_optimizer(model, learning_rate=5e-5, warmup_steps=0,
                       total_steps=0, weight_decay=0.01, k_steps=1,
                       use_amp=False):
    """The reference ERNIE finetune recipe: AdamW-style decay + warmup
    schedule, optional gradient merge and AMP decoration."""
    import paddle_tpu as fluid
    from ..dygraph.learning_rate_scheduler import (NoamDecay,
                                                   LinearLrWarmup)
    from ..regularizer import L2Decay
    lr = learning_rate
    if warmup_steps:
        lr = LinearLrWarmup(learning_rate, warmup_steps, 0.0, learning_rate)
    opt = fluid.optimizer.AdamOptimizer(
        lr, parameter_list=model.parameters(),
        regularization=L2Decay(weight_decay))
    if use_amp:
        from ..contrib.mixed_precision import decorate
        opt = decorate(opt)
    return opt
