"""MobileNet-V1/V2, VGG, TSM and DCGAN (SURVEY §2.10 vision long tail).

Parity targets: PaddlePaddle/models image_classification/models/{mobilenet,
mobilenet_v2,vgg}.py, video TSM and the DCGAN of the reference's
test_gan unittests — rebuilt on the dygraph Layer API (all convs lower to
lax.conv_general_dilated → MXU).
"""
from __future__ import annotations

import numpy as np

from ..dygraph import Layer
from ..dygraph.nn import (Conv2D, Conv2DTranspose, Pool2D, BatchNorm, Linear,
                          Dropout)
from ..dygraph.tape import dispatch_op, Tensor


class ConvBN(Layer):
    def __init__(self, cin, cout, k, stride=1, padding=None, groups=1,
                 act='relu'):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride,
                           padding=(k - 1) // 2 if padding is None
                           else padding,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm(cout, act=act)

    def forward(self, x):
        return self.bn(self.conv(x))


# ---------------------------------------------------------------------------
# MobileNet V1 / V2
# ---------------------------------------------------------------------------


class DepthwiseSeparable(Layer):
    def __init__(self, cin, cout, stride, scale=1.0):
        super().__init__()
        cin, cout = int(cin * scale), int(cout * scale)
        self.dw = ConvBN(cin, cin, 3, stride=stride, groups=cin)
        self.pw = ConvBN(cin, cout, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()
        s = lambda c: int(c * scale)
        self.stem = ConvBN(3, s(32), 3, stride=2)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        self.blocks = []
        for i, (cin, cout, st) in enumerate(cfg):
            blk = DepthwiseSeparable(cin, cout, st, scale)
            self.add_sublayer(f'ds_{i}', blk)
            self.blocks.append(blk)
        self.pool = Pool2D(pool_type='avg', global_pooling=True)
        self.fc = Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.stem(x)
        for b in self.blocks:
            x = b(x)
        x = self.pool(x)
        x = dispatch_op('reshape', {'x': x}, {'shape': [x.shape[0], -1]})
        return self.fc(x)


class InvertedResidual(Layer):
    def __init__(self, cin, cout, stride, expand):
        super().__init__()
        hidden = int(round(cin * expand))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand != 1:
            layers.append(ConvBN(cin, hidden, 1, act='relu6'))
        layers.append(ConvBN(hidden, hidden, 3, stride=stride, groups=hidden,
                             act='relu6'))
        layers.append(ConvBN(hidden, cout, 1, act=None))
        self.body = []
        for i, l in enumerate(layers):
            self.add_sublayer(f'b{i}', l)
            self.body.append(l)

    def forward(self, x):
        y = x
        for l in self.body:
            y = l(y)
        return x + y if self.use_res else y


class MobileNetV2(Layer):
    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        cin = int(32 * scale)
        self.stem = ConvBN(3, cin, 3, stride=2, act='relu6')
        self.blocks = []
        i = 0
        for expand, c, n, st in cfg:
            cout = int(c * scale)
            for j in range(n):
                blk = InvertedResidual(cin, cout, st if j == 0 else 1, expand)
                self.add_sublayer(f'ir_{i}', blk)
                self.blocks.append(blk)
                cin = cout
                i += 1
        clast = int(1280 * max(1.0, scale))
        self.head = ConvBN(cin, clast, 1, act='relu6')
        self.pool = Pool2D(pool_type='avg', global_pooling=True)
        self.fc = Linear(clast, num_classes)

    def forward(self, x):
        x = self.stem(x)
        for b in self.blocks:
            x = b(x)
        x = self.pool(self.head(x))
        x = dispatch_op('reshape', {'x': x}, {'shape': [x.shape[0], -1]})
        return self.fc(x)


# ---------------------------------------------------------------------------
# VGG
# ---------------------------------------------------------------------------

_VGG_CFGS = {
    11: [1, 1, 2, 2, 2], 13: [2, 2, 2, 2, 2],
    16: [2, 2, 3, 3, 3], 19: [2, 2, 4, 4, 4],
}


class VGG(Layer):
    def __init__(self, layers=16, num_classes=1000, use_bn=True,
                 image_channels=3, fc_dim=4096, input_size=224):
        super().__init__()
        counts = _VGG_CFGS[layers]
        chans = [64, 128, 256, 512, 512]
        self.features = []
        cin = image_channels
        idx = 0
        for n, cout in zip(counts, chans):
            for _ in range(n):
                conv = ConvBN(cin, cout, 3) if use_bn else \
                    Conv2D(cin, cout, 3, padding=1, act='relu')
                self.add_sublayer(f'conv_{idx}', conv)
                self.features.append(conv)
                cin = cout
                idx += 1
            pool = Pool2D(2, pool_type='max', pool_stride=2)
            self.add_sublayer(f'pool_{idx}', pool)
            self.features.append(pool)
        spatial = input_size // 32
        self.fc1 = Linear(512 * spatial * spatial, fc_dim, act='relu')
        self.fc2 = Linear(fc_dim, fc_dim, act='relu')
        self.fc3 = Linear(fc_dim, num_classes)
        self.drop = Dropout(0.5)

    def forward(self, x):
        for f in self.features:
            x = f(x)
        x = dispatch_op('reshape', {'x': x}, {'shape': [x.shape[0], -1]})
        x = self.drop(self.fc1(x))
        x = self.drop(self.fc2(x))
        return self.fc3(x)


# ---------------------------------------------------------------------------
# TSM (Temporal Shift Module) — video classification
# ---------------------------------------------------------------------------


class TSM(Layer):
    """TSM over a ResNet backbone: input (N*T, C, H, W) with seg_num frames
    per clip; each block's input is temporally shifted (temporal_shift op)."""

    def __init__(self, num_classes=400, seg_num=8, backbone_layers=50):
        super().__init__()
        from .resnet import ResNet
        self.seg_num = seg_num
        self.backbone = ResNet(backbone_layers, class_dim=num_classes)
        # wrap each bottleneck with a pre-shift
        for name, block in self.backbone.named_sublayers():
            if hasattr(block, 'conv0') and hasattr(block, 'conv2'):
                block.__class__ = _shifted(block.__class__, seg_num)

    def forward(self, x):
        logits = self.backbone(x)                       # (N*T, classes)
        nt = logits.shape[0]
        n = nt // self.seg_num
        y = dispatch_op('reshape', {'x': logits},
                        {'shape': [n, self.seg_num, -1]})
        return dispatch_op('reduce_mean', {'x': y}, {'dim': 1})


_shift_cache = {}


def _shifted(cls, seg_num):
    key = (cls, seg_num)
    if key in _shift_cache:
        return _shift_cache[key]
    base_forward = cls.forward

    class Shifted(cls):
        def forward(self, x):
            x = dispatch_op('temporal_shift', {'x': x},
                            {'seg_num': seg_num, 'shift_ratio': 0.25})
            return base_forward(self, x)

    Shifted.__name__ = f'Shifted{cls.__name__}'
    _shift_cache[key] = Shifted
    return Shifted


# ---------------------------------------------------------------------------
# DCGAN
# ---------------------------------------------------------------------------


class DCGenerator(Layer):
    def __init__(self, z_dim=100, base=64, out_channels=1):
        super().__init__()
        self.fc = Linear(z_dim, base * 4 * 4 * 4)
        self.base = base
        self.deconv1 = Conv2DTranspose(base * 4, base * 2, 4, stride=2,
                                       padding=1)
        self.bn1 = BatchNorm(base * 2, act='relu')
        self.deconv2 = Conv2DTranspose(base * 2, base, 4, stride=2,
                                       padding=1)
        self.bn2 = BatchNorm(base, act='relu')
        self.deconv3 = Conv2DTranspose(base, out_channels, 4, stride=2,
                                       padding=1)

    def forward(self, z):
        x = self.fc(z)
        x = dispatch_op('reshape', {'x': x},
                        {'shape': [z.shape[0], self.base * 4, 4, 4]})
        x = self.bn1(self.deconv1(x))
        x = self.bn2(self.deconv2(x))
        return dispatch_op('tanh', {'x': self.deconv3(x)}, {})


class DCDiscriminator(Layer):
    def __init__(self, base=64, in_channels=1):
        super().__init__()
        self.conv1 = Conv2D(in_channels, base, 4, stride=2, padding=1)
        self.conv2 = Conv2D(base, base * 2, 4, stride=2, padding=1)
        self.bn2 = BatchNorm(base * 2)
        self.conv3 = Conv2D(base * 2, base * 4, 4, stride=2, padding=1)
        self.bn3 = BatchNorm(base * 4)
        self.fc = Linear(base * 4 * 4 * 4, 1)

    def forward(self, x):
        def lrelu(t):
            return dispatch_op('leaky_relu', {'x': t}, {'alpha': 0.2})
        x = lrelu(self.conv1(x))
        x = lrelu(self.bn2(self.conv2(x)))
        x = lrelu(self.bn3(self.conv3(x)))
        x = dispatch_op('reshape', {'x': x}, {'shape': [x.shape[0], -1]})
        return self.fc(x)
