"""Transformer for NMT (WMT en-de) — encoder-decoder with beam-search
inference.

Parity target: the reference trains this as its NLP flagship
(PaddlePaddle/models neural_machine_translation/transformer, exercised by
the ref's test_transformer unittests); `big`/`base` configs match the paper.

TPU notes: fixed max_length padded batches with additive attention biases
(no dynamic shapes); greedy/beam decode runs a fixed-trip-count loop; the
optional `sequence_parallel` flag routes self-attention through
parallel.ring_attention over the 'sp' mesh axis for long-context training.
"""
from __future__ import annotations

import math

import numpy as np

from ..dygraph import Layer
from ..dygraph.nn import Linear, Embedding, LayerNorm, Dropout
from ..dygraph.tape import dispatch_op, Tensor
from ..param_attr import ParamAttr
from ..initializer import NormalInitializer

_NEG = -1e9


class TransformerConfig:
    def __init__(self, src_vocab_size=32000, trg_vocab_size=32000,
                 max_length=256, d_model=512, n_head=8, n_layer=6,
                 d_inner=2048, dropout=0.1, weight_sharing=False,
                 sequence_parallel=False):
        self.src_vocab_size = src_vocab_size
        self.trg_vocab_size = trg_vocab_size
        self.max_length = max_length
        self.d_model = d_model
        self.n_head = n_head
        self.n_layer = n_layer
        self.d_inner = d_inner
        self.dropout = dropout
        self.weight_sharing = weight_sharing
        self.sequence_parallel = sequence_parallel

    @classmethod
    def base(cls, **kw):
        return cls(d_model=512, n_head=8, n_layer=6, d_inner=2048, **kw)

    @classmethod
    def big(cls, **kw):
        return cls(d_model=1024, n_head=16, n_layer=6, d_inner=4096, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault('src_vocab_size', 64)
        kw.setdefault('trg_vocab_size', 64)
        kw.setdefault('max_length', 16)
        return cls(d_model=32, n_head=2, n_layer=2, d_inner=64, **kw)


def _pinit(cfg):
    return ParamAttr(initializer=NormalInitializer(
        0.0, cfg.d_model ** -0.5))


def position_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype('float32')
    i = np.arange(d_model // 2)[None, :].astype('float32')
    angle = pos / np.power(10000.0, 2 * i / d_model)
    enc = np.zeros((max_len, d_model), 'float32')
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


class _MHA(Layer):
    def __init__(self, cfg, sequence_parallel=False):
        super().__init__()
        d = cfg.d_model
        self.q = Linear(d, d, param_attr=_pinit(cfg))
        self.k = Linear(d, d, param_attr=_pinit(cfg))
        self.v = Linear(d, d, param_attr=_pinit(cfg))
        self.out = Linear(d, d, param_attr=_pinit(cfg))
        self.n_head = cfg.n_head
        self.d_head = d // cfg.n_head
        self.drop = Dropout(cfg.dropout,
                            dropout_implementation='upscale_in_train')
        self.sequence_parallel = sequence_parallel

    def forward(self, q_in, kv_in, bias=None, causal=False):
        b, sq, d = q_in.shape
        sk = kv_in.shape[1]

        def heads(t, s):
            t = dispatch_op('reshape', {'x': t},
                            {'shape': [b, s, self.n_head, self.d_head]})
            return t

        q = heads(self.q(q_in), sq)              # (B, S, H, Dh)
        k = heads(self.k(kv_in), sk)
        v = heads(self.v(kv_in), sk)
        if self.sequence_parallel and bias is None and q_in is kv_in:
            # long-context path: ring attention over the 'sp' mesh axis
            from ..parallel.ring_attention import ring_attention
            ctx = Tensor(ring_attention(q.value, k.value, v.value,
                                        causal=causal),
                         stop_gradient=False) if isinstance(q, Tensor) \
                else ring_attention(q, k, v, causal=causal)
            ctx = dispatch_op('reshape', {'x': ctx}, {'shape': [b, sq, d]})
            return self.out(ctx)
        qt = dispatch_op('transpose', {'x': q}, {'perm': [0, 2, 1, 3]})
        kt = dispatch_op('transpose', {'x': k}, {'perm': [0, 2, 1, 3]})
        vt = dispatch_op('transpose', {'x': v}, {'perm': [0, 2, 1, 3]})
        scores = dispatch_op('matmul', {'x': qt, 'y': kt},
                             {'transpose_y': True,
                              'alpha': 1.0 / math.sqrt(self.d_head)})
        if bias is not None:
            scores = scores + bias
        if causal:
            mask = np.triu(np.full((sq, sk), _NEG, 'float32'), 1)
            scores = scores + Tensor(mask[None, None], stop_gradient=True)
        probs = self.drop(dispatch_op('softmax', {'x': scores}, {}))
        ctx = dispatch_op('matmul', {'x': probs, 'y': vt}, {})
        ctx = dispatch_op('transpose', {'x': ctx}, {'perm': [0, 2, 1, 3]})
        ctx = dispatch_op('reshape', {'x': ctx}, {'shape': [b, sq, d]})
        return self.out(ctx)


class _FFN(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.fc1 = Linear(cfg.d_model, cfg.d_inner, param_attr=_pinit(cfg),
                          act='relu')
        self.fc2 = Linear(cfg.d_inner, cfg.d_model, param_attr=_pinit(cfg))
        self.drop = Dropout(cfg.dropout,
                            dropout_implementation='upscale_in_train')

    def forward(self, x):
        return self.fc2(self.drop(self.fc1(x)))


class EncoderLayer(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.attn = _MHA(cfg, sequence_parallel=cfg.sequence_parallel)
        self.ln1 = LayerNorm(cfg.d_model)
        self.ffn = _FFN(cfg)
        self.ln2 = LayerNorm(cfg.d_model)
        self.drop = Dropout(cfg.dropout,
                            dropout_implementation='upscale_in_train')

    def forward(self, x, bias):
        x = self.ln1(x + self.drop(self.attn(x, x, bias)))
        return self.ln2(x + self.drop(self.ffn(x)))


class DecoderLayer(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.self_attn = _MHA(cfg)
        self.ln1 = LayerNorm(cfg.d_model)
        self.cross_attn = _MHA(cfg)
        self.ln2 = LayerNorm(cfg.d_model)
        self.ffn = _FFN(cfg)
        self.ln3 = LayerNorm(cfg.d_model)
        self.drop = Dropout(cfg.dropout,
                            dropout_implementation='upscale_in_train')

    def forward(self, x, enc_out, self_bias, cross_bias):
        x = self.ln1(x + self.drop(self.self_attn(x, x, self_bias,
                                                  causal=True)))
        x = self.ln2(x + self.drop(self.cross_attn(x, enc_out, cross_bias)))
        return self.ln3(x + self.drop(self.ffn(x)))


class Transformer(Layer):
    def __init__(self, cfg: TransformerConfig):
        super().__init__()
        self.cfg = cfg
        self.src_emb = Embedding([cfg.src_vocab_size, cfg.d_model],
                                 param_attr=_pinit(cfg))
        if cfg.weight_sharing and cfg.src_vocab_size == cfg.trg_vocab_size:
            self.trg_emb = self.src_emb
        else:
            self.trg_emb = Embedding([cfg.trg_vocab_size, cfg.d_model],
                                     param_attr=_pinit(cfg))
        self.pos_enc = position_encoding(cfg.max_length, cfg.d_model)
        self.enc_layers = []
        self.dec_layers = []
        for i in range(cfg.n_layer):
            enc = EncoderLayer(cfg)
            dec = DecoderLayer(cfg)
            self.add_sublayer(f'enc_{i}', enc)
            self.add_sublayer(f'dec_{i}', dec)
            self.enc_layers.append(enc)
            self.dec_layers.append(dec)
        self.proj = Linear(cfg.d_model, cfg.trg_vocab_size,
                           param_attr=_pinit(cfg))
        self.drop = Dropout(cfg.dropout,
                            dropout_implementation='upscale_in_train')

    def _embed(self, emb, ids):
        x = emb(ids)
        s = ids.shape[1]
        # lookup_table squeezes (B, 1) id columns (LoD convention) — restore
        x = dispatch_op('reshape', {'x': x},
                        {'shape': [ids.shape[0], s, self.cfg.d_model]})
        x = x * (self.cfg.d_model ** 0.5)
        pe = Tensor(self.pos_enc[None, :s], stop_gradient=True)
        return self.drop(x + pe)

    @staticmethod
    def _pad_bias(pad_mask):
        """(B, S) 1=valid → (B, 1, 1, S) additive bias."""
        m = dispatch_op('reshape', {'x': pad_mask},
                        {'shape': [pad_mask.shape[0], 1, 1,
                                   pad_mask.shape[1]]})
        return (1.0 - m) * _NEG

    def encode(self, src_ids, src_mask=None):
        bias = self._pad_bias(src_mask) if src_mask is not None else None
        x = self._embed(self.src_emb, src_ids)
        for layer in self.enc_layers:
            x = layer(x, bias)
        return x

    def decode(self, trg_ids, enc_out, src_mask=None):
        cross_bias = self._pad_bias(src_mask) if src_mask is not None \
            else None
        x = self._embed(self.trg_emb, trg_ids)
        for layer in self.dec_layers:
            x = layer(x, enc_out, None, cross_bias)
        return self.proj(x)

    def forward(self, src_ids, trg_ids, src_mask=None):
        return self.decode(trg_ids, self.encode(src_ids, src_mask), src_mask)


def transformer_loss(logits, labels, pad_id=0, label_smooth_eps=0.1):
    """Label-smoothed CE, pad positions masked out. logits (B, S, V),
    labels (B, S)."""
    V = logits.shape[-1]
    flat = dispatch_op('reshape', {'x': logits}, {'shape': [-1, V]})
    lbl = dispatch_op('reshape', {'x': labels}, {'shape': [-1, 1]})
    onehot = dispatch_op('one_hot', {'x': lbl}, {'depth': V})
    onehot = dispatch_op('reshape', {'x': onehot}, {'shape': [-1, V]})
    if label_smooth_eps:
        onehot = onehot * (1.0 - label_smooth_eps) + \
            label_smooth_eps / V
    loss = dispatch_op('softmax_with_cross_entropy',
                       {'logits': flat, 'label': onehot},
                       {'soft_label': True})[0]
    mask = dispatch_op('cast', {'x': dispatch_op(
        'not_equal', {'x': lbl,
                      'y': Tensor(np.array([pad_id], np.int64),
                                  stop_gradient=True)}, {})},
        {'dtype': 'float32'})
    loss = dispatch_op('reshape', {'x': loss}, {'shape': [-1, 1]}) * mask
    total = dispatch_op('reduce_sum', {'x': loss}, {})
    denom = dispatch_op('reduce_sum', {'x': mask}, {})
    return total / (denom + 1e-9)


def greedy_decode(model, src_ids, bos_id, eos_id, max_len=32, src_mask=None):
    """Fixed-trip greedy decode; returns (B, max_len) int64 ids (eos-padded
    past each row's stop).

    Shape discipline: the decoder runs every step at ONE fixed
    (B, max_len+1) shape and step t reads the logits column t — the causal
    mask makes that column depend only on tokens 0..t, so the eos padding
    in the unwritten tail never leaks in. The original grew ``ys`` by one
    token per step, which re-traced and re-compiled a fresh program for
    every generated length (tests/models/test_decode_retrace.py asserts
    the compile count now stays flat via the eager kernel-cache
    counters)."""
    enc = model.encode(src_ids, src_mask)
    B = src_ids.shape[0]
    ys = np.full((B, max_len + 1), eos_id, np.int64)
    ys[:, 0] = bos_id
    done = np.zeros(B, bool)
    for t in range(max_len):
        logits = model.decode(Tensor(ys, stop_gradient=True), enc, src_mask)
        nxt = np.asarray(logits.numpy())[:, t].argmax(-1)
        nxt = np.where(done, eos_id, nxt)
        done |= (nxt == eos_id)
        ys[:, t + 1] = nxt
        if done.all():
            break
    return ys[:, 1:]


def beam_search_decode(model, src_ids, bos_id, eos_id, beam_size=4,
                       max_len=32, src_mask=None, alpha=0.6):
    """Beam search over the decoder (ref: the transformer model's
    fast_decode path). Dense (B*W) beams, fixed max_len trip count, and —
    like greedy_decode above — ONE fixed (B*W, max_len+1) decoder shape
    for every step (step t reads logits column t; beam reordering gathers
    host-side rows of the fixed buffer), so the whole search costs a
    single decoder compile instead of one per generated length."""
    enc = model.encode(src_ids, src_mask)
    B = src_ids.shape[0]
    W = beam_size
    enc_np = np.asarray(enc.numpy() if hasattr(enc, 'numpy') else enc)
    enc_t = Tensor(np.repeat(enc_np, W, axis=0), stop_gradient=True)
    mask_t = None
    if src_mask is not None:
        m_np = np.asarray(src_mask.numpy() if hasattr(src_mask, 'numpy')
                          else src_mask)
        mask_t = Tensor(np.repeat(m_np, W, axis=0), stop_gradient=True)
    ys = np.full((B * W, max_len + 1), eos_id, np.int64)
    ys[:, 0] = bos_id
    scores = np.tile(np.array([0.0] + [-1e9] * (W - 1), np.float32), B)
    finished = np.zeros(B * W, bool)
    for t in range(max_len):
        logits = model.decode(Tensor(ys, stop_gradient=True), enc_t, mask_t)
        logp = np.asarray(
            dispatch_op('log_softmax',
                        {'x': logits}, {}).numpy())[:, t]     # (B*W, V)
        V = logp.shape[-1]
        # finished beams only extend with eos at score 0
        fin_row = np.full(V, -1e9, np.float32)
        fin_row[eos_id] = 0.0
        logp = np.where(finished[:, None], fin_row[None], logp)
        total = scores[:, None] + logp                        # (B*W, V)
        total = total.reshape(B, W * V)
        top = np.argsort(-total, axis=1)[:, :W]               # (B, W)
        scores = np.take_along_axis(total, top, 1).reshape(-1)
        beam_idx = top // V + np.arange(B)[:, None] * W
        tok = (top % V).astype(np.int64)
        ys = ys[beam_idx.reshape(-1)]
        ys[:, t + 1] = tok.reshape(-1)
        finished = finished[beam_idx.reshape(-1)] | \
            (tok.reshape(-1) == eos_id)
        if finished.all():
            break
    # length-normalized best beam per batch row
    lens = (ys[:, 1:] != eos_id).sum(1) + 1
    norm = scores / (((5 + lens) / 6.0) ** alpha)
    best = norm.reshape(B, W).argmax(1) + np.arange(B) * W
    return ys[best, 1:]
