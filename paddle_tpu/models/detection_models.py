"""YOLOv3 (DarkNet-53) and OCR CRNN-CTC models (SURVEY §2.10).

Parity targets: PaddlePaddle/models yolov3 and ocr_recognition (CRNN-CTC),
wired onto this framework's detection ops (yolov3_loss / yolo_box /
multiclass_nms) and warpctc/ctc_greedy_decoder.
"""
from __future__ import annotations

import numpy as np

from ..dygraph import Layer
from ..dygraph.nn import Conv2D, BatchNorm, Pool2D, Linear
from ..dygraph.tape import dispatch_op, Tensor


class _ConvBNLeaky(Layer):
    def __init__(self, cin, cout, k, stride=1):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride,
                           padding=(k - 1) // 2, bias_attr=False)
        self.bn = BatchNorm(cout)

    def forward(self, x):
        return dispatch_op('leaky_relu', {'x': self.bn(self.conv(x))},
                           {'alpha': 0.1})


class _DarkBlock(Layer):
    def __init__(self, c):
        super().__init__()
        self.c1 = _ConvBNLeaky(c, c // 2, 1)
        self.c2 = _ConvBNLeaky(c // 2, c, 3)

    def forward(self, x):
        return x + self.c2(self.c1(x))


class DarkNet53(Layer):
    """Backbone; returns C3/C4/C5 feature maps."""

    def __init__(self, depths=(1, 2, 8, 8, 4)):
        super().__init__()
        self.stem = _ConvBNLeaky(3, 32, 3)
        chans = [64, 128, 256, 512, 1024]
        self.stages = []
        cin = 32
        for si, (n, c) in enumerate(zip(depths, chans)):
            stage = [_ConvBNLeaky(cin, c, 3, stride=2)]
            for bi in range(n):
                stage.append(_DarkBlock(c))
            for li, l in enumerate(stage):
                self.add_sublayer(f's{si}_{li}', l)
            self.stages.append(stage)
            cin = c

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for stage in self.stages:
            for l in stage:
                x = l(x)
            feats.append(x)
        return feats[2], feats[3], feats[4]       # C3, C4, C5


class _YoloHead(Layer):
    def __init__(self, cin, cmid, n_anchors, class_num):
        super().__init__()
        self.body = []
        chans = [cin, cmid, cmid * 2, cmid, cmid * 2, cmid]
        for i in range(5):
            k = 1 if i % 2 == 0 else 3
            l = _ConvBNLeaky(chans[i], chans[i + 1], k)
            self.add_sublayer(f'h{i}', l)
            self.body.append(l)
        self.tip = _ConvBNLeaky(cmid, cmid * 2, 3)
        self.pred = Conv2D(cmid * 2, n_anchors * (5 + class_num), 1)

    def forward(self, x):
        for l in self.body:
            x = l(x)
        route = x
        return route, self.pred(self.tip(x))


class YOLOv3(Layer):
    ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45,
               59, 119, 116, 90, 156, 198, 373, 326]
    ANCHOR_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]

    def __init__(self, class_num=80):
        super().__init__()
        self.class_num = class_num
        self.backbone = DarkNet53()
        self.head5 = _YoloHead(1024, 512, 3, class_num)
        self.route5 = _ConvBNLeaky(512, 256, 1)
        self.head4 = _YoloHead(512 + 256, 256, 3, class_num)
        self.route4 = _ConvBNLeaky(256, 128, 1)
        self.head3 = _YoloHead(256 + 128, 128, 3, class_num)

    @staticmethod
    def _up2(x):
        h, w = x.shape[2], x.shape[3]
        return dispatch_op('interpolate', {'x': x},
                           {'out_shape': [h * 2, w * 2], 'method': 'nearest',
                            'align_corners': False})

    def forward(self, img):
        c3, c4, c5 = self.backbone(img)
        r5, p5 = self.head5(c5)
        u5 = self._up2(self.route5(r5))
        r4, p4 = self.head4(dispatch_op('concat', {'xs': [u5, c4]},
                                        {'axis': 1}))
        u4 = self._up2(self.route4(r4))
        _, p3 = self.head3(dispatch_op('concat', {'xs': [u4, c3]},
                                       {'axis': 1}))
        return [p5, p4, p3]                # strides 32, 16, 8

    def loss(self, outputs, gt_box, gt_label, gt_score=None,
             ignore_thresh=0.7):
        total = None
        for out, mask, down in zip(outputs, self.ANCHOR_MASKS, (32, 16, 8)):
            l = dispatch_op(
                'yolov3_loss',
                {'x': out, 'gt_box': gt_box, 'gt_label': gt_label,
                 'gt_score': gt_score},
                {'anchors': self.ANCHORS, 'anchor_mask': mask,
                 'class_num': self.class_num, 'ignore_thresh': ignore_thresh,
                 'downsample_ratio': down})[0]
            s = dispatch_op('reduce_mean', {'x': l}, {})
            total = s if total is None else total + s
        return total

    def infer(self, outputs, img_size, conf_thresh=0.01, nms_thresh=0.45,
              keep_top_k=100):
        boxes, scores = [], []
        for out, mask, down in zip(outputs, self.ANCHOR_MASKS, (32, 16, 8)):
            anchors = []
            for m in mask:
                anchors += self.ANCHORS[2 * m:2 * m + 2]
            b, s = dispatch_op(
                'yolo_box', {'x': out, 'img_size': img_size},
                {'anchors': anchors, 'class_num': self.class_num,
                 'conf_thresh': conf_thresh, 'downsample_ratio': down})
            boxes.append(b)
            scores.append(s)
        all_b = dispatch_op('concat', {'xs': boxes}, {'axis': 1})
        all_s = dispatch_op('concat', {'xs': scores}, {'axis': 1})
        all_s = dispatch_op('transpose', {'x': all_s}, {'perm': [0, 2, 1]})
        out = dispatch_op(
            'multiclass_nms', {'bboxes': all_b, 'scores': all_s},
            {'background_label': -1, 'score_threshold': conf_thresh,
             'nms_top_k': 400, 'nms_threshold': nms_thresh,
             'keep_top_k': keep_top_k, 'normalized': False})[0]
        return out


# ---------------------------------------------------------------------------
# OCR CRNN-CTC
# ---------------------------------------------------------------------------


class CRNN(Layer):
    """Conv feature extractor → bidirectional GRU → per-timestep vocab
    logits; train with warpctc, decode with ctc_greedy_decoder."""

    def __init__(self, num_classes=95, image_channels=1, hidden=96):
        super().__init__()
        self.convs = []
        cfg = [(image_channels, 16, 2), (16, 32, 2), (32, 64, (2, 1)),
               (64, 96, (2, 1))]
        for i, (cin, cout, stride) in enumerate(cfg):
            conv = Conv2D(cin, cout, 3, stride=1, padding=1, act='relu')
            pool = Pool2D(2, 'max', stride, 0, ceil_mode=True)
            self.add_sublayer(f'conv_{i}', conv)
            self.add_sublayer(f'pool_{i}', pool)
            self.convs.append((conv, pool))
        from .nlp_rec import DyGRU
        feat_dim = 96 * 2      # channels × collapsed height (32→2 via pools)
        self.fw = DyGRU(feat_dim, hidden)
        self.bw = DyGRU(feat_dim, hidden, reverse=True)
        self.proj = Linear(hidden * 2, num_classes + 1)   # + blank
        self.blank = num_classes

    def forward(self, img):
        x = img
        for conv, pool in self.convs:
            x = pool(conv(x))
        # (B, C, H, W) → time-major sequence over W: (B, W, C*H)
        b, c, h, w = x.shape
        x = dispatch_op('transpose', {'x': x}, {'perm': [0, 3, 1, 2]})
        x = dispatch_op('reshape', {'x': x}, {'shape': [b, w, c * h]})
        fw_outs, _ = self.fw(x)
        bw_outs, _ = self.bw(x)
        outs = dispatch_op('concat', {'xs': [fw_outs, bw_outs]},
                           {'axis': -1})
        return self.proj(outs)                            # (B, W, classes+1)

    def ctc_loss(self, logits, label, label_length=None):
        loss = dispatch_op('warpctc',
                           {'logits': logits, 'label': label,
                            'label_len': label_length},
                           {'blank': self.blank, 'norm_by_times': False})
        return dispatch_op('reduce_mean', {'x': loss}, {})

    def decode(self, logits):
        probs = dispatch_op('softmax', {'x': logits}, {})
        out, lens = dispatch_op('ctc_greedy_decoder', {'x': probs},
                                {'blank': self.blank})
        return out, lens
