"""word2vec, seq2seq (RNN attention), DeepFM, GRU4Rec (SURVEY §2.10).

Parity targets: PaddlePaddle/models word2vec / seq2seq (RNN search) /
DeepFM / gru4rec as exercised by the reference's imperative unittests
(test_imperative_deepcf etc.) — rebuilt on the dygraph Layer API.
"""
from __future__ import annotations

import math

import numpy as np

from ..dygraph import Layer
from ..dygraph.nn import Embedding, Linear, Dropout
from ..dygraph.tape import dispatch_op, Tensor
from ..param_attr import ParamAttr
from ..initializer import UniformInitializer, XavierInitializer


# ---------------------------------------------------------------------------
# word2vec — skip-gram with negative sampling
# ---------------------------------------------------------------------------


class Word2Vec(Layer):
    def __init__(self, vocab_size, embedding_size=128, neg_num=5):
        super().__init__()
        bound = 0.5 / embedding_size
        self.emb_in = Embedding(
            [vocab_size, embedding_size],
            param_attr=ParamAttr(initializer=UniformInitializer(
                -bound, bound)))
        self.emb_out = Embedding(
            [vocab_size, embedding_size],
            param_attr=ParamAttr(initializer=UniformInitializer(
                -bound, bound)))
        self.neg_num = neg_num
        self.vocab_size = vocab_size

    def forward(self, center, targets):
        """center (B,), targets (B, 1+neg) [col 0 = positive]. Returns
        sampled-softmax BCE loss."""
        c = self.emb_in(center)                      # (B, D)
        t = self.emb_out(targets)                    # (B, 1+neg, D)
        logits = dispatch_op('matmul',
                             {'x': t,
                              'y': dispatch_op('unsqueeze', {'x': c},
                                               {'axes': [2]})}, {})
        logits = dispatch_op('reshape', {'x': logits},
                             {'shape': [center.shape[0], -1]})  # (B, 1+neg)
        B, K = logits.shape
        labels = np.zeros((B, K), np.float32)
        labels[:, 0] = 1.0
        loss = dispatch_op('sigmoid_cross_entropy_with_logits',
                           {'x': logits,
                            'label': Tensor(labels, stop_gradient=True)}, {})
        return dispatch_op('reduce_mean', {'x': loss}, {})


# ---------------------------------------------------------------------------
# dygraph GRU (parameters tracked by Layer, eager step loop)
# ---------------------------------------------------------------------------


class DyGRU(Layer):
    """Batch-major GRU as a dygraph Layer: (B, T, D) → (B, T, H)."""

    def __init__(self, input_dim, hidden, reverse=False):
        super().__init__()
        self.gate = Linear(input_dim + hidden, 2 * hidden, act='sigmoid')
        self.cand = Linear(input_dim + hidden, hidden, act='tanh')
        self.hidden = hidden
        self.reverse = reverse

    def forward(self, x, h0=None):
        B, T, _ = x.shape
        h = h0 if h0 is not None else Tensor(
            np.zeros((B, self.hidden), np.float32), stop_gradient=True)
        outs = []
        steps = range(T - 1, -1, -1) if self.reverse else range(T)
        for t in steps:
            xt = dispatch_op('slice', {'x': x},
                             {'axes': [1], 'starts': [t], 'ends': [t + 1]})
            xt = dispatch_op('reshape', {'x': xt}, {'shape': [B, -1]})
            xh = dispatch_op('concat', {'xs': [xt, h]}, {'axis': -1})
            gates = self.gate(xh)
            u = dispatch_op('slice', {'x': gates},
                            {'axes': [1], 'starts': [0],
                             'ends': [self.hidden]})
            r = dispatch_op('slice', {'x': gates},
                            {'axes': [1], 'starts': [self.hidden],
                             'ends': [2 * self.hidden]})
            c = self.cand(dispatch_op('concat', {'xs': [xt, r * h]},
                                      {'axis': -1}))
            h = u * h + (1.0 - u) * c
            outs.append(h)
        if self.reverse:
            outs = outs[::-1]
        stacked = dispatch_op('stack', {'xs': outs}, {'axis': 1})
        return stacked, h


# ---------------------------------------------------------------------------
# seq2seq — GRU encoder/decoder with attention (RNN search)
# ---------------------------------------------------------------------------


class Seq2SeqAttn(Layer):
    def __init__(self, src_vocab, trg_vocab, hidden=128, emb_dim=128):
        super().__init__()
        self.src_emb = Embedding([src_vocab, emb_dim])
        self.trg_emb = Embedding([trg_vocab, emb_dim])
        self.enc = DyGRU(emb_dim, hidden)
        self.dec = DyGRU(emb_dim, hidden)
        self.attn_w = Linear(hidden, hidden)
        self.out = Linear(hidden * 2, trg_vocab)
        self.hidden = hidden

    def forward(self, src_ids, trg_in):
        src = self.src_emb(src_ids)
        enc_outs, enc_final = self.enc(src)
        trg = self.trg_emb(trg_in)
        dec_outs, _ = self.dec(trg, enc_final)
        # Luong-style dot attention of each decoder step over encoder outs
        q = self.attn_w(dec_outs)                          # (B, Td, H)
        scores = dispatch_op('matmul', {'x': q, 'y': enc_outs},
                             {'transpose_y': True,
                              'alpha': 1.0 / math.sqrt(self.hidden)})
        probs = dispatch_op('softmax', {'x': scores}, {})
        ctx = dispatch_op('matmul', {'x': probs, 'y': enc_outs}, {})
        cat = dispatch_op('concat', {'xs': [dec_outs, ctx]}, {'axis': -1})
        return self.out(cat)                               # (B, Td, V)


# ---------------------------------------------------------------------------
# DeepFM — factorization machine + deep tower over sparse id features
# ---------------------------------------------------------------------------


class DeepFM(Layer):
    def __init__(self, field_num, feature_size, embedding_size=8,
                 deep_layers=(64, 32), is_sparse=False):
        super().__init__()
        init = ParamAttr(initializer=XavierInitializer())
        # is_sparse=True: both tables train through the rows-only
        # gradient fast path (docs/SPARSE.md) — the recsys-scale setting
        # where feature_size is millions and a batch touches thousands
        self.fm_w = Embedding([feature_size, 1], param_attr=init,
                              is_sparse=is_sparse)
        self.emb = Embedding([feature_size, embedding_size], param_attr=init,
                             is_sparse=is_sparse)
        dims = [field_num * embedding_size] + list(deep_layers)
        self.deep = []
        for i in range(len(deep_layers)):
            fc = Linear(dims[i], dims[i + 1], act='relu', param_attr=init)
            self.add_sublayer(f'deep_{i}', fc)
            self.deep.append(fc)
        self.out = Linear(deep_layers[-1] + 2, 1)
        self.field_num = field_num
        self.embedding_size = embedding_size

    def forward(self, feat_ids, feat_vals):
        """feat_ids (B, F) int64, feat_vals (B, F) float32 → (B, 1) logit."""
        B, F = feat_ids.shape
        vals = dispatch_op('unsqueeze', {'x': feat_vals}, {'axes': [2]})
        # first-order term
        w = self.fm_w(feat_ids)                       # (B, F, 1)
        first = dispatch_op('reduce_sum', {'x': w * vals},
                            {'dim': 1})               # (B, 1)
        # second-order FM term: 0.5 * ((Σv)² - Σv²)
        e = self.emb(feat_ids) * vals                 # (B, F, D)
        sum_sq = dispatch_op('square', {'x': dispatch_op(
            'reduce_sum', {'x': e}, {'dim': 1})}, {})
        sq_sum = dispatch_op('reduce_sum', {'x': dispatch_op(
            'square', {'x': e}, {})}, {'dim': 1})
        second = 0.5 * dispatch_op('reduce_sum', {'x': sum_sq - sq_sum},
                                   {'dim': 1, 'keep_dim': True})
        # deep tower
        deep = dispatch_op('reshape', {'x': e},
                           {'shape': [B, F * self.embedding_size]})
        for fc in self.deep:
            deep = fc(deep)
        cat = dispatch_op('concat', {'xs': [first, second, deep]},
                          {'axis': 1})
        return self.out(cat)


# ---------------------------------------------------------------------------
# GRU4Rec — session-based recommendation
# ---------------------------------------------------------------------------


class GRU4Rec(Layer):
    def __init__(self, vocab_size, hidden=128, emb_dim=128):
        super().__init__()
        self.emb = Embedding([vocab_size, emb_dim])
        self.gru = DyGRU(emb_dim, hidden)
        self.proj = Linear(hidden, vocab_size)

    def forward(self, item_ids):
        """item_ids (B, T) → next-item logits (B, T, V)."""
        x = self.emb(item_ids)
        outs, _ = self.gru(x)
        return self.proj(outs)
