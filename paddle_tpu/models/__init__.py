"""Model zoo (SURVEY §2.10)."""
from .lenet import LeNet, build_static_lenet
from .resnet import (ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
                     ResNet152)
from .bert import (BertConfig, BertModel, BertForPretraining, pretrain_loss)
