"""Model zoo (SURVEY §2.10)."""
from .lenet import LeNet, build_static_lenet
from .resnet import (ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
                     ResNet152)
from .bert import (BertConfig, BertModel, BertForPretraining, pretrain_loss)
from .causal_lm import (CausalLMConfig, TransformerLM, lm_loss,
                        greedy_generate)
from .transformer import (TransformerConfig, Transformer, transformer_loss,
                          greedy_decode, beam_search_decode)
from .vision import (MobileNetV1, MobileNetV2, VGG, TSM, DCGenerator,
                     DCDiscriminator)
from .nlp_rec import Word2Vec, Seq2SeqAttn, DeepFM, GRU4Rec
from .detection_models import DarkNet53, YOLOv3, CRNN
from .ernie import (ErnieConfig, ErnieForSequenceClassification,
                    finetune_optimizer)
