"""ParallelExecutor (ref: python/paddle/fluid/parallel_executor.py +
paddle/fluid/framework/details/ SSA-graph executor).

TPU redesign: there is no per-device graph clone — ONE jitted program with
batch feeds sharded over the device mesh; XLA emits the fused-allreduce
schedule over ICI (the reference's fuse_all_reduce pass is free here).
"""
from __future__ import annotations

import numpy as np

from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from .core.scope import global_scope
from .executor import Executor
from .framework import default_main_program


class ParallelExecutor:
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        self._program = main_program or default_main_program()
        self._compiled = CompiledProgram(
            self._program, build_strategy).with_data_parallel(
                loss_name=loss_name, exec_strategy=exec_strategy)
        self._exe = Executor()
        self._scope = scope or global_scope()

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._compiled, feed=feed, fetch_list=fetch_list,
                             scope=self._scope, return_numpy=return_numpy)

    def drop_local_exe_scopes(self):
        pass
