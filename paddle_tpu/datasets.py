"""Dataset readers (SURVEY §2.7): MNIST, CIFAR, ImageNet-folder, synthetic.

Parity target: python/paddle/dataset/{mnist,cifar,flowers}.py — reader
creators yielding (image, label) samples, composable with the reader
decorators and DataLoader. This environment has no network egress, so the
readers load the standard files from a data_dir when present and otherwise
fall back to a deterministic synthetic stream with identical shapes/dtypes
(marked by `is_synthetic`), which keeps benches and tests runnable anywhere.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

DATA_HOME = os.environ.get('PADDLE_TPU_DATA_HOME',
                           os.path.expanduser('~/.cache/paddle_tpu/dataset'))

_log = __import__('logging').getLogger(__name__)


def _fallback(name, missing):
    """Loud, once-per-path warning: convergence/accuracy runs must not
    silently train on random pixels."""
    _log.warning(
        "paddle_tpu.datasets.%s: data files not found (%s) — falling back "
        "to a SYNTHETIC random stream (reader.is_synthetic=True). Results "
        "are meaningless for accuracy; set PADDLE_TPU_DATA_HOME or pass "
        "data_dir to use real data.", name, missing)


def _synthetic(shape, num_classes, n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            img = rng.rand(*shape).astype('float32')
            yield img, rng.randint(0, num_classes)
    reader.is_synthetic = True
    return reader


# ---------------------------------------------------------------------------
# MNIST (IDX files)
# ---------------------------------------------------------------------------


def _mnist_reader(images_path, labels_path, n_synth, seed):
    if os.path.exists(images_path) and os.path.exists(labels_path):
        def reader():
            with gzip.open(images_path, 'rb') if images_path.endswith('.gz') \
                    else open(images_path, 'rb') as f:
                magic, n, rows, cols = struct.unpack('>IIII', f.read(16))
                imgs = np.frombuffer(f.read(), np.uint8).reshape(n, rows,
                                                                 cols)
            with gzip.open(labels_path, 'rb') if labels_path.endswith('.gz') \
                    else open(labels_path, 'rb') as f:
                struct.unpack('>II', f.read(8))
                labels = np.frombuffer(f.read(), np.uint8)
            for img, lab in zip(imgs, labels):
                yield (img.astype('float32') / 127.5 - 1.0).reshape(1, 28,
                                                                    28), \
                    int(lab)
        reader.is_synthetic = False
        return reader
    _fallback('mnist', images_path)
    return _synthetic((1, 28, 28), 10, n_synth, seed)


def mnist_train(data_dir=None):
    d = data_dir or os.path.join(DATA_HOME, 'mnist')
    return _mnist_reader(os.path.join(d, 'train-images-idx3-ubyte.gz'),
                         os.path.join(d, 'train-labels-idx1-ubyte.gz'),
                         1024, 0)


def mnist_test(data_dir=None):
    d = data_dir or os.path.join(DATA_HOME, 'mnist')
    return _mnist_reader(os.path.join(d, 't10k-images-idx3-ubyte.gz'),
                         os.path.join(d, 't10k-labels-idx1-ubyte.gz'),
                         256, 1)


# ---------------------------------------------------------------------------
# CIFAR-10/100 (python pickle tarballs)
# ---------------------------------------------------------------------------


def _cifar_reader(tar_path, member_match, label_key, n_synth, seed):
    if os.path.exists(tar_path):
        def reader():
            with tarfile.open(tar_path) as tf:
                for m in tf.getmembers():
                    if member_match in m.name:
                        batch = pickle.load(tf.extractfile(m),
                                            encoding='bytes')
                        data = batch[b'data'].reshape(-1, 3, 32, 32)
                        labels = batch[label_key]
                        for img, lab in zip(data, labels):
                            yield (img.astype('float32') / 127.5 - 1.0), \
                                int(lab)
        reader.is_synthetic = False
        return reader
    _fallback('cifar', tar_path)
    return _synthetic((3, 32, 32), 10, n_synth, seed)


def cifar10_train(data_dir=None):
    d = data_dir or os.path.join(DATA_HOME, 'cifar')
    return _cifar_reader(os.path.join(d, 'cifar-10-python.tar.gz'),
                         'data_batch', b'labels', 1024, 2)


def cifar10_test(data_dir=None):
    d = data_dir or os.path.join(DATA_HOME, 'cifar')
    return _cifar_reader(os.path.join(d, 'cifar-10-python.tar.gz'),
                         'test_batch', b'labels', 256, 3)


# ---------------------------------------------------------------------------
# ImageNet-style folder (class subdirectories of .npy images)
# ---------------------------------------------------------------------------


def image_folder(root, shape=(3, 224, 224), n_synth=256, seed=4):
    """root/<class_name>/*.npy — .npy files hold CHW float32 images (decode
    jpegs to .npy in preprocessing; raw-jpeg decode needs an image lib this
    environment doesn't guarantee)."""
    if os.path.isdir(root):
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        idx = {c: i for i, c in enumerate(classes)}
        files = [(os.path.join(root, c, f), idx[c])
                 for c in classes
                 for f in sorted(os.listdir(os.path.join(root, c)))
                 if f.endswith('.npy')]
        if files:
            def reader():
                for path, lab in files:
                    yield np.load(path).astype('float32'), lab
            reader.is_synthetic = False
            return reader
    _fallback('image_folder', root)
    return _synthetic(shape, 1000, n_synth, seed)


# ---------------------------------------------------------------------------
# synthetic (bench configs)
# ---------------------------------------------------------------------------


def synthetic(shape=(3, 224, 224), num_classes=1000, num_samples=1024,
              seed=0):
    return _synthetic(shape, num_classes, num_samples, seed)
