"""TrainerFactory + fetch monitoring (ref: python/paddle/fluid/
trainer_factory.py)."""
import threading

import numpy as np

from .trainer_desc import (MultiTrainer, DistMultiTrainer, PipelineTrainer)
from .device_worker import (Hogwild, DownpourSGD, DownpourSGDOPT, Section)

__all__ = ['TrainerFactory', 'FetchHandler', 'FetchHandlerMonitor']


class TrainerFactory:
    """ref trainer_factory.py:TrainerFactory — build (trainer, worker) from
    a program's _fleet_opt dict; defaults to MultiTrainer + Hogwild."""

    def _create_trainer(self, opt_info=None):
        if not opt_info:
            trainer = MultiTrainer()
            device_worker = Hogwild()
        else:
            trainer_name = opt_info.get('trainer', 'MultiTrainer')
            worker_name = opt_info.get('device_worker', 'Hogwild')
            trainer = {'MultiTrainer': MultiTrainer,
                       'DistMultiTrainer': DistMultiTrainer,
                       'PipelineTrainer': PipelineTrainer}[trainer_name]()
            device_worker = {'Hogwild': Hogwild,
                             'DownpourSGD': DownpourSGD,
                             'DownpourSGDOPT': DownpourSGDOPT,
                             'Section': Section}[worker_name]()
            if 'fleet_desc' in opt_info:
                device_worker._set_fleet_desc(opt_info['fleet_desc'])
        trainer._set_device_worker(device_worker)
        return trainer


class FetchHandler:
    """ref trainer_factory.py:FetchHandler — subclass and override
    `handler(fetch_dict)`; the monitor calls it every `period_secs`."""

    def __init__(self, var_dict=None, period_secs=60):
        if var_dict is None:
            raise ValueError('var_dict cannot be None')
        self.var_dict = var_dict
        self.period_secs = period_secs

    def handler(self, res_dict):
        for key in res_dict:
            if isinstance(res_dict[key], np.ndarray):
                print(f'{key}[0]: {res_dict[key].ravel()[:1]}')  # lint: allow-print (default debug FetchHandler, fluid parity)

    @staticmethod
    def help():
        # lint: allow-print (interactive help())
        print("""class FetchHandlerExample(FetchHandler):
    def handler(self, res_dict):
        print(res_dict["var_name"])""")


class FetchHandlerMonitor:
    """ref trainer_factory.py:FetchHandlerMonitor — background thread that
    reads the handler's vars from a scope on a period."""

    def __init__(self, scope, handler):
        self.scope = scope
        self.handler = handler
        self._stop = threading.Event()
        self.fetch_thread = threading.Thread(
            target=self._loop, daemon=True)

    def _loop(self):
        while not self._stop.wait(self.handler.period_secs):
            res = {}
            for key, var in self.handler.var_dict.items():
                val = self.scope.find(getattr(var, 'name', var))
                res[key] = None if val is None else np.asarray(val)
            self.handler.handler(res)

    def start(self):
        self._stop.clear()
        if not self.fetch_thread.is_alive():
            self.fetch_thread = threading.Thread(target=self._loop,
                                                 daemon=True)
            self.fetch_thread.start()

    def stop(self):
        self._stop.set()
