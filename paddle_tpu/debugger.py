"""Program debugger: graphviz drawing + pretty printer.

Parity with reference python/paddle/fluid/debugger.py — draw_block_graphviz
(:229) emits a .dot file of the op/var graph; pprint_program_codes (:112)
renders the program as readable pseudo-code. No graphviz binary required:
the .dot text is self-contained (render with `dot -Tpng` or any viewer).
"""
from __future__ import annotations

from .framework import BACKWARD_OP_TYPE, Parameter, Program

__all__ = ['draw_block_graphviz', 'pprint_program_codes', 'pprint_block_codes']


def _esc(s):
    return str(s).replace('"', '\\"')


def draw_block_graphviz(block, highlights=None, path='./temp.dot'):
    """Write a graphviz dot file for `block` (ref debugger.py:229): ellipse
    nodes for vars (bold for Parameters), box nodes for ops, edges for
    dataflow. `highlights` is an iterable of var names drawn filled red."""
    highlights = set(highlights or ())
    lines = ['digraph G {', '  rankdir=TB;']
    var_ids, op_ids = {}, {}
    for i, (name, var) in enumerate(sorted(block.vars.items())):
        var_ids[name] = f'var_{i}'
        style = 'style=filled, fillcolor=red,' if name in highlights else (
            'style=bold,' if isinstance(var, Parameter) else '')
        shape = getattr(var, 'shape', None)
        lines.append(
            f'  var_{i} [shape=ellipse, {style} '
            f'label="{_esc(name)}\\n{_esc(shape)}"];')
    for j, op in enumerate(block.ops):
        op_ids[j] = f'op_{j}'
        color = 'fillcolor=lightblue, style=filled' \
            if op.type != BACKWARD_OP_TYPE else \
            'fillcolor=orange, style=filled'
        lines.append(f'  op_{j} [shape=box, {color}, '
                     f'label="{_esc(op.type)}"];')
        for n in op.input_names():
            if n in var_ids:
                lines.append(f'  {var_ids[n]} -> op_{j};')
        for n in op.output_names():
            if n in var_ids:
                lines.append(f'  op_{j} -> {var_ids[n]};')
    lines.append('}')
    text = '\n'.join(lines)
    with open(path, 'w') as f:
        f.write(text)
    return text


def pprint_block_codes(block, show_backward=True):
    """Readable pseudo-code for one block (ref debugger.py:112)."""
    out = [f"# block {block.idx} (parent {block.parent_idx})"]
    for name, var in sorted(block.vars.items()):
        kind = 'param' if isinstance(var, Parameter) else (
            'data' if var.is_data else 'var')
        out.append(f"{kind} {name}: {var.dtype}{list(var.shape or [])}"
                   f"{' persistable' if var.persistable else ''}")
    for op in block.ops:
        if not show_backward and op.type == BACKWARD_OP_TYPE:
            continue
        outs = ', '.join(op.output_names()) or '_'
        ins = ', '.join(op.input_names())
        from .ops.registry import NON_KERNEL_ATTRS
        attrs = {k: v for k, v in op.attrs.items()
                 if k not in NON_KERNEL_ATTRS}
        out.append(f"{outs} = {op.type}({ins})"
                   f"{'  # ' + repr(attrs) if attrs else ''}")
    return '\n'.join(out)


def pprint_program_codes(program, show_backward=True):
    assert isinstance(program, Program)
    text = '\n\n'.join(pprint_block_codes(b, show_backward)
                       for b in program.blocks)
    print(text)
    return text
