"""Program debugger: graphviz drawing + pretty printer.

Parity with reference python/paddle/fluid/debugger.py — draw_block_graphviz
(:229) emits a .dot file of the op/var graph; pprint_program_codes (:112)
renders the program as readable pseudo-code. No graphviz binary required:
the .dot text is self-contained (render with `dot -Tpng` or any viewer).
"""
from __future__ import annotations

from .framework import BACKWARD_OP_TYPE, Parameter, Program, Variable

__all__ = ['draw_block_graphviz', 'pprint_program_codes',
           'pprint_block_codes', 'repr_var', 'repr_op', 'repr_attr',
           'repr_tensor', 'repr_lodtensor', 'repr_selected_rows',
           'repr_tensor_array', 'repr_data_type',
           'prepare_fast_nan_inf_debug', 'run_fast_nan_inf_debug']


# ---------------------------------------------------------------------------
# repr helpers (ref debugger.py:53-226) — over the op-list IR instead of
# framework_pb2 descs
# ---------------------------------------------------------------------------

def repr_data_type(dtype):
    """ref debugger.py:53 — dtype → printable name (ours are strings)."""
    return str(dtype)


def repr_tensor(var):
    """ref debugger.py:57."""
    return f'tensor(type={var.dtype}, shape={list(var.shape or [])})'


def repr_lodtensor(var):
    """ref debugger.py:65 — ragged vars carry lod_level in the IR."""
    if not getattr(var, 'lod_level', 0):
        return None
    return (f'LoDTensor(lod_level={var.lod_level}, type={var.dtype}, '
            f'shape={list(var.shape or [])})')


def repr_selected_rows(var):
    """ref debugger.py:77 — sparse rows lower to dense scatter on TPU; the
    printable form is kept for parity."""
    return f'SelectedRows(type={var.dtype}, shape={list(var.shape or [])})'


def repr_tensor_array(var):
    """ref debugger.py:87."""
    return (f'TensorArray(type={var.dtype}, '
            f'shape={list(var.shape or [])})')


def repr_var(var):
    """ref debugger.py:105 — best printable form for a Variable."""
    kind = 'param' if isinstance(var, Parameter) else (
        'data' if var.is_data else 'var')
    body = repr_lodtensor(var) or repr_tensor(var)
    return f'{kind} {var.name}: {body}'


def repr_attr(name, value):
    """ref debugger.py:161 — attr → printable (name, value) text."""
    return f'{name}={value!r}'


def repr_op(op):
    """ref debugger.py:193."""
    from .ops.registry import NON_KERNEL_ATTRS
    attrs = ', '.join(repr_attr(k, v) for k, v in sorted(op.attrs.items())
                      if k not in NON_KERNEL_ATTRS)
    outs = ', '.join(op.output_names()) or '_'
    ins = ', '.join(op.input_names())
    return f'{outs} = {op.type}({ins}{", " + attrs if attrs else ""})'


def _esc(s):
    return str(s).replace('"', '\\"')


def draw_block_graphviz(block, highlights=None, path='./temp.dot'):
    """Write a graphviz dot file for `block` (ref debugger.py:229): ellipse
    nodes for vars (bold for Parameters), box nodes for ops, edges for
    dataflow. `highlights` is an iterable of var names drawn filled red."""
    highlights = set(highlights or ())
    lines = ['digraph G {', '  rankdir=TB;']
    var_ids, op_ids = {}, {}
    for i, (name, var) in enumerate(sorted(block.vars.items())):
        var_ids[name] = f'var_{i}'
        style = 'style=filled, fillcolor=red,' if name in highlights else (
            'style=bold,' if isinstance(var, Parameter) else '')
        shape = getattr(var, 'shape', None)
        lines.append(
            f'  var_{i} [shape=ellipse, {style} '
            f'label="{_esc(name)}\\n{_esc(shape)}"];')
    for j, op in enumerate(block.ops):
        op_ids[j] = f'op_{j}'
        color = 'fillcolor=lightblue, style=filled' \
            if op.type != BACKWARD_OP_TYPE else \
            'fillcolor=orange, style=filled'
        lines.append(f'  op_{j} [shape=box, {color}, '
                     f'label="{_esc(op.type)}"];')
        for n in op.input_names():
            if n in var_ids:
                lines.append(f'  {var_ids[n]} -> op_{j};')
        for n in op.output_names():
            if n in var_ids:
                lines.append(f'  op_{j} -> {var_ids[n]};')
    lines.append('}')
    text = '\n'.join(lines)
    with open(path, 'w') as f:
        f.write(text)
    return text


def pprint_block_codes(block, show_backward=True):
    """Readable pseudo-code for one block (ref debugger.py:112)."""
    out = [f"# block {block.idx} (parent {block.parent_idx})"]
    for name, var in sorted(block.vars.items()):
        kind = 'param' if isinstance(var, Parameter) else (
            'data' if var.is_data else 'var')
        out.append(f"{kind} {name}: {var.dtype}{list(var.shape or [])}"
                   f"{' persistable' if var.persistable else ''}")
    for op in block.ops:
        if not show_backward and op.type == BACKWARD_OP_TYPE:
            continue
        outs = ', '.join(op.output_names()) or '_'
        ins = ', '.join(op.input_names())
        from .ops.registry import NON_KERNEL_ATTRS
        attrs = {k: v for k, v in op.attrs.items()
                 if k not in NON_KERNEL_ATTRS}
        out.append(f"{outs} = {op.type}({ins})"
                   f"{'  # ' + repr(attrs) if attrs else ''}")
    return '\n'.join(out)


def pprint_program_codes(program, show_backward=True):
    assert isinstance(program, Program)
    text = '\n\n'.join(pprint_block_codes(b, show_backward)
                       for b in program.blocks)
    print(text)  # lint: allow-print (pprint API contract is console output)
    return text


# ---------------------------------------------------------------------------
# fast NaN/Inf localisation (ref debugger.py:285,330)
# ---------------------------------------------------------------------------

def prepare_fast_nan_inf_debug(_program):
    """ref debugger.py:285 — mark the program for NaN/Inf localisation.

    The reference appends isfinite ops per var; in the XLA lowering we
    instead record every op-output var name so run_fast_nan_inf_debug can
    fetch them all from ONE jitted run and binary-search on host."""
    names = []
    for op in _program.global_block().ops:
        for n in op.output_names():
            if not n.endswith('@LEN'):
                names.append(n)
    _program._nan_inf_watch = names
    return _program


def run_fast_nan_inf_debug(executor, program=None, feed=None,
                           fetch_list=None, scope=None, return_numpy=True,
                           use_program_cache=False):
    """ref debugger.py:330 — run once, report the FIRST op whose output
    contains NaN/Inf (raises RuntimeError naming op and var), else return
    the normal fetches."""
    import numpy as np
    if program is None:
        from .framework import default_main_program
        program = default_main_program()
    watch = getattr(program, '_nan_inf_watch', None)
    if watch is None:
        prepare_fast_nan_inf_debug(program)
        watch = program._nan_inf_watch
    fetch_names = [getattr(f, 'name', f) for f in (fetch_list or [])]
    vals = executor.run(program, feed=feed,
                        fetch_list=list(watch) + fetch_names,
                        scope=scope, return_numpy=return_numpy)
    producer = {}
    for op in program.global_block().ops:
        for n in op.output_names():
            producer.setdefault(n, op)
    for name, val in zip(watch, vals):
        arr = np.asarray(val)
        if arr.dtype.kind in 'fc' and not np.isfinite(arr).all():
            op = producer[name]
            raise RuntimeError(
                f'NaN/Inf first appears in var {name!r} produced by '
                f'{repr_op(op)}')
    return vals[len(watch):]
