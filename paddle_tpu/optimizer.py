"""Optimizers (ref: python/paddle/fluid/optimizer.py, 28 classes).

Static mode: `minimize(loss)` appends the backward marker, regularization /
clip ops, then one registered update op per parameter — all of which lower
into the SAME jitted step as the forward pass (no per-param kernel launches;
XLA fuses the full update).
Dygraph mode: a fused jitted pytree update over all parameters at once.
"""
from __future__ import annotations

from collections import defaultdict

import jax
import jax.numpy as jnp

from .backward import append_backward
from .clip import append_gradient_clip_ops
from .core import unique_name
from .framework import (BACKWARD_OP_TYPE, Variable, default_main_program,
                        in_dygraph_mode)
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .layers.common import apply_op_layer
from .regularizer import append_regularization_ops


class Optimizer:
    _op_type = None           # registered update-op name
    _slot_names = ()          # accumulator slots, in op-arg order
    _has_lr_input = True

    def __init__(self, learning_rate=0.001, parameter_list=None,
                 regularization=None, grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameter_list) if parameter_list else None
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._lr_var = None
        self._dy_slots = defaultdict(dict)   # param id → slot dict (dygraph)
        self._dy_step_fn = None
        self._global_step = 0

    # -- hyperparameters each subclass passes to its update op --
    def _hypers(self):
        return {}

    def _hypers_for(self, param):
        """Per-PARAMETER hypers: the per-layer treatment hook (LARS/Lamb
        exclude biases & norm params from weight decay). Default: the
        shared hypers."""
        return self._hypers()

    def _slot_init(self, param_shape, dtype):
        """slot name → (shape, fill value); default zeros_like(param)."""
        return {s: (param_shape, 0.0) for s in self._slot_names}

    # ==================================================================
    # static-graph path
    # ==================================================================
    def get_lr_var(self):
        if isinstance(self._learning_rate, Variable):
            return self._learning_rate
        if self._lr_var is None:
            from .layers.tensor import create_global_var
            self._lr_var = create_global_var(
                [1], float(self._learning_rate), 'float32', persistable=True,
                name=unique_name.generate('learning_rate'))
            self._lr_var.belong_to_optimizer = True
        return self._lr_var

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list or self._parameter_names(),
                               no_grad_set, callbacks)

    def _parameter_names(self):
        if self._parameter_list is None:
            return None
        return [p if isinstance(p, str) else p.name
                for p in self._parameter_list]

    def apply_gradients(self, params_grads):
        # rows-only embedding gradients (docs/SPARSE.md) skip
        # regularization and clipping — both are dense whole-tensor
        # transforms; the reference PS path applied neither to
        # SelectedRows gradients
        sparse_pg = [(p, g) for p, g in params_grads
                     if getattr(g, 'is_sparse_rows', False)]
        dense_pg = [(p, g) for p, g in params_grads
                    if not getattr(g, 'is_sparse_rows', False)]
        dense_pg = append_regularization_ops(dense_pg, self.regularization)
        if self._grad_clip is not None:
            dense_pg = self._grad_clip.process(dense_pg)
        else:
            dense_pg = append_gradient_clip_ops(dense_pg)
        lr = self.get_lr_var()
        for p, g in dense_pg:
            self._append_optimize_op(p, g, lr)
        for p, g in sparse_pg:
            self._append_sparse_optimize_op(p, g, lr)
        return []

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        self.apply_gradients(params_grads)
        return None, params_grads

    # -- accumulators --
    def _make_slot_var(self, param, slot, shape, fill):
        helper = LayerHelper('optimizer')
        name = unique_name.generate(f"{param.name}_{slot}")
        block = helper.main_program.global_block()
        v = block.create_var(name=name, shape=list(shape), dtype='float32',
                             persistable=True, stop_gradient=True)
        sb = helper.startup_program.global_block()
        sv = sb.create_var(name=name, shape=list(shape), dtype='float32',
                           persistable=True, stop_gradient=True)
        # explicit tag: io.is_belong_to_optimizer keys on this, not on name
        # patterns (a user var containing '@' must not be misclassified)
        v.belong_to_optimizer = True
        sv.belong_to_optimizer = True
        ConstantInitializer(fill)(sv, sb)
        return v

    def _append_optimize_op(self, param, grad, lr):
        slots = self._slot_init(list(param.shape), param.dtype)
        slot_vars = [self._make_slot_var(param, s, shp, fill)
                     for s, (shp, fill) in slots.items()]
        opdef_inputs = {'param': param.name, 'grad': grad.name}
        for s, v in zip(slots, slot_vars):
            opdef_inputs[s] = v.name
        if self._has_lr_input:
            opdef_inputs['lr'] = lr.name
        outputs = {'ParamOut': param.name}
        from .ops.registry import get_op
        out_slots = get_op(self._op_type).output_slots
        for oslot, v in zip(out_slots[1:], slot_vars):
            outputs[oslot] = v.name
        helper = LayerHelper('optimizer')
        # current (not global) block: GradientMergeOptimizer nests the
        # update ops inside a cond sub-block
        helper.main_program.current_block().append_op(
            type=self._op_type, inputs=opdef_inputs, outputs=outputs,
            attrs=self._hypers_for(param))

    def _sparse_op_type(self):
        """The rows-only counterpart of this optimizer's update op, or a
        ValueError naming the supported set (docs/SPARSE.md)."""
        from .ops.sparse_ops import SPARSE_UPDATE_OPS
        st = SPARSE_UPDATE_OPS.get(self._op_type)
        if st is None:
            raise ValueError(
                f"optimizer op {self._op_type!r} has no sparse (rows-only) "
                f"update; tables trained with lookup_table(is_sparse=True) "
                f"need one of {sorted(SPARSE_UPDATE_OPS)} — or set "
                f"PADDLE_TPU_SPARSE_GRAD=0 for the dense legacy path")
        return st

    def _append_sparse_optimize_op(self, param, grad, lr):
        """Emit ``sparse_<op>`` consuming the marker's padded-COO grad
        pair (``grad`` is the @GRAD@VALS var; its ``sparse_rows_var``
        attribute names the companion @GRAD@ROWS var)."""
        sparse_type = self._sparse_op_type()
        slots = self._slot_init(list(param.shape), param.dtype)
        slot_vars = [self._make_slot_var(param, s, shp, fill)
                     for s, (shp, fill) in slots.items()]
        inputs = {'param': param.name,
                  'rows': grad.sparse_rows_var.name,
                  'vals': grad.name}
        for s, v in zip(slots, slot_vars):
            inputs[s] = v.name
        if self._has_lr_input:
            inputs['lr'] = lr.name
        from .ops.registry import get_op
        out_slots = get_op(sparse_type).output_slots
        outputs = {'ParamOut': param.name}
        for oslot, v in zip(out_slots[1:], slot_vars):
            outputs[oslot] = v.name
        helper = LayerHelper('optimizer')
        helper.main_program.current_block().append_op(
            type=sparse_type, inputs=inputs, outputs=outputs,
            attrs=self._hypers_for(param))

    # ==================================================================
    # dygraph path — fused jitted pytree update
    # ==================================================================
    def _current_lr(self):
        lr = self._learning_rate
        if callable(lr) and not isinstance(lr, Variable):
            return float(lr())
        if hasattr(lr, 'step'):  # LearningRateDecay-like
            return float(lr())
        return float(lr)

    def _dygraph_minimize(self, loss, parameter_list=None):
        params = parameter_list or self._parameter_list
        if params is None:
            raise ValueError(
                "dygraph optimizers need parameter_list "
                "(ref behavior: Optimizer(..., parameter_list=model.parameters()))")
        params = [p for p in params
                  if getattr(p, 'trainable', True) and p.grad is not None]
        if not params:
            return None, []
        pvals = {p.name: p.value for p in params}
        gvals = {p.name: p.grad for p in params}
        for p in params:
            if p.name not in self._dy_slots:
                self._dy_slots[p.name] = {
                    s: jnp.full(shp, fill, jnp.float32)
                    for s, (shp, fill) in
                    self._slot_init(list(p.shape), p.dtype).items()}
        svals = {p.name: self._dy_slots[p.name] for p in params}
        regs = {p.name: getattr(p, 'regularizer', None) for p in params}

        from .ops.sparse_ops import SparseRowsGrad
        if any(isinstance(g, SparseRowsGrad) for g in gvals.values()):
            self._sparse_op_type()   # raises early for unsupported types

        if self._dy_step_fn is None:
            from .ops.registry import get_op
            fn = get_op(self._op_type).fn
            hypers = {p.name: self._hypers_for(p) for p in params}
            has_lr = self._has_lr_input
            clip = self._grad_clip
            base_reg = self.regularization
            opt = self

            def step(pvals, gvals, svals, lr):
                # rows-only grads (docs/SPARSE.md) skip regularization and
                # clip — dense whole-tensor transforms — and scatter-apply
                # through the sparse_* update kernels; the isinstance
                # branches are static per jit signature, so a mixed
                # dense/sparse parameter set compiles one fused step
                for n in gvals:
                    if isinstance(gvals[n], SparseRowsGrad):
                        continue
                    reg = regs.get(n) or base_reg
                    if reg is not None:
                        gvals[n] = reg.apply(pvals[n], gvals[n])
                if clip is not None:
                    dense = {n: g for n, g in gvals.items()
                             if not isinstance(g, SparseRowsGrad)}
                    gvals = {**gvals, **clip.apply_tree(dense)}
                new_p, new_s = {}, {}
                for n, p in pvals.items():
                    slots = svals[n]
                    g = gvals[n]
                    if isinstance(g, SparseRowsGrad):
                        sfn = get_op(opt._sparse_op_type()).fn
                        args = [p, g.rows, g.vals] \
                            + [slots[s] for s in self._slot_names]
                        if has_lr:
                            args.append(lr)
                        res = sfn(*args, **hypers.get(n, self._hypers()))
                    else:
                        args = [p, g] + [slots[s] for s in self._slot_names]
                        if has_lr:
                            args.append(lr)
                        res = fn(*args, **hypers.get(n, self._hypers()))
                    res = res if isinstance(res, tuple) else (res,)
                    # pin param/slot dtypes: fp32 hypers meeting bf16 params
                    # would promote the update, and a donated step whose
                    # outputs change dtype recompiles every call
                    new_p[n] = res[0].astype(p.dtype)
                    new_s[n] = {s: r.astype(slots[s].dtype)
                                for s, r in zip(self._slot_names, res[1:])}
                return new_p, new_s

            from .core.compile_cache import setup_persistent_cache
            setup_persistent_cache()
            self._dy_step_fn = jax.jit(step, donate_argnums=(0, 2))

        new_p, new_s = self._dy_step_fn(pvals, gvals, svals,
                                        jnp.float32(self._current_lr()))
        for p in params:
            p.value = new_p[p.name]
            self._dy_slots[p.name] = new_s[p.name]
        self._global_step += 1
        if hasattr(self._learning_rate, 'step'):
            self._learning_rate.step()
        return None, [(p, p.grad) for p in params]

    def clear_gradients(self):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_gradient()

    def state_dict(self):
        return {'slots': dict(self._dy_slots), 'global_step': self._global_step}

    def set_dict(self, state):
        self._dy_slots.update(state.get('slots', {}))
        self._global_step = state.get('global_step', 0)

    set_state_dict = set_dict

    @property
    def current_step_lr(self):
        return self._current_lr()


class SGDOptimizer(Optimizer):
    _op_type = 'sgd'
    _slot_names = ()


class MomentumOptimizer(Optimizer):
    _op_type = 'momentum'
    _slot_names = ('velocity',)

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _hypers(self):
        return {'mu': self._momentum, 'use_nesterov': self._use_nesterov}


class LarsMomentumOptimizer(Optimizer):
    """LARS (You et al., the ResNet large-batch recipe of arXiv
    1909.09756 §2): per-LAYER trust ratio — each parameter's update is
    scaled by ‖w‖/(‖∇w‖ + wd·‖w‖ + ε), so early layers with small
    gradients and late layers with large ones both train stably at 32k
    batch. `exclude_from_weight_decay_fn` gives it the same per-layer
    treatment Lamb has: parameters it matches (biases, BN scale/shift —
    the standard recipe) take lars_weight_decay=0 in THEIR update op
    (static + dygraph paths; per-param attrs, so the fuse pass groups
    excluded params separately and numerics are preserved)."""

    _op_type = 'lars_momentum'
    _slot_names = ('velocity',)

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, epsilon=0.0,
                 exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _hypers(self):
        return {'mu': self._momentum, 'lars_coeff': self._lars_coeff,
                'lars_weight_decay': self._lars_weight_decay,
                'epsilon': self._epsilon}

    def _hypers_for(self, param):
        h = self._hypers()
        if self._exclude_fn is not None and self._exclude_fn(param):
            h['lars_weight_decay'] = 0.0
        return h


class AdamOptimizer(Optimizer):
    _op_type = 'adam'
    _slot_names = ('moment1', 'moment2', 'beta1_pow', 'beta2_pow')

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _hypers(self):
        return {'beta1': self._beta1, 'beta2': self._beta2,
                'epsilon': self._epsilon}

    def _slot_init(self, param_shape, dtype):
        return {'moment1': (param_shape, 0.0), 'moment2': (param_shape, 0.0),
                'beta1_pow': ([1], self._beta1), 'beta2_pow': ([1], self._beta2)}


class AdamaxOptimizer(Optimizer):
    _op_type = 'adamax'
    _slot_names = ('moment', 'inf_norm', 'beta1_pow')

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _hypers(self):
        return {'beta1': self._beta1, 'beta2': self._beta2,
                'epsilon': self._epsilon}

    def _slot_init(self, param_shape, dtype):
        return {'moment': (param_shape, 0.0), 'inf_norm': (param_shape, 0.0),
                'beta1_pow': ([1], self._beta1)}


class AdagradOptimizer(Optimizer):
    _op_type = 'adagrad'
    _slot_names = ('moment',)

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0,
                 **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _hypers(self):
        return {'epsilon': self._epsilon}

    def _slot_init(self, param_shape, dtype):
        return {'moment': (param_shape, self._init_acc)}


class DecayedAdagradOptimizer(Optimizer):
    _op_type = 'decayed_adagrad'
    _slot_names = ('moment',)

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _hypers(self):
        return {'decay': self._decay, 'epsilon': self._epsilon}


class RMSPropOptimizer(Optimizer):
    _op_type = 'rmsprop'
    _slot_names = ('mean_square', 'moment', 'mean_grad')

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _hypers(self):
        return {'rho': self._rho, 'epsilon': self._epsilon,
                'momentum': self._momentum, 'centered': self._centered}


class AdadeltaOptimizer(Optimizer):
    _op_type = 'adadelta'
    _slot_names = ('avg_squared_grad', 'avg_squared_update')
    _has_lr_input = False

    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon

    def _hypers(self):
        return {'rho': self._rho, 'epsilon': self._epsilon}


class FtrlOptimizer(Optimizer):
    _op_type = 'ftrl'
    _slot_names = ('squared_accum', 'linear_accum')

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _hypers(self):
        return {'l1': self._l1, 'l2': self._l2, 'lr_power': self._lr_power}


class LambOptimizer(Optimizer):
    _op_type = 'lamb'
    _slot_names = ('moment1', 'moment2', 'beta1_pow', 'beta2_pow')

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, exclude_from_weight_decay_fn=None,
                 **kw):
        super().__init__(learning_rate, **kw)
        self._wd, self._beta1, self._beta2, self._epsilon = \
            lamb_weight_decay, beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _hypers(self):
        return {'weight_decay': self._wd, 'beta1': self._beta1,
                'beta2': self._beta2, 'epsilon': self._epsilon}

    def _hypers_for(self, param):
        # ref: optimizer.py:LambOptimizer — matched params take
        # weight_decay=0 in their own update op (the fn was previously
        # accepted-but-ignored here; now live on both paths)
        h = self._hypers()
        if self._exclude_fn is not None and self._exclude_fn(param):
            h['weight_decay'] = 0.0
        return h

    def _slot_init(self, param_shape, dtype):
        return {'moment1': (param_shape, 0.0), 'moment2': (param_shape, 0.0),
                'beta1_pow': ([1], self._beta1), 'beta2_pow': ([1], self._beta2)}


class DpsgdOptimizer(Optimizer):
    _op_type = 'dpsgd'
    _slot_names = ()

    def __init__(self, learning_rate, clip=10.0, batch_size=16.0, sigma=1.0,
                 **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _hypers(self):
        return {'clip': self._clip, 'batch_size': self._batch_size,
                'sigma': self._sigma}

    def _append_optimize_op(self, param, grad, lr):
        helper = LayerHelper('optimizer')
        helper.main_program.global_block().append_op(
            type='dpsgd',
            inputs={'param': param.name, 'grad': grad.name, 'lr': lr.name},
            outputs={'ParamOut': param.name}, attrs=self._hypers())


class RecomputeOptimizer(Optimizer):
    """ref: optimizer.py:RecomputeOptimizer → jax.checkpoint over segments.
    The checkpoint list is recorded on the backward marker; lowering remats
    the forward between checkpoints (memory ↔ FLOPs trade, SURVEY §6).

    For AUTOMATIC checkpoint selection set ``PADDLE_TPU_HBM_BUDGET_MB``
    instead: the ``auto_remat`` IR pass picks the segments from the
    memory plan (docs/ANALYSIS.md) — same marker mechanism, bitwise-
    identical numerics vs a manual list of the same names."""

    def __init__(self, optimizer):
        self._inner = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        """Strict: entries must be Variables or names, and names must be
        unique — a duplicate or mistyped checkpoint used to silently
        no-op into the backward marker (the lowering splits at producer
        ops, so an unmatched name changed nothing without a word)."""
        if checkpoints is None:
            self._checkpoints = None
            return
        if not isinstance(checkpoints, (list, tuple)):
            raise ValueError(
                f'RecomputeOptimizer checkpoints must be a list/tuple of '
                f'Variables or var names, got '
                f'{type(checkpoints).__name__}')
        names = []
        for c in checkpoints:
            n = c.name if hasattr(c, 'name') else c
            if not isinstance(n, str):
                raise ValueError(
                    f'RecomputeOptimizer checkpoint entries must be '
                    f'Variables or var names, got {type(c).__name__}: '
                    f'{c!r}')
            names.append(n)
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            raise ValueError(
                f'RecomputeOptimizer checkpoints contain duplicate '
                f'name(s): {dupes}')
        self._checkpoints = list(checkpoints)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._checkpoints:
            names = [c.name if hasattr(c, 'name') else c
                     for c in self._checkpoints]
            program = loss.block.program
            unknown = sorted(
                n for n in names
                if not any(n in b.vars for b in program.blocks))
            if unknown:
                raise ValueError(
                    f'RecomputeOptimizer checkpoints name var(s) the '
                    f'program does not declare: {unknown} (typo, or a '
                    f'var from a different Program?)')
        params_grads = append_backward(
            loss, parameter_list or self._inner._parameter_names(),
            no_grad_set, checkpoints=self._checkpoints)
        self._inner.apply_gradients(params_grads)
        return None, params_grads


class ModelAverage(Optimizer):
    """ref: optimizer.py:ModelAverage — running average of parameters with
    apply()/restore() context for eval."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self._avgs = {}
        self._n = 0
        self._backup = None

    def accumulate(self, parameters):
        self._n += 1
        for p in parameters:
            a = self._avgs.get(p.name)
            self._avgs[p.name] = p.value if a is None else a + (p.value - a) / self._n

    import contextlib

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield
        return ctx()

    def apply_params(self, parameters):
        self._backup = {p.name: p.value for p in parameters}
        for p in parameters:
            if p.name in self._avgs:
                p.value = self._avgs[p.name]

    def restore_params(self, parameters):
        for p in parameters:
            if self._backup and p.name in self._backup:
                p.value = self._backup[p.name]


class ExponentialMovingAverage:
    """ref: optimizer.py:ExponentialMovingAverage (dygraph + functional)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = {}
        self._step = 0
        self._backup = None

    def update(self, parameters):
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in parameters:
            prev = self._ema.get(p.name, p.value)
            self._ema[p.name] = d * prev + (1 - d) * p.value

    def apply(self, parameters):
        self._backup = {p.name: p.value for p in parameters}
        for p in parameters:
            if p.name in self._ema:
                p.value = self._ema[p.name]

    def restore(self, parameters):
        for p in parameters:
            if self._backup and p.name in self._backup:
                p.value = self._backup[p.name]


class LookaheadOptimizer:
    """ref: optimizer.py:LookaheadOptimizer — slow/fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = {}
        self._step = 0

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self.inner_optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        if in_dygraph_mode():
            self._step += 1
            params = parameter_list or self.inner_optimizer._parameter_list
            if self._step % self.k == 0 and params:
                for p in params:
                    slow = self._slow.get(p.name, p.value)
                    slow = slow + self.alpha * (p.value - slow)
                    self._slow[p.name] = slow
                    p.value = slow
        return result


# short aliases (ref exports both)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
RMSProp = RMSPropOptimizer
Adadelta = AdadeltaOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
Dpsgd = DpsgdOptimizer


class DGCMomentumOptimizer(Optimizer):
    """ref: optimizer.py:DGCMomentumOptimizer — top-k sparsified momentum
    with error feedback (ops/optimizer_ops.py:dgc_momentum). rampup args are
    accepted; sparsity uses the final value of rampup_percent_list."""
    _op_type = 'dgc_momentum'
    _slot_names = ('velocity', 'error')

    def __init__(self, learning_rate, momentum=0.9, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 local_grad_clip_norm=None, num_trainers=None,
                 regularization=None, grad_clip=None, name=None,
                 parameter_list=None):
        super().__init__(learning_rate, parameter_list, regularization,
                         grad_clip, name)
        self._momentum = momentum
        self._sparsity = list(sparsity)[-1] if sparsity else 0.999
        self._use_nesterov = use_nesterov

    def _hypers(self):
        return {'mu': self._momentum, 'sparsity': self._sparsity,
                'use_nesterov': self._use_nesterov}


class GradientMergeOptimizer(Optimizer):
    """ref: optimizer.py:GradientMergeOptimizer — accumulate gradients for
    k_steps runs, apply the inner optimizer on the merged gradient every
    k-th run. Lowered the same way as the reference: the inner update ops
    sit in a conditional block (here → one lax.cond inside the fused step),
    so off-steps cost only the accumulation adds."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            raise RuntimeError("GradientMergeOptimizer is a static-graph "
                               "construct (use dygraph grad accumulation)")
        params_grads = self._inner.backward(loss, startup_program,
                                            parameter_list, no_grad_set)
        self.apply_gradients(params_grads)
        return None, params_grads

    def apply_gradients(self, params_grads):
        from .layers import tensor as T
        from .layers import control_flow as cf
        from .layers.common import apply_op_layer
        from .core import unique_name as un
        if any(getattr(g, 'is_sparse_rows', False) for _, g in params_grads):
            raise RuntimeError(
                'GradientMergeOptimizer cannot accumulate rows-only sparse '
                'embedding gradients (rows differ per step); set '
                'PADDLE_TPU_SPARSE_GRAD=0 or use is_sparse=False under '
                'gradient merge')
        k = self.k_steps
        counter = T.create_global_var([1], -1, 'int64', persistable=True,
                                      name=un.generate('grad_merge_counter'))
        cf.increment(counter, value=1, in_place=True)
        merged = []
        for p, g in params_grads:
            helper = LayerHelper('grad_merge')
            acc = helper.create_global_variable(
                list(p.shape), 'float32', persistable=True,
                name=un.generate(f'{p.name}_grad_merge'))
            sb = helper.startup_program.global_block()
            sv = sb.create_var(name=acc.name, shape=list(p.shape),
                               dtype='float32', persistable=True,
                               stop_gradient=True)
            ConstantInitializer(0.0)(sv, sb)
            helper.append_op(type='elementwise_add',
                             inputs={'x': acc.name, 'y': g.name},
                             outputs={'Out': acc.name}, attrs={})
            merged.append((p, acc))
        mod = apply_op_layer('elementwise_mod',
                             {'x': counter,
                              'y': T.fill_constant([1], 'int64', k)})
        pred = cf.equal(mod, T.fill_constant([1], 'int64', k - 1))

        def apply_block():
            scaled = [(p, apply_op_layer(
                'scale', {'x': acc}, {'scale': 1.0 / k}) if self.avg else acc)
                for p, acc in merged]
            self._inner.apply_gradients(scaled)
            for _, acc in merged:
                helper = LayerHelper('grad_merge')
                helper.append_op(type='scale',
                                 inputs={'x': acc.name},
                                 outputs={'Out': acc.name},
                                 attrs={'scale': 0.0})

        cf.cond(pred, apply_block, None)
        return []


def _stamp_pipeline(program, cut_vars, num_microbatches, schedule,
                    num_stages=None, loss_name=None):
    """Stamp the pipeline plan onto the backward marker. With no explicit
    cut and a stage count, the cut is COMPUTED: ``solve_stage_cuts``
    (analysis/stage.py) balances predicted per-stage FLOPs+bytes from the
    cost model. ``num_microbatches`` 0 is the auto sentinel — the executor
    solves the count against ``PADDLE_TPU_HBM_BUDGET_MB`` at lowering
    time, when feed shapes are known."""
    block = program.global_block()
    marker = next(op for op in reversed(block.ops)
                  if op.type == BACKWARD_OP_TYPE)
    cut_vars = [v.name if hasattr(v, 'name') else v
                for v in (cut_vars or [])]
    if not cut_vars and num_stages:
        from .analysis.stage import solve_stage_cuts
        cut_vars, _report = solve_stage_cuts(
            program, num_stages,
            fetch_names=(loss_name,) if loss_name else ())
    marker._set_attr('pipeline', {
        'cut_vars': cut_vars,
        'num_microbatches': int(num_microbatches),
        'schedule': schedule})


class PipelineOptimizer:
    """ref: optimizer.py:3405 PipelineOptimizer — the reference splits the
    Program at `cut_list` points and streams batches through per-device
    section workers. The TPU lowering (executor.py `_lower`): the Program is
    split at the cut vars into stages; with ``schedule='gpipe'`` (default),
    isomorphic stages stack their parameters over the 'pp' mesh axis and
    run the SPMD GPipe schedule (paddle_tpu.partition.pipeline: lax.scan +
    ppermute over ICI), non-uniform stages fall back to a microbatched
    lax.scan with gradient accumulation — the same GPipe numerics
    (mean-of-microbatch grads) and per-microbatch activation memory.
    ``schedule='1f1b'``/'interleaved' run the backward per microbatch/wave
    inside the scan (executor sched_fwd_grad): bitwise-identical gradients
    at one wave of resident activations instead of all m.

    New vs the reference signature: ``schedule`` (∈ partition.pipeline
    .PP_SCHEDULES; PADDLE_TPU_PP_SCHEDULE overrides), ``num_stages``
    (auto-cut via the cost model when cut_list is omitted), and
    ``num_microbatches='auto'`` (count solved to fit
    PADDLE_TPU_HBM_BUDGET_MB; PADDLE_TPU_PP_MICROBATCHES overrides)."""

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0, num_microbatches=None,
                 schedule=None, num_stages=None):
        self._inner = optimizer
        self.cut_list = cut_list
        if schedule is not None:
            from .partition.pipeline import PP_SCHEDULES
            if schedule not in PP_SCHEDULES:
                raise ValueError(
                    f'PipelineOptimizer: unknown schedule {schedule!r} '
                    f"(supported: {', '.join(PP_SCHEDULES)})")
        self.schedule = schedule
        self.num_stages = int(num_stages) if num_stages else None
        if self.num_stages is not None and self.num_stages < 2:
            raise ValueError(
                f'PipelineOptimizer: num_stages must be >= 2, '
                f'got {num_stages}')
        if num_microbatches == 'auto' or (
                num_microbatches is None
                and (schedule is not None or num_stages is not None)):
            self.num_microbatches = 0      # executor solves vs HBM budget
        else:
            self.num_microbatches = num_microbatches or max(
                len(place_list or []) or 1, 1)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            raise RuntimeError("PipelineOptimizer is a static-graph "
                               "construct (use partition.pipeline for "
                               "the functional path)")
        params_grads = self._inner.backward(loss, startup_program,
                                            parameter_list, no_grad_set)
        _stamp_pipeline(loss.block.program, self.cut_list,
                        self.num_microbatches, self.schedule,
                        num_stages=self.num_stages, loss_name=loss.name)
        self._inner.apply_gradients(params_grads)
        return None, params_grads
