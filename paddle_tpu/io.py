"""Model save/load (ref: python/paddle/fluid/io.py): save_params,
save_persistables, load_params, save/load_inference_model + dygraph
save_dygraph/load_dygraph re-export. Program IR serializes to JSON (the
reference uses protobuf ProgramDesc); params to .npz.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax.numpy as jnp

from .core.dtypes import to_jax_dtype
from .core.scope import global_scope
from .framework import (BACKWARD_OP_TYPE, Block, Operator, Parameter, Program,
                        Variable, default_main_program)
from .dygraph.checkpoint import save_dygraph, load_dygraph

__all__ = ['save_params', 'save_persistables', 'load_params',
           'load_persistables', 'save_inference_model', 'load_inference_model',
           'save_dygraph', 'load_dygraph', 'save_vars', 'load_vars']


def _collect(program, predicate, scope):
    out = {}
    for v in program.list_vars():
        if predicate(v):
            val = scope.find(v.name)
            if val is not None:
                out[v.name] = np.asarray(val)
    return out


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    program = main_program or default_main_program()
    scope = global_scope()
    if vars is not None:
        data = {v.name if isinstance(v, Variable) else v:
                np.asarray(scope.find(v.name if isinstance(v, Variable) else v))
                for v in vars}
    else:
        data = _collect(program, predicate, scope)
    os.makedirs(dirname, exist_ok=True)
    np.savez(os.path.join(dirname, filename or 'params.npz'), **data)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable and not v.is_data,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    program = main_program or default_main_program()
    scope = global_scope()
    path = os.path.join(dirname, filename or 'params.npz')
    data = np.load(path)
    names = set(data.files)
    for v in program.list_vars():
        want = (vars is not None and any(
            (x.name if isinstance(x, Variable) else x) == v.name for x in vars)) \
            or (predicate is not None and predicate(v))
        if want and v.name in names:
            scope.set(v.name, jnp.asarray(data[v.name],
                                          to_jax_dtype(v.dtype)))


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable and not v.is_data,
              filename=filename)


# ---------------------------------------------------------------------------
# Program IR serialization (JSON; ref uses protobuf ProgramDesc)
# ---------------------------------------------------------------------------

def _program_to_dict(program):
    blocks = []
    for b in program.blocks:
        vars_ = []
        for v in b.vars.values():
            vars_.append({
                'name': v.name, 'shape': list(v.shape) if v.shape else None,
                'dtype': v.dtype, 'persistable': v.persistable,
                'is_data': v.is_data, 'stop_gradient': v.stop_gradient,
                'is_parameter': isinstance(v, Parameter),
                'trainable': v.trainable, 'lod_level': v.lod_level})
        ops = []
        for op in b.ops:
            attrs = {}
            skipped = False
            for k, val in op.attrs.items():
                if k == 'initializer' or isinstance(val, np.ndarray):
                    skipped = True
                    continue
                attrs[k] = val
            entry = {'type': op.type, 'inputs': op.inputs,
                     'outputs': op.outputs, 'attrs': attrs}
            if skipped and op.type == '__constant__':
                entry['constant_value'] = np.asarray(
                    op.attrs['value']).tolist()
                entry['constant_dtype'] = str(
                    np.asarray(op.attrs['value']).dtype)
            ops.append(entry)
        blocks.append({'idx': b.idx, 'parent_idx': b.parent_idx,
                       'vars': vars_, 'ops': ops})
    return {'blocks': blocks, 'version': 1}


def _program_from_dict(d):
    p = Program()
    p.blocks = []
    for bd in d['blocks']:
        b = Block(p, bd['idx'], bd['parent_idx'])
        for vd in bd['vars']:
            if vd.pop('is_parameter', False):
                b.create_parameter(vd['name'], vd['shape'], vd['dtype'],
                                   trainable=vd.get('trainable', True))
            else:
                b.create_var(**vd)
        for od in bd['ops']:
            attrs = od['attrs']
            if 'constant_value' in od:
                attrs['value'] = np.asarray(od['constant_value'],
                                            od['constant_dtype'])
            op = Operator(b, od['type'], od['inputs'], od['outputs'], attrs)
            b.ops.append(op)
        p.blocks.append(b)
    p.current_block_idx = 0
    return p


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """ref: io.py:save_inference_model — prunes to the inference slice."""
    program = main_program or default_main_program()
    inference_program = program.clone(for_test=True)
    inference_program = inference_program._prune(target_vars)
    os.makedirs(dirname, exist_ok=True)
    meta = _program_to_dict(inference_program)
    meta['feed_names'] = list(feeded_var_names)
    meta['fetch_names'] = [t.name if isinstance(t, Variable) else t
                           for t in target_vars]
    with open(os.path.join(dirname, model_filename or '__model__.json'),
              'w') as f:
        json.dump(meta, f)
    if not program_only:
        save_persistables(executor, dirname, inference_program,
                          params_filename or 'params.npz')
    return meta['fetch_names']


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname, model_filename or '__model__.json')) as f:
        meta = json.load(f)
    program = _program_from_dict(meta)
    scope = global_scope()
    path = os.path.join(dirname, params_filename or 'params.npz')
    if os.path.exists(path):
        data = np.load(path)
        for v in program.list_vars():
            if v.persistable and v.name in data.files:
                scope.set(v.name, jnp.asarray(data[v.name],
                                              to_jax_dtype(v.dtype)))
    fetch_vars = [program.global_block().var(n) for n in meta['fetch_names']]
    return program, meta['feed_names'], fetch_vars


def _save_jit_model(dirname, layer, params, buffers):
    os.makedirs(dirname, exist_ok=True)
    np.savez(os.path.join(dirname, 'jit_params.npz'),
             **{k: np.asarray(v) for k, v in params.items()})
    np.savez(os.path.join(dirname, 'jit_buffers.npz'),
             **{k: np.asarray(v) for k, v in buffers.items()})


# parity: the reference exposes DataLoader under fluid.io as well
from .reader import DataLoader  # noqa: E402
