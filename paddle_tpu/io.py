"""Model save/load (ref: python/paddle/fluid/io.py): save_params,
save_persistables, load_params, save/load_inference_model + dygraph
save_dygraph/load_dygraph re-export. Program IR serializes to JSON (the
reference uses protobuf ProgramDesc); params to .npz.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax.numpy as jnp

from .core.dtypes import to_jax_dtype
from .core.scope import global_scope
from .framework import (BACKWARD_OP_TYPE, Block, Operator, Parameter, Program,
                        Variable, default_main_program)
from .dygraph.checkpoint import save_dygraph, load_dygraph

__all__ = ['save_params', 'save_persistables', 'load_params',
           'load_persistables', 'save_inference_model', 'load_inference_model',
           'save_dygraph', 'load_dygraph', 'save_vars', 'load_vars',
           'is_parameter', 'is_persistable', 'is_belong_to_optimizer',
           'get_program_parameter', 'get_program_persistable_vars',
           'get_parameter_value', 'get_parameter_value_by_name',
           'prepend_feed_ops', 'append_fetch_ops',
           'save', 'load', 'load_program_state', 'set_program_state']


def _atomic_savez(path, data):
    """np.savez via temp-in-target-dir + fsync + os.replace: a `kill -9`
    mid-save can never leave a torn npz at `path` (docs/RESILIENCE.md).
    Writing through a file object also pins the EXACT filename — np.savez
    given a str would append '.npz', silently desyncing save/load names."""
    import io as _io
    from .resilience.snapshot import atomic_write_bytes
    buf = _io.BytesIO()
    np.savez(buf, **data)
    atomic_write_bytes(path, buf.getvalue())


def _atomic_write_text(path, text):
    """Same torn-write guarantee for the JSON model/manifest artifacts."""
    from .resilience.snapshot import atomic_write_bytes
    atomic_write_bytes(path, text.encode())


def is_parameter(var):
    """ref io.py:67 — var is a trainable Parameter."""
    return isinstance(var, Parameter)


def is_persistable(var):
    """ref io.py:88 — persistable and not a feed/fetch plumbing var."""
    return bool(var.persistable) and not var.is_data


def is_belong_to_optimizer(var):
    """ref io.py:113 — optimizer slot vars (moments, velocities, steps…).

    Keyed on the explicit ``belong_to_optimizer`` tag set at accumulator /
    lr-var creation (optimizer.py `_make_slot_var`), not on name patterns —
    a user var whose name happens to contain '@' or start with
    ``learning_rate`` must not be misclassified.
    """
    return (bool(var.persistable) and not isinstance(var, Parameter)
            and bool(getattr(var, 'belong_to_optimizer', False)))


def get_program_parameter(program):
    """ref io.py:120 — all Parameters of the program."""
    return [v for v in program.list_vars() if is_parameter(v)]


def get_program_persistable_vars(program):
    """ref io.py:142 — all persistable vars of the program."""
    return [v for v in program.list_vars() if is_persistable(v)]


def get_parameter_value(para, executor=None):
    """ref io.py:1365 — fetch a Parameter's current value as numpy."""
    val = global_scope().find(para.name if isinstance(para, Variable) else para)
    if val is None:
        raise ValueError(f'parameter {para} has no value in the scope; '
                         'run the startup program first')
    return np.asarray(val)


def get_parameter_value_by_name(name, executor=None, program=None):
    """ref io.py:1396."""
    program = program or default_main_program()
    var = program.global_block().var(name)
    if not is_parameter(var):
        raise TypeError(f'{name} is not a Parameter')
    return get_parameter_value(var, executor)


def _collect(program, predicate, scope):
    out = {}
    for v in program.list_vars():
        if predicate(v):
            val = scope.find(v.name)
            if val is not None:
                out[v.name] = np.asarray(val)
    return out


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    program = main_program or default_main_program()
    scope = global_scope()
    if vars is not None:
        data = {}
        for v in vars:
            name = v.name if isinstance(v, Variable) else v
            val = scope.find(name)
            if val is None:
                # np.asarray(None) would silently save an object array
                raise ValueError(
                    f"save_vars: variable '{name}' has no value in the "
                    f'scope (run the startup program, or drop it from '
                    f'vars=)')
            data[name] = np.asarray(val)
    else:
        data = _collect(program, predicate, scope)
    os.makedirs(dirname, exist_ok=True)
    _atomic_savez(os.path.join(dirname, filename or 'params.npz'), data)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable and not v.is_data,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    program = main_program or default_main_program()
    scope = global_scope()
    path = os.path.join(dirname, filename or 'params.npz')
    data = np.load(path)
    names = set(data.files)
    if vars is not None:
        # ref io.py load_vars raises when a requested var has no saved
        # entry; silently skipping would leave it stale/uninitialized
        requested = [x.name if isinstance(x, Variable) else x for x in vars]
        missing = sorted(set(requested) - names)
        if missing:
            raise ValueError(
                f'load_vars: requested vars not found in {path}: {missing}')
    for v in program.list_vars():
        want = (vars is not None and any(
            (x.name if isinstance(x, Variable) else x) == v.name for x in vars)) \
            or (predicate is not None and predicate(v))
        if want and v.name in names:
            scope.set(v.name, jnp.asarray(data[v.name],
                                          to_jax_dtype(v.dtype)))


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable and not v.is_data,
              filename=filename)


# ---------------------------------------------------------------------------
# Program IR serialization (JSON; ref uses protobuf ProgramDesc)
# ---------------------------------------------------------------------------

def _program_to_dict(program):
    blocks = []
    for b in program.blocks:
        vars_ = []
        for v in b.vars.values():
            vars_.append({
                'name': v.name, 'shape': list(v.shape) if v.shape else None,
                'dtype': v.dtype, 'persistable': v.persistable,
                'is_data': v.is_data, 'stop_gradient': v.stop_gradient,
                'is_parameter': isinstance(v, Parameter),
                'trainable': v.trainable, 'lod_level': v.lod_level,
                'belong_to_optimizer': bool(
                    getattr(v, 'belong_to_optimizer', False))})
        ops = []
        for op in b.ops:
            attrs = {}
            skipped = False
            for k, val in op.attrs.items():
                if k == 'initializer' or isinstance(val, np.ndarray):
                    skipped = True
                    continue
                attrs[k] = val
            entry = {'type': op.type, 'inputs': op.inputs,
                     'outputs': op.outputs, 'attrs': attrs}
            if skipped and op.type == '__constant__':
                entry['constant_value'] = np.asarray(
                    op.attrs['value']).tolist()
                entry['constant_dtype'] = str(
                    np.asarray(op.attrs['value']).dtype)
            ops.append(entry)
        blocks.append({'idx': b.idx, 'parent_idx': b.parent_idx,
                       'vars': vars_, 'ops': ops})
    return {'blocks': blocks, 'version': 1}


def _program_from_dict(d):
    p = Program()
    p.blocks = []
    for bd in d['blocks']:
        b = Block(p, bd['idx'], bd['parent_idx'])
        for vd in bd['vars']:
            belong = vd.pop('belong_to_optimizer', False)
            if vd.pop('is_parameter', False):
                b.create_parameter(vd['name'], vd['shape'], vd['dtype'],
                                   trainable=vd.get('trainable', True))
            else:
                v = b.create_var(**vd)
                if belong:
                    v.belong_to_optimizer = True
        for od in bd['ops']:
            attrs = od['attrs']
            if 'constant_value' in od:
                attrs['value'] = np.asarray(od['constant_value'],
                                            od['constant_dtype'])
            op = Operator(b, od['type'], od['inputs'], od['outputs'], attrs)
            b.ops.append(op)
        p.blocks.append(b)
    p.current_block_idx = 0
    return p


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """ref: io.py:save_inference_model — prunes to the inference slice."""
    program = main_program or default_main_program()
    inference_program = program.clone(for_test=True)
    inference_program = inference_program._prune(target_vars)
    os.makedirs(dirname, exist_ok=True)
    meta = _program_to_dict(inference_program)
    meta['feed_names'] = list(feeded_var_names)
    meta['fetch_names'] = [t.name if isinstance(t, Variable) else t
                           for t in target_vars]
    _atomic_write_text(
        os.path.join(dirname, model_filename or '__model__.json'),
        json.dumps(meta))
    if not program_only:
        save_persistables(executor, dirname, inference_program,
                          params_filename or 'params.npz')
    return meta['fetch_names']


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname, model_filename or '__model__.json')) as f:
        meta = json.load(f)
    program = _program_from_dict(meta)
    scope = global_scope()
    path = os.path.join(dirname, params_filename or 'params.npz')
    saved = set()
    if os.path.exists(path):
        data = np.load(path)
        saved = set(data.files)
        for v in program.list_vars():
            if v.persistable and v.name in saved:
                scope.set(v.name, jnp.asarray(data[v.name],
                                              to_jax_dtype(v.dtype)))
    # a persistable with neither a saved entry nor a pre-set scope value
    # would flow into the jitted step as garbage — fail here, not at serve
    # time (scope pre-population is the supported program_only workflow)
    missing = sorted(v.name for v in program.list_vars()
                     if v.persistable and v.name not in saved
                     and scope.find(v.name) is None)
    if missing:
        raise RuntimeError(
            f'load_inference_model: persistable vars have no saved value in '
            f'{path} and no value in the current scope: {missing} (saved '
            f'with program_only=True? load/set the parameters first)')
    fetch_vars = [program.global_block().var(n) for n in meta['fetch_names']]
    return program, meta['feed_names'], fetch_vars


def prepend_feed_ops(inference_program, feed_target_names,
                     feed_holder_name='feed'):
    """ref io.py:984 — record the feed interface on the program.

    The reference prepends C++ ``feed`` ops that copy out of a feed-holder
    LoDTensorArray; our Executor binds feeds directly as jit arguments, so
    the interface is metadata: the names are stored on the program and
    validated at run time.
    """
    inference_program._feed_names = list(feed_target_names)
    return inference_program


def append_fetch_ops(inference_program, fetch_target_names,
                     fetch_holder_name='fetch'):
    """ref io.py:1005 — record the fetch interface (see prepend_feed_ops)."""
    inference_program._fetch_names = list(fetch_target_names)
    return inference_program


# ---------------------------------------------------------------------------
# fluid.save / fluid.load single-file checkpoints (ref io.py:1507,1565)
# ---------------------------------------------------------------------------

def save(program, model_path):
    """ref io.py:1507 — writes {path}.pdparams / {path}.pdopt / {path}.pdmodel
    (parameters / optimizer state / program IR)."""
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    scope = global_scope()
    params = {v.name: np.asarray(scope.find(v.name))
              for v in get_program_parameter(program)
              if scope.find(v.name) is not None}
    opt = {v.name: np.asarray(scope.find(v.name))
           for v in program.list_vars()
           if is_persistable(v) and not is_parameter(v)
           and scope.find(v.name) is not None}
    # atomic + exact filenames (np.savez(str) would append '.npz', breaking
    # the documented `{path}.pdparams` artifact layout)
    _atomic_savez(model_path + '.pdparams', params)
    _atomic_savez(model_path + '.pdopt', opt)
    _atomic_write_text(model_path + '.pdmodel',
                       json.dumps(_program_to_dict(program)))


def load(program, model_path, executor=None, var_list=None):
    """ref io.py:1565 — restore state saved by `save` into the scope."""
    state = load_program_state(model_path, var_list)
    set_program_state(program, state)


def load_program_state(model_path, var_list=None):
    """ref io.py:1731 — {name: ndarray} from {path}.pdparams (+ .pdopt)."""
    state = {}
    for ext in ('.pdparams', '.pdopt'):
        p = model_path + ext
        # legacy fallback ONLY when the exact-name artifact is absent (old
        # save() went through np.savez(str) which appended '.npz'); a stale
        # legacy file must never shadow a fresh exact-name checkpoint
        if not os.path.exists(p) and os.path.exists(p + '.npz'):
            p = p + '.npz'
        if os.path.exists(p):
            with np.load(p) as data:
                state.update({k: data[k] for k in data.files})
    if not state:
        raise FileNotFoundError(f'no saved state at {model_path}.pdparams')
    if var_list is not None:
        want = {v.name if isinstance(v, Variable) else v for v in var_list}
        missing = want - set(state)
        if missing:
            raise ValueError(f'vars not found in {model_path}: {sorted(missing)}')
        state = {k: v for k, v in state.items() if k in want}
    return state


def set_program_state(program, state_dict):
    """ref io.py:1861 — write a load_program_state dict into the scope,
    checking shape/dtype against the program's vars."""
    scope = global_scope()
    by_name = {v.name: v for v in program.list_vars() if is_persistable(v)}
    used = 0
    for name, arr in state_dict.items():
        v = by_name.get(name)
        if v is None:
            continue
        if v.shape and -1 not in v.shape \
                and tuple(np.shape(arr)) != tuple(v.shape):
            raise ValueError(
                f'shape mismatch for {name}: program has {tuple(v.shape)}, '
                f'state has {np.shape(arr)}')
        want = np.dtype(to_jax_dtype(v.dtype))
        have = np.asarray(arr).dtype
        if have.kind != want.kind:
            raise ValueError(
                f'dtype mismatch for {name}: program has {want}, '
                f'state has {have}')
        scope.set(name, jnp.asarray(arr, to_jax_dtype(v.dtype)))
        used += 1
    return used


def _save_jit_model(dirname, layer, params, buffers):
    os.makedirs(dirname, exist_ok=True)
    _atomic_savez(os.path.join(dirname, 'jit_params.npz'),
                  {k: np.asarray(v) for k, v in params.items()})
    _atomic_savez(os.path.join(dirname, 'jit_buffers.npz'),
                  {k: np.asarray(v) for k, v in buffers.items()})


# parity: the reference exposes DataLoader under fluid.io as well
from .reader import DataLoader  # noqa: E402
