"""DataFeeder (ref: python/paddle/fluid/data_feeder.py): converts a batch of
python rows into the feed dict of batched numpy arrays."""
from __future__ import annotations

import numpy as np

from .core.dtypes import convert_dtype
from .framework import Variable


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    @staticmethod
    def _convert(var, arr):
        """Cast/reshape one batched array to its feed var's declared spec."""
        if isinstance(var, Variable):
            want = np.dtype(convert_dtype(var.dtype)
                            .replace('bfloat16', 'float32'))
            arr = arr.astype(want, copy=False)
            # reshape trailing dims to the declared var shape
            tail = [s for s in var.shape[1:]]
            if tail and all(s > 0 for s in tail):
                arr = arr.reshape((arr.shape[0], *tail))
        return arr

    def feed(self, iterable):
        columns = None
        for row in iterable:
            if columns is None:
                columns = [[] for _ in row]
            for c, item in zip(columns, row):
                c.append(np.asarray(item))
        out = {}
        for var, col in zip(self.feed_vars, columns or []):
            name = var.name if isinstance(var, Variable) else var
            ragged = (isinstance(var, Variable) and var.lod_level
                      and len({c.shape[:1] for c in col}) > 1)
            if ragged:
                # lod_level>0 var with varying row lengths → LoDTensor
                # (Executor unpacks to padded data + '@LEN' lengths)
                from .core.lod import create_lod_tensor
                out[name] = create_lod_tensor(
                    col, [[int(c.shape[0]) for c in col]])
            else:
                out[name] = self._convert(var, np.stack(col))
        return out


    def feed_batch(self, fields):
        """Already-batched per-field arrays → feed dict with the same
        cast/reshape rules as feed() (the native-pipeline fast path)."""
        out = {}
        for var, arr in zip(self.feed_vars, fields):
            name = var.name if isinstance(var, Variable) else var
            out[name] = self._convert(var, np.asarray(arr))
        return out
