"""Elastic runtime (ROADMAP item: elastic fleet + autoscaling tier).

Two halves, both riding existing machinery instead of new control planes:

- **Training — resize-on-restore** (:mod:`reshard`, :mod:`schedule`): a
  sharded checkpoint written at nproc=4 restores onto nproc=2/8 — full
  values reassemble on read (PR 12), the partitioner re-lays the tiles,
  and :func:`check_reshard` validates the saved mesh/specs against the
  restoring fleet UP FRONT (typed :class:`ReshardError` instead of a
  ``device_put`` shape error). Scheduled grow/shrink
  (``PADDLE_TPU_ELASTIC_RESIZE``) checkpoints synchronously at the
  boundary and exits through the exit-for-resume ladder; goodput books
  the downtime in its own resize bucket.
- **Serving — autoscaler** (:mod:`autoscaler`, :mod:`launcher`): a
  control loop beside the router consumes the always-on windowed series
  and spawns/retires replicas through the :class:`ReplicaLauncher` seam,
  gated behind the existing drain + cold-replica warmup machinery
  (``PADDLE_TPU_AUTOSCALE_*`` knobs).

Docs: docs/RESILIENCE.md "Elasticity", docs/SERVING.md "Autoscaler".
"""
from .reshard import ReshardError, check_reshard, current_mesh_axes
from .schedule import (ENV_ELASTIC_RESIZE, RESIZE_FILE, ResizePlan,
                       clear_resize_request, parse_resize_env,
                       parse_resize_spec, read_resize_request,
                       write_resize_request)

__all__ = [
    'ReshardError', 'check_reshard', 'current_mesh_axes',
    'ResizePlan', 'parse_resize_env', 'parse_resize_spec',
    'write_resize_request', 'read_resize_request', 'clear_resize_request',
    'ENV_ELASTIC_RESIZE', 'RESIZE_FILE',
    'AutoscaleConfig', 'Autoscaler', 'ReplicaLauncher',
    'ProcessReplicaLauncher', 'CallableReplicaLauncher',
]


def __getattr__(name):
    # the serving-side half imports the serving package; keep it lazy so
    # training-only processes never pay (or break on) that import
    if name in ('AutoscaleConfig', 'Autoscaler'):
        from . import autoscaler as _a
        return getattr(_a, name)
    if name in ('ReplicaLauncher', 'ProcessReplicaLauncher',
                'CallableReplicaLauncher'):
        from . import launcher as _l
        return getattr(_l, name)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
