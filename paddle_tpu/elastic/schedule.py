"""Scheduled fleet grow/shrink at step boundaries.

The resize ladder (docs/RESILIENCE.md "Elasticity") rides the PR 12
exit-for-resume machinery end to end — no new process-control plane:

1. ``PADDLE_TPU_ELASTIC_RESIZE=at_step=N:nproc=M`` (strict parse) arms
   the :class:`~paddle_tpu.resilience.manager.CheckpointManager` with a
   :class:`ResizePlan`;
2. at the first boundary ``step >= N`` the manager commits a SYNCHRONOUS
   checkpoint at that exact step (durable before any exit — a scheduled
   resize must lose zero steps, unlike a crash), rank 0 writes the
   ``resize.json`` request beside the checkpoints, and the heartbeat is
   stamped ``resize_exit`` so the next incarnation's goodput books the
   downtime into the *resize* bucket, not the crash bucket;
3. every host returns ``True`` from ``end_of_step`` with
   ``manager.resize_requested`` set; the train loop exits through
   :func:`~paddle_tpu.fleet_runtime.coordinator.exit_for_resume`
   (exit 75 — the restarter's existing resume signal);
4. the restarter reads :func:`read_resize_request` and relaunches the
   fleet at ``target_nproc``; restore re-lays the tiles onto the new mesh
   (validated by :mod:`~paddle_tpu.elastic.reshard`).
"""
from __future__ import annotations

import json
import os
import time

from ..resilience.snapshot import atomic_write_bytes

__all__ = ['ResizePlan', 'parse_resize_env', 'parse_resize_spec',
           'write_resize_request', 'read_resize_request',
           'clear_resize_request', 'ENV_ELASTIC_RESIZE', 'RESIZE_FILE']

ENV_ELASTIC_RESIZE = 'PADDLE_TPU_ELASTIC_RESIZE'
RESIZE_FILE = 'resize.json'

_FORM = "'at_step=<N>:nproc=<M>' with N >= 1 and M >= 1"


class ResizePlan:
    """One scheduled resize: exit for resume at the first boundary
    ``>= step``, to be relaunched at ``nproc`` processes."""

    __slots__ = ('step', 'nproc')

    def __init__(self, step, nproc):
        self.step = int(step)
        self.nproc = int(nproc)

    def due(self, step):
        return int(step) >= self.step

    def __repr__(self):
        return f'ResizePlan(step={self.step}, nproc={self.nproc})'

    def __eq__(self, other):
        return (isinstance(other, ResizePlan)
                and (self.step, self.nproc) == (other.step, other.nproc))

    def __hash__(self):
        return hash((self.step, self.nproc))


def parse_resize_spec(raw, name=ENV_ELASTIC_RESIZE):
    """``at_step=N:nproc=M`` → :class:`ResizePlan`; anything else raises
    naming the knob and the supported form (house strict-parse rule)."""
    fields = {}
    for part in str(raw).split(':'):
        key, sep, val = part.partition('=')
        if not sep or key.strip() not in ('at_step', 'nproc'):
            raise ValueError(
                f'{name}={raw!r} is not supported; supported form: {_FORM}')
        try:
            fields[key.strip()] = int(val)
        except ValueError:
            raise ValueError(
                f'{name}={raw!r} is not supported; supported form: {_FORM}')
    if set(fields) != {'at_step', 'nproc'} or fields['at_step'] < 1 \
            or fields['nproc'] < 1:
        raise ValueError(
            f'{name}={raw!r} is not supported; supported form: {_FORM}')
    return ResizePlan(fields['at_step'], fields['nproc'])


def parse_resize_env(environ=None):
    """The armed :class:`ResizePlan` from ``PADDLE_TPU_ELASTIC_RESIZE``,
    or None when the knob is unset."""
    raw = (environ if environ is not None
           else os.environ).get(ENV_ELASTIC_RESIZE, '').strip()
    if not raw:
        return None
    return parse_resize_spec(raw)


def write_resize_request(directory, step, target_nproc, from_nproc=None):
    """Atomic ``resize.json`` beside the checkpoints: the restarter's
    instruction to relaunch at ``target_nproc``. Returns the record."""
    record = {'step': int(step), 'target_nproc': int(target_nproc),
              'from_nproc': None if from_nproc is None else int(from_nproc),
              'unix_time': time.time()}
    atomic_write_bytes(os.path.join(directory, RESIZE_FILE),
                       json.dumps(record, indent=1).encode())
    return record


def read_resize_request(directory):
    """The pending resize request, or None."""
    try:
        with open(os.path.join(directory, RESIZE_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def clear_resize_request(directory):
    """Consume the request (the restarter, after relaunching)."""
    try:
        os.unlink(os.path.join(directory, RESIZE_FILE))
    except OSError:
        pass
