"""Reshard manifest validation: may a checkpoint saved under one mesh be
re-laid onto the mesh the RESTORING fleet configured?

PR 12's sharded checkpoints reassemble every variable to its FULL global
value on read, so reshard-on-restore needs no data movement beyond the
normal re-placement — *when the new mesh can actually tile the state*. The
failure mode this module closes is the other case: a checkpoint whose spec
manifest shards ``scope/fc_0.w_0`` dim 0 over ``fsdp`` restored onto a
fleet whose ``fsdp`` axis no longer divides that dim (or no longer exists)
used to die as an opaque shape error deep inside ``device_put``, after
minutes of bring-up. :func:`check_reshard` validates the saved manifest
against the restoring partitioner UP FRONT and raises a typed
:class:`ReshardError` naming the saved vs. current mesh axes and the first
offending variable/dimension.

The saved manifest is the partitioner's
:meth:`~paddle_tpu.partition.partitioner.Partitioner.state_manifest`
(``{'mesh_axes', 'axis_rules', 'specs'}``) recorded in every checkpoint's
``meta['partition']``; shapes come from the reassembled arrays themselves.
"""
from __future__ import annotations

__all__ = ['ReshardError', 'check_reshard', 'current_mesh_axes']

_SCOPE_PREFIX = 'scope/'


class ReshardError(ValueError):
    """A checkpoint's saved partition layout cannot be re-laid onto the
    restoring fleet's mesh. Carries ``saved_axes`` / ``current_axes``
    (mesh-axis-name → size dicts) and, when per-variable, ``name``/``dim``
    of the first offending tile layout."""

    def __init__(self, message, saved_axes=None, current_axes=None,
                 name=None, dim=None):
        super().__init__(message)
        self.saved_axes = dict(saved_axes or {})
        self.current_axes = dict(current_axes or {})
        self.name = name
        self.dim = dim


def current_mesh_axes(partitioner=None):
    """The restoring process's mesh axes (``{name: size}``), or ``{}``
    when no mesh is configured (single-device / replicated semantics —
    every full value is placeable, nothing to validate)."""
    if partitioner is None:
        from ..partition import get_partitioner
        partitioner = get_partitioner()
    if partitioner.mesh is None:
        return {}
    return dict(partitioner.axis_sizes())


def _spec_axes(entry):
    """One spec entry (None | axis name | list of axis names) → tuple of
    mesh axis names the dim is sharded over."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def _shape_for(name, shapes):
    if shapes is None:
        return None
    return shapes.get(name) or shapes.get(_SCOPE_PREFIX + name)


def check_reshard(saved, partitioner=None, shapes=None, step=None):
    """Validate `saved` (a checkpoint's ``meta['partition']`` manifest)
    against the restoring process's mesh. Returns a summary dict
    ``{'saved_axes', 'current_axes', 'resharded'}`` — ``resharded`` is
    True when the mesh topology changed and tiles will be re-laid.

    Raises :class:`ReshardError` up front when a saved spec names a mesh
    axis the current mesh does not have, or when the product of the
    current axis sizes for a sharded dim no longer divides that dim
    (`shapes`: ``{name_or_scope_key: global shape}`` from the reassembled
    arrays; dims with no shape available are skipped).

    A process with NO configured mesh restores every value replicated —
    always legal, never an error."""
    saved = saved or {}
    saved_axes = dict(saved.get('mesh_axes') or {})
    current_axes = current_mesh_axes(partitioner)
    where = f' (checkpoint step {step})' if step is not None else ''
    summary = {'saved_axes': saved_axes, 'current_axes': current_axes,
               'resharded': bool(saved_axes) and saved_axes != current_axes}
    if not current_axes:
        return summary
    for name, entries in (saved.get('specs') or {}).items():
        shape = _shape_for(name, shapes)
        for dim, entry in enumerate(entries):
            axes = _spec_axes(entry)
            if not axes:
                continue
            missing = [a for a in axes if a not in current_axes]
            if missing:
                raise ReshardError(
                    f'cannot reshard {name!r} dim {dim}{where}: saved '
                    f'layout shards it over mesh axis '
                    f'{"/".join(missing)!s} which the restoring mesh '
                    f'does not have (saved mesh {saved_axes}, current '
                    f'mesh {current_axes})',
                    saved_axes=saved_axes, current_axes=current_axes,
                    name=name, dim=dim)
            if shape is None or dim >= len(shape):
                continue
            size = 1
            for a in axes:
                size *= int(current_axes[a])
            if size > 0 and int(shape[dim]) % size != 0:
                raise ReshardError(
                    f'cannot reshard {name!r}{where}: dim {dim} of '
                    f'global shape {tuple(shape)} is sharded over '
                    f'{"x".join(axes)} but is not divisible by the '
                    f'restoring mesh\'s {"x".join(axes)} size {size} '
                    f'(saved mesh {saved_axes}, current mesh '
                    f'{current_axes})',
                    saved_axes=saved_axes, current_axes=current_axes,
                    name=name, dim=dim)
    return summary
