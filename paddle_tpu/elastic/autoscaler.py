"""Serving-tier autoscaler: a control loop beside the router that grows
and shrinks the replica set from the always-on windowed load series.

Signals (PR 17 series, shipped in every replica's ``/healthz`` and cached
on the router's :class:`~paddle_tpu.serving.tier.router.Replica` view):
``queue_depth`` (scheduler backlog), ``occupancy`` (decode slot
utilization), ``ttft`` p99 (time-to-first-token — the user-visible SLO).
Policy, evaluated once per ``interval_s`` tick (docs/SERVING.md
"Autoscaler"):

- **scale UP** when mean queue depth per routable replica exceeds
  ``up_queue`` or p99 TTFT exceeds ``up_ttft_s``, capped at
  ``max_replicas``;
- **scale DOWN** when mean occupancy stays below ``down_occupancy`` AND
  the queue is empty for ``down_delay_s`` straight, floored at
  ``min_replicas``;
- both directions respect ``cooldown_s`` between decisions (hysteresis:
  one decision per cooldown window, sustained-low for down).

Safety rides the EXISTING tier machinery, never around it: a launched
replica enters the router cold and unroutable — the warmup gate plus the
fast initial health poll (PR 19 router fix) decide time-to-routable; a
retiring replica is DRAINED first (router stops routing, in-flight
streams run to completion, replica-side queue observed empty) and only
then retired through the :class:`~paddle_tpu.elastic.launcher
.ReplicaLauncher` seam — scale-down drops zero requests by construction.

Every decision is recorded: ``autoscale_decisions{action,trigger}``,
``autoscale_replicas``, ``autoscale_time_to_routable_seconds``, and the
in-memory ``Autoscaler.decisions`` journal the drills assert on.
"""
from __future__ import annotations

import logging
import threading
import time

from ..log_helper import get_logger
from ..serving import metrics as _m
from ..serving.tier.knobs import (
    ENV_AUTOSCALE, ENV_AUTOSCALE_COOLDOWN_S, ENV_AUTOSCALE_DOWN_DELAY_S,
    ENV_AUTOSCALE_DOWN_OCC, ENV_AUTOSCALE_INTERVAL_S, ENV_AUTOSCALE_MAX,
    ENV_AUTOSCALE_MIN, ENV_AUTOSCALE_UP_QUEUE, ENV_AUTOSCALE_UP_TTFT_S,
    parse_flag_env, parse_float_env, parse_int_env)

__all__ = ['AutoscaleConfig', 'Autoscaler']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [elastic] %(message)s')


class AutoscaleConfig:
    """Hysteresis policy knobs; :meth:`from_env` strict-parses the
    ``PADDLE_TPU_AUTOSCALE_*`` set (tier/knobs.py table)."""

    def __init__(self, min_replicas=1, max_replicas=4, interval_s=1.0,
                 up_queue=4.0, up_ttft_s=2.0, down_occupancy=0.25,
                 cooldown_s=10.0, down_delay_s=30.0):
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.up_queue = float(up_queue)
        self.up_ttft_s = float(up_ttft_s)
        self.down_occupancy = float(down_occupancy)
        self.cooldown_s = float(cooldown_s)
        self.down_delay_s = float(down_delay_s)
        if self.min_replicas > self.max_replicas:
            raise ValueError(
                f'{ENV_AUTOSCALE_MIN}={self.min_replicas} must be <= '
                f'{ENV_AUTOSCALE_MAX}={self.max_replicas}')

    @classmethod
    def from_env(cls):
        return cls(
            min_replicas=parse_int_env(ENV_AUTOSCALE_MIN, 1, minimum=1),
            max_replicas=parse_int_env(ENV_AUTOSCALE_MAX, 4, minimum=1),
            interval_s=parse_float_env(ENV_AUTOSCALE_INTERVAL_S, 1.0),
            up_queue=parse_float_env(ENV_AUTOSCALE_UP_QUEUE, 4.0),
            up_ttft_s=parse_float_env(ENV_AUTOSCALE_UP_TTFT_S, 2.0),
            down_occupancy=parse_float_env(ENV_AUTOSCALE_DOWN_OCC, 0.25),
            cooldown_s=parse_float_env(ENV_AUTOSCALE_COOLDOWN_S, 10.0),
            down_delay_s=parse_float_env(ENV_AUTOSCALE_DOWN_DELAY_S, 30.0))

    @staticmethod
    def enabled_from_env():
        return parse_flag_env(ENV_AUTOSCALE, default=False)


class Autoscaler:
    """The control loop. ``start=True`` runs :meth:`tick` every
    ``config.interval_s`` on a daemon thread; tests drive :meth:`tick`
    directly with ``start=False``."""

    def __init__(self, router, launcher, config=None, start=True):
        self.router = router
        self.launcher = launcher
        self.config = config if config is not None \
            else AutoscaleConfig.from_env()
        self.decisions = []            # [{'action','trigger','replicas',..}]
        self._lock = threading.Lock()
        self._last_action_t = -float('inf')
        self._low_since = None
        self._pending_up = {}          # url -> launch monotonic (cold gate)
        self._retiring = {}            # url -> drain-start monotonic
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name='paddle-tpu-autoscaler', daemon=True)
        if start:
            self._thread.start()

    # -- signal collection -------------------------------------------------
    def signals(self):
        """Fold the routable replicas' cached /healthz series into the
        tick's decision inputs. Replicas predating the series block fall
        back to their reported live load."""
        reps = [r for r in list(self.router.replicas)
                if r.url not in self._retiring]
        routable = [r for r in reps if r.routable()]
        queue = occ = ttft = 0.0
        if routable:
            queues, occs, ttfts = [], [], []
            for r in routable:
                s = getattr(r, 'series', None) or {}
                q = (s.get('queue_depth') or {}).get('mean')
                queues.append(float(q) if q is not None
                              else float(r.reported_load))
                o = (s.get('occupancy') or {}).get('mean')
                if o is not None:
                    occs.append(float(o))
                t = (s.get('ttft') or {}).get('p99')
                if t is not None:
                    ttfts.append(float(t))
            queue = sum(queues) / len(queues)
            occ = sum(occs) / len(occs) if occs else 0.0
            ttft = max(ttfts) if ttfts else 0.0
        return {'replicas': len(reps), 'routable': len(routable),
                'queue_depth': queue, 'occupancy': occ, 'ttft_p99': ttft}

    # -- the decision ------------------------------------------------------
    def tick(self, now=None):
        """One control-loop evaluation; returns the decision record made
        this tick (or None). Also advances pending scale-ups (cold →
        routable bookkeeping) and pending drains (drained → retired)."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            self._advance_pending(now)
            sig = self.signals()
            _m.autoscale_replicas.set(sig['replicas'])
            _m.autoscale_replicas_routable.set(sig['routable'])
            cooled = now - self._last_action_t >= self.config.cooldown_s
            low = (sig['routable'] > 0
                   and sig['occupancy'] < self.config.down_occupancy
                   and sig['queue_depth'] < 1.0)
            if low:
                if self._low_since is None:
                    self._low_since = now
            else:
                self._low_since = None
            decision = None
            if sig['replicas'] < self.config.min_replicas:
                decision = self._scale_up(sig, 'min_replicas', now)
            elif cooled and sig['replicas'] < self.config.max_replicas \
                    and sig['routable'] > 0 \
                    and sig['queue_depth'] > self.config.up_queue:
                decision = self._scale_up(sig, 'queue_depth', now)
            elif cooled and sig['replicas'] < self.config.max_replicas \
                    and sig['routable'] > 0 \
                    and sig['ttft_p99'] > self.config.up_ttft_s:
                decision = self._scale_up(sig, 'ttft_p99', now)
            elif cooled and low and not self._pending_up \
                    and sig['replicas'] > self.config.min_replicas \
                    and sig['routable'] > 1 \
                    and now - self._low_since >= self.config.down_delay_s:
                decision = self._scale_down(sig, 'occupancy', now)
            return decision

    def _record(self, action, trigger, sig, extra=None):
        record = {'action': action, 'trigger': trigger,
                  'replicas': sig['replicas'], 'signals': dict(sig),
                  'unix_time': time.time()}
        record.update(extra or {})
        self.decisions.append(record)
        _m.autoscale_decisions.labels(action=action, trigger=trigger).inc()
        _logger.info('autoscale %s (trigger=%s): %s', action, trigger, sig)
        return record

    def _scale_up(self, sig, trigger, now):
        url = self.launcher.launch()
        self.router.add_replica(url)
        self._pending_up[url.rstrip('/')] = now
        self._last_action_t = now
        return self._record('up', trigger, sig, {'url': url})

    def _scale_down(self, sig, trigger, now):
        # drain the least-loaded routable replica; never the last one
        candidates = [r for r in list(self.router.replicas)
                      if r.routable() and r.url not in self._retiring]
        victim = min(candidates, key=lambda r: r.load())
        self.router.drain(victim.url)
        self._retiring[victim.url] = now
        self._low_since = None
        self._last_action_t = now
        return self._record('down', trigger, sig, {'url': victim.url})

    def _advance_pending(self, now):
        # cold scale-ups: book time-to-routable once the warmup gate opens
        for url, t0 in list(self._pending_up.items()):
            try:
                rep = self.router._replica_by_url(url)
            except KeyError:
                self._pending_up.pop(url)
                continue
            if rep.routable():
                self._pending_up.pop(url)
                _m.autoscale_time_to_routable_seconds.observe(now - t0)
        # drains: retire once the router-side in-flight AND the replica's
        # own queue are empty — the zero-drop contract
        for url, t0 in list(self._retiring.items()):
            try:
                rep = self.router._replica_by_url(url)
            except KeyError:
                self._retiring.pop(url)
                continue
            if rep.inflight == 0 and rep.reported_load == 0:
                self.router.remove_replica(url)
                self._retiring.pop(url)
                _m.autoscale_drain_seconds.observe(now - t0)
                try:
                    self.launcher.retire(url)
                except Exception as e:   # noqa: BLE001 — replica already gone
                    _logger.warning('retire(%s) failed: %s', url, e)

    # -- lifecycle ---------------------------------------------------------
    def _loop(self):
        while not self._closed.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception as e:   # noqa: BLE001 — loop must survive
                _logger.warning('autoscaler tick failed: %s', e)

    def draining(self):
        return sorted(self._retiring)

    def close(self):
        self._closed.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
