"""The ReplicaLauncher seam: how the autoscaler turns a scale decision
into an actual replica process (and back).

The :class:`~paddle_tpu.elastic.autoscaler.Autoscaler` never spawns or
kills anything itself — it calls ``launcher.launch() -> url`` and
``launcher.retire(url)`` through this seam, so the same control loop
drives real subprocesses (:class:`ProcessReplicaLauncher` →
``python -m paddle_tpu.serving.tier.replica``), in-process stacks in
tests/bench (:class:`CallableReplicaLauncher`), or a cluster scheduler
(implement the two methods).
"""
from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time

from ..log_helper import get_logger

__all__ = ['ReplicaLauncher', 'ProcessReplicaLauncher',
           'CallableReplicaLauncher']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [elastic] %(message)s')


class ReplicaLauncher:
    """Abstract seam. ``launch()`` returns the new replica's base URL
    (the replica may still be COLD — the router's warmup gate, not the
    launcher, decides routability); ``retire(url)`` tears one down. The
    autoscaler only calls ``retire`` after the router drained the replica
    to zero in-flight work."""

    def launch(self):
        raise NotImplementedError

    def retire(self, url):
        raise NotImplementedError

    def close(self):
        """Tear down everything this launcher started (best effort)."""


class ProcessReplicaLauncher(ReplicaLauncher):
    """Spawns real decode-replica subprocesses
    (``python -m paddle_tpu.serving.tier.replica --port 0``) and parses
    the ready-line handshake for the bound port. ``lazy_warmup=True``
    (the default) returns as soon as the process is serving — COLD — so
    scale-up latency is the spawn, not the compile cliff; the router's
    warmup gate holds traffic until ``/healthz`` flips ``warmup.done``."""

    def __init__(self, seed=None, extra_args=None, env=None,
                 lazy_warmup=True, ready_timeout_s=120.0):
        self.seed = seed
        self.extra_args = list(extra_args or [])
        self.env = dict(env) if env is not None else None
        self.lazy_warmup = bool(lazy_warmup)
        self.ready_timeout_s = float(ready_timeout_s)
        self._procs = {}            # url -> subprocess.Popen

    def launch(self):
        cmd = [sys.executable, '-m', 'paddle_tpu.serving.tier.replica',
               '--port', '0']
        if self.seed is not None:
            cmd += ['--seed', str(int(self.seed))]
        if self.lazy_warmup:
            cmd.append('--lazy-warmup')
        cmd += self.extra_args
        env = dict(os.environ if self.env is None else self.env)
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, env=env,
                                text=True)
        deadline = time.monotonic() + self.ready_timeout_s
        line = ''
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.strip() or proc.poll() is not None:
                break
        try:
            ready = json.loads(line)
            assert ready.get('ready') and 'port' in ready
        except (ValueError, AssertionError):
            proc.kill()
            raise RuntimeError(
                f'replica launch failed: no ready line within '
                f'{self.ready_timeout_s:.0f}s (got {line!r}, '
                f'rc={proc.poll()})')
        url = f"http://127.0.0.1:{ready['port']}"
        self._procs[url] = proc
        _logger.info('launched replica %s (pid %d)', url, proc.pid)
        return url

    def retire(self, url):
        proc = self._procs.pop(url.rstrip('/'), None)
        if proc is None:
            raise KeyError(f'unknown replica {url}')
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        _logger.info('retired replica %s', url)

    def close(self):
        for url in list(self._procs):
            try:
                self.retire(url)
            except Exception:
                pass


class CallableReplicaLauncher(ReplicaLauncher):
    """Launcher over two callables — ``launch_fn() -> url`` and
    ``retire_fn(url)`` — for in-process replica stacks (tests, the
    autoscaler bench) and custom schedulers."""

    def __init__(self, launch_fn, retire_fn, close_fn=None):
        self._launch = launch_fn
        self._retire = retire_fn
        self._close = close_fn
        self.launched = []
        self.retired = []

    def launch(self):
        url = self._launch()
        self.launched.append(url)
        return url

    def retire(self, url):
        self._retire(url)
        self.retired.append(url)

    def close(self):
        if self._close is not None:
            self._close()
