"""fluid.input (ref: python/paddle/fluid/input.py) — the v1.7 new-style
`embedding` / `one_hot` entry points. The TPU lowering is shared with the
layers versions (gather / one-hot are single XLA ops either way); the
new-style semantics (no trailing [.,1] dim requirement) already hold
because the underlying ops accept ids of any rank."""
from .layers.nn import embedding, one_hot

__all__ = ['embedding', 'one_hot']
