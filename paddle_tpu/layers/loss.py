"""fluid.layers loss functions (ref: python/paddle/fluid/layers/loss.py)."""
from __future__ import annotations

from .common import apply_op_layer, generate_layer_fn

__all__ = ['cross_entropy', 'square_error_cost', 'softmax_with_cross_entropy',
           'sigmoid_cross_entropy_with_logits', 'smooth_l1', 'huber_loss',
           'kldiv_loss', 'bpr_loss', 'rank_loss', 'margin_rank_loss',
           'log_loss', 'mse_loss', 'npair_loss', 'dice_loss', 'center_loss',
           'teacher_student_sigmoid_loss', 'sampled_softmax_with_cross_entropy',
           'hsigmoid', 'edit_distance', 'warpctc']


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return apply_op_layer('cross_entropy', {'x': input, 'label': label},
                          {'soft_label': soft_label,
                           'ignore_index': ignore_index})


def square_error_cost(input, label):
    return apply_op_layer('square_error_cost', {'x': input, 'label': label})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss, sm = apply_op_layer('softmax_with_cross_entropy',
                              {'logits': logits, 'label': label},
                              {'soft_label': soft_label,
                               'ignore_index': ignore_index, 'axis': axis})
    return (loss, sm) if return_softmax else loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    return apply_op_layer('sigmoid_cross_entropy_with_logits',
                          {'x': x, 'label': label},
                          {'ignore_index': ignore_index,
                           'normalize': normalize}, name=name)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    return apply_op_layer('smooth_l1_loss',
                          {'x': x, 'y': y, 'inside_weight': inside_weight,
                           'outside_weight': outside_weight},
                          {'sigma': sigma if sigma is not None else 1.0})


huber_loss = generate_layer_fn('huber_loss')
kldiv_loss = generate_layer_fn('kldiv_loss')
bpr_loss = generate_layer_fn('bpr_loss')
rank_loss = generate_layer_fn('rank_loss')
margin_rank_loss = generate_layer_fn('margin_rank_loss')
log_loss = generate_layer_fn('log_loss')
teacher_student_sigmoid_loss = generate_layer_fn('teacher_student_sigmoid_loss')


def mse_loss(input, label):
    sq = apply_op_layer('square_error_cost', {'x': input, 'label': label})
    return apply_op_layer('reduce_mean', {'x': sq})


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """ref: layers/loss.py:npair_loss — composed from existing layers."""
    from . import nn
    a2 = apply_op_layer('reduce_sum', {'x': apply_op_layer(
        'elementwise_mul', {'x': anchor, 'y': anchor})})
    p2 = apply_op_layer('reduce_sum', {'x': apply_op_layer(
        'elementwise_mul', {'x': positive, 'y': positive})})
    l2 = apply_op_layer('scale', {'x': apply_op_layer(
        'elementwise_add', {'x': a2, 'y': p2})}, {'scale': l2_reg * 0.25})
    sim = nn.matmul(anchor, positive, transpose_y=True)
    lbl = apply_op_layer('cast', {'x': labels}, {'dtype': 'float32'})
    import numpy as np
    # soft labels: equality matrix normalized per row
    eq = apply_op_layer('equal', {'x': apply_op_layer('unsqueeze', {'x': lbl}, {'axes': [1]}),
                                  'y': apply_op_layer('unsqueeze', {'x': lbl}, {'axes': [0]})})
    eqf = apply_op_layer('cast', {'x': eq}, {'dtype': 'float32'})
    row = apply_op_layer('reduce_sum', {'x': eqf}, {'dim': [1], 'keep_dim': True})
    soft = apply_op_layer('elementwise_div', {'x': eqf, 'y': row})
    ce = apply_op_layer('softmax_with_cross_entropy',
                        {'logits': sim, 'label': soft}, {'soft_label': True})[0]
    loss = apply_op_layer('reduce_mean', {'x': ce})
    return apply_op_layer('elementwise_add', {'x': loss, 'y': l2})


def dice_loss(input, label, epsilon=1e-5):
    return apply_op_layer('dice_loss', {'x': input, 'label': label},
                          {'epsilon': epsilon})


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    from ..layer_helper import LayerHelper
    from ..initializer import ConstantInitializer
    helper = LayerHelper('center_loss', param_attr=param_attr)
    d = input.shape[-1]
    centers = helper.create_parameter(
        helper.param_attr, [num_classes, d], input.dtype,
        default_initializer=ConstantInitializer(0.0))
    centers.stop_gradient = True
    centers.trainable = False
    from .tensor import fill_constant
    rate = alpha if hasattr(alpha, 'name') else fill_constant([1], 'float32', alpha)
    loss, _, _ = apply_op_layer(
        'center_loss',
        {'x': input, 'label': label, 'centers': centers, 'update_rate': rate},
        {'cluster_num': num_classes, 'need_update': update_center})
    return loss


def sampled_softmax_with_cross_entropy(logits, label, num_samples, **kw):
    """TPU formulation: full softmax is MXU-cheap; sampling adds no win at the
    ref's class counts, so this lowers to softmax_with_cross_entropy (same
    estimator in expectation; ref: layers/loss.py:1204)."""
    return softmax_with_cross_entropy(logits, label)


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid (ref: layers/loss.py:hsigmoid). Default complete-
    binary-tree coding, dense TPU formulation (ops/extra_ops.py)."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('hsigmoid', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr, [num_classes, d],
                                input.dtype)
    b = helper.create_parameter(helper.bias_attr, [num_classes], input.dtype,
                                is_bias=True)
    return apply_op_layer('hsigmoid',
                          {'x': input, 'label': label, 'weight': w, 'bias': b},
                          {'num_classes': num_classes})


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance on padded id sequences (ref: edit_distance_op.cc),
    lax.scan DP over columns — static shapes, TPU-safe (ops/extra_ops.py)."""
    out, seq_num = apply_op_layer(
        'edit_distance',
        {'x': input, 'label': label, 'x_len': input_length,
         'label_len': label_length},
        {'normalized': normalized})
    return out, seq_num


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """CTC loss (ref: warpctc_op.cc) — native jax log-space forward algorithm
    over lax.scan, ops/extra_ops.py (replaces the warp-ctc CUDA library)."""
    return apply_op_layer(
        'warpctc',
        {'logits': input, 'label': label, 'logit_len': input_length,
         'label_len': label_length},
        {'blank': blank, 'norm_by_times': norm_by_times})
