"""Structure utilities (ref: python/paddle/fluid/layers/utils.py) —
nest flatten/pack/map used by RNN cells and decoders."""

__all__ = ['convert_to_list', 'is_sequence', 'flatten', 'map_structure',
           'pack_sequence_as', 'assert_same_structure']


def convert_to_list(value, n, name, dtype=int):
    """ref utils.py:convert_to_list — scalar → [v]*n, or validate a list
    of length n."""
    if isinstance(value, dtype):
        return [value] * n
    try:
        value_list = list(value)
    except TypeError:
        raise ValueError(
            f'The {name} argument must be a {dtype} or list of {n} '
            f'{dtype}s, got {value}')
    if len(value_list) != n:
        raise ValueError(
            f'The {name} argument must be a {dtype} or list of {n} '
            f'{dtype}s, got {value}')
    for v in value_list:
        if not isinstance(v, dtype):
            raise ValueError(
                f'The {name} argument must contain {dtype}s, got {v}')
    return value_list


def is_sequence(seq):
    """ref utils.py:is_sequence — list/tuple/dict but not str."""
    return isinstance(seq, (list, tuple, dict)) \
        and not isinstance(seq, str)


def flatten(nest):
    """ref utils.py:flatten — depth-first leaves of a nested structure."""
    if isinstance(nest, dict):
        out = []
        for k in sorted(nest):
            out.extend(flatten(nest[k]))
        return out
    if isinstance(nest, (list, tuple)):
        out = []
        for x in nest:
            out.extend(flatten(x))
        return out
    return [nest]


def pack_sequence_as(structure, flat_sequence):
    """ref utils.py:pack_sequence_as — rebuild `structure`'s shape from a
    flat leaf list."""
    flat = list(flat_sequence)
    want = len(flatten(structure))
    if want != len(flat):
        raise ValueError(
            f'Could not pack sequence: structure has {want} leaves but '
            f'flat_sequence has {len(flat)} elements')

    def build(s):
        if isinstance(s, dict):
            return {k: build(s[k]) for k in sorted(s)}
        if isinstance(s, tuple) and hasattr(s, '_fields'):
            return type(s)(*[build(e) for e in s])
        if isinstance(s, (list, tuple)):
            return type(s)(build(e) for e in s)
        return flat.pop(0)
    return build(structure)


def map_structure(func, *structures):
    """ref utils.py:map_structure — apply func leafwise, preserving
    structure."""
    s0 = structures[0]
    if isinstance(s0, dict):
        return {k: map_structure(func, *[s[k] for s in structures])
                for k in sorted(s0)}
    if isinstance(s0, tuple) and hasattr(s0, '_fields'):
        return type(s0)(*[map_structure(func, *elems)
                          for elems in zip(*structures)])
    if isinstance(s0, (list, tuple)):
        return type(s0)(map_structure(func, *elems)
                        for elems in zip(*structures))
    return func(*structures)


def assert_same_structure(nest1, nest2, check_types=True):
    """ref utils.py:assert_same_structure."""
    f1, f2 = flatten(nest1), flatten(nest2)
    if len(f1) != len(f2):
        raise ValueError(
            f"The two structures don't have the same number of elements: "
            f'{len(f1)} vs {len(f2)}')

    def walk(a, b):
        sa, sb = is_sequence(a), is_sequence(b)
        if sa != sb:
            raise ValueError(
                "The two structures don't have the same nested structure")
        if not sa:
            return
        if check_types and type(a) is not type(b):
            raise TypeError(
                f"The two structures don't have the same sequence type: "
                f'{type(a)} vs {type(b)}')
        if isinstance(a, dict):
            if sorted(a) != sorted(b):
                raise ValueError(
                    "The two dictionaries don't have the same keys")
            for k in a:
                walk(a[k], b[k])
        else:
            if len(a) != len(b):
                raise ValueError(
                    "The two structures don't have the same length")
            for x, y in zip(a, b):
                walk(x, y)
    walk(nest1, nest2)
