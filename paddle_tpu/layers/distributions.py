"""Probability distributions (ref: python/paddle/fluid/layers/
distributions.py — Uniform / Normal / Categorical / MultivariateNormalDiag).

Built on registered ops so every method works in both static graph and
dygraph, and everything inlines into the jitted step. Sampling routes through
the framework PRNG plumbing (needs_rng ops), not host RNG.
"""
from __future__ import annotations

import math

import numpy as np

from ..framework import Variable, in_dygraph_mode
from .common import apply_op_layer, op_call as _op
from .tensor import assign, cast, fill_constant

__all__ = ['Uniform', 'Normal', 'Categorical', 'MultivariateNormalDiag']


def _to_var(x, dtype='float32'):
    if isinstance(x, Variable):
        return x
    if in_dygraph_mode():
        from ..dygraph.base import to_variable
        return to_variable(np.asarray(x, dtype))
    arr = np.asarray(x, dtype)
    if arr.ndim == 0:
        return fill_constant([1], dtype, float(arr))
    return assign(arr)


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high); low/high broadcastable floats or Variables."""

    def __init__(self, low, high):
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        u = _op('uniform_random',
                attrs={'shape': list(shape) + list(self.low.shape),
                       'min': 0.0, 'max': 1.0, 'seed': seed})
        return self.low + u * (self.high - self.low)

    def entropy(self):
        return _op('log', x=self.high - self.low)

    def log_prob(self, value):
        lb = cast(apply_op_layer('greater_equal',
                                 {'x': value, 'y': self.low}), 'float32')
        ub = cast(apply_op_layer('less_than', {'x': value, 'y': self.high}),
                  'float32')
        return _op('log', x=lb * ub) - _op('log', x=self.high - self.low)


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        z = _op('gaussian_random',
                attrs={'shape': list(shape) + list(self.loc.shape),
                       'mean': 0.0, 'std': 1.0, 'seed': seed})
        return self.loc + z * self.scale

    def entropy(self):
        return 0.5 + 0.5 * math.log(2.0 * math.pi) + _op('log', x=self.scale)

    def log_prob(self, value):
        var = self.scale * self.scale
        return (-1.0 * ((value - self.loc) * (value - self.loc)) / (2.0 * var)
                - 0.5 * math.log(2.0 * math.pi) - _op('log', x=self.scale))

    def kl_divergence(self, other):
        """KL(self || other), other a Normal."""
        var_ratio = self.scale / other.scale
        var_ratio = var_ratio * var_ratio
        t1 = (self.loc - other.loc) / other.scale
        t1 = t1 * t1
        return 0.5 * (var_ratio + t1 - 1.0 - _op('log', x=var_ratio))


class Categorical(Distribution):
    def __init__(self, logits):
        self.logits = _to_var(logits)

    def _probs(self):
        return _op('softmax', x=self.logits)

    def entropy(self):
        p = self._probs()
        logp = _op('log', x=p + 1e-12)
        neg = -1.0 * _op('reduce_sum', x=p * logp,
                         attrs={'dim': -1, 'keep_dim': False})
        return neg

    def kl_divergence(self, other):
        p = self._probs()
        logp = _op('log', x=p + 1e-12)
        logq = _op('log', x=other._probs() + 1e-12)
        return _op('reduce_sum', x=p * (logp - logq),
                   attrs={'dim': -1, 'keep_dim': False})


class MultivariateNormalDiag(Distribution):
    """N(loc, diag(scale)); scale is the diagonal covariance-factor matrix
    (the reference takes a full `scale` matrix and uses only its diagonal
    determinant/inverse — we use the diagonal directly)."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def _diag(self):
        if len(self.scale.shape) >= 2:
            return apply_op_layer('matrix_diag_part', {'x': self.scale})
        return self.scale

    def entropy(self):
        d = self._diag()
        k = float(self.loc.shape[-1])
        logdet = _op('reduce_sum', x=_op('log', x=d + 1e-12),
                     attrs={'dim': -1, 'keep_dim': False})
        return 0.5 * k * (1.0 + math.log(2.0 * math.pi)) + 0.5 * logdet

    def kl_divergence(self, other):
        d1, d2 = self._diag(), other._diag()
        k = float(self.loc.shape[-1])
        tr = _op('reduce_sum', x=d1 / d2, attrs={'dim': -1, 'keep_dim': False})
        diff = other.loc - self.loc
        quad = _op('reduce_sum', x=diff * diff / d2,
                   attrs={'dim': -1, 'keep_dim': False})
        logdet = (_op('reduce_sum', x=_op('log', x=d2 + 1e-12),
                      attrs={'dim': -1, 'keep_dim': False})
                  - _op('reduce_sum', x=_op('log', x=d1 + 1e-12),
                        attrs={'dim': -1, 'keep_dim': False}))
        return 0.5 * (tr + quad - k + logdet)
