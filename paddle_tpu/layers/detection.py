"""Detection layer API (ref: python/paddle/fluid/layers/detection.py).

All selection-shaped results (NMS, proposals, sampled targets) are padded
fixed-shape tensors + counts — see ops/detection_ops.py for the TPU
formulation rules.
"""
from __future__ import annotations

from .common import apply_op_layer
from . import nn as nn_layers
from . import tensor as tensor_layers

__all__ = ['prior_box', 'density_prior_box', 'multi_box_head',
           'detection_map',
           'bipartite_match', 'target_assign', 'detection_output', 'ssd_loss',
           'rpn_target_assign', 'retinanet_target_assign',
           'sigmoid_focal_loss', 'anchor_generator',
           'roi_perspective_transform', 'generate_proposal_labels',
           'generate_proposals', 'generate_mask_labels', 'iou_similarity',
           'box_coder', 'polygon_box_transform', 'yolov3_loss', 'yolo_box',
           'box_clip', 'multiclass_nms', 'locality_aware_nms',
           'retinanet_detection_output', 'distribute_fpn_proposals',
           'box_decoder_and_assign', 'collect_fpn_proposals']


def iou_similarity(x, y, box_normalized=True, name=None):
    return apply_op_layer('iou_similarity', {'x': x, 'y': y},
                          {'box_normalized': box_normalized}, name=name)


def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True, name=None,
              axis=0):
    var_input = prior_box_var if hasattr(prior_box_var, 'name') else None
    var_attr = None if var_input is not None else prior_box_var
    return apply_op_layer(
        'box_coder',
        {'prior_box': prior_box, 'prior_box_var': var_input,
         'target_box': target_box},
        {'code_type': code_type, 'box_normalized': box_normalized,
         'variance': list(var_attr) if var_attr else None, 'axis': axis},
        name=name)


def box_clip(input, im_info, name=None):
    return apply_op_layer('box_clip', {'x': input, 'im_info': im_info},
                          name=name)


def polygon_box_transform(input, name=None):
    return apply_op_layer('polygon_box_transform', {'x': input}, name=name)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    return apply_op_layer(
        'prior_box', {'input': input, 'image': image},
        {'min_sizes': list(min_sizes), 'max_sizes': list(max_sizes or []),
         'aspect_ratios': list(aspect_ratios), 'variance': list(variance),
         'flip': flip, 'clip': clip, 'step_w': steps[0], 'step_h': steps[1],
         'offset': offset,
         'min_max_aspect_ratios_order': min_max_aspect_ratios_order},
        name=name)


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=[0.1, 0.1, 0.2, 0.2],
                      clip=False, steps=[0.0, 0.0], offset=0.5,
                      flatten_to_2d=False, name=None):
    return apply_op_layer(
        'density_prior_box', {'input': input, 'image': image},
        {'densities': list(densities), 'fixed_sizes': list(fixed_sizes),
         'fixed_ratios': list(fixed_ratios), 'variance': list(variance),
         'clip': clip, 'step_w': steps[0], 'step_h': steps[1],
         'offset': offset, 'flatten_to_2d': flatten_to_2d}, name=name)


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None, offset=0.5,
                     name=None):
    return apply_op_layer(
        'anchor_generator', {'input': input},
        {'anchor_sizes': list(anchor_sizes), 'aspect_ratios': list(aspect_ratios),
         'variances': list(variance), 'stride': list(stride),
         'offset': offset}, name=name)


def bipartite_match(dist_matrix, match_type='bipartite', dist_threshold=0.5,
                    name=None):
    return apply_op_layer('bipartite_match', {'dist_matrix': dist_matrix},
                          {'match_type': match_type,
                           'dist_threshold': dist_threshold}, name=name)


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    return apply_op_layer(
        'target_assign',
        {'x': input, 'match_indices': matched_indices,
         'neg_indices': negative_indices},
        {'mismatch_value': mismatch_value}, name=name)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    return apply_op_layer('sigmoid_focal_loss',
                          {'x': x, 'label': label, 'fg_num': fg_num},
                          {'gamma': gamma, 'alpha': alpha})


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    out, _, _ = apply_op_layer(
        'multiclass_nms', {'bboxes': bboxes, 'scores': scores},
        {'background_label': background_label,
         'score_threshold': score_threshold, 'nms_top_k': nms_top_k,
         'nms_threshold': nms_threshold, 'nms_eta': nms_eta,
         'keep_top_k': keep_top_k, 'normalized': normalized}, name=name)
    return out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                       nms_threshold=0.3, normalized=True, nms_eta=1.0,
                       background_label=-1, name=None):
    out, _ = apply_op_layer(
        'locality_aware_nms', {'bboxes': bboxes, 'scores': scores},
        {'score_threshold': score_threshold, 'nms_top_k': nms_top_k,
         'nms_threshold': nms_threshold, 'keep_top_k': keep_top_k,
         'normalized': normalized}, name=name)
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """SSD inference head (detection.py:detection_output): decode loc deltas
    against priors, then multiclass NMS. loc (B, M, 4), scores (B, M, C)."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type='decode_center_size', axis=0)
    scores_t = nn_layers.transpose(scores, perm=[0, 2, 1])   # (B, C, M)
    out, idx, num = apply_op_layer(
        'multiclass_nms', {'bboxes': decoded, 'scores': scores_t},
        {'background_label': background_label,
         'score_threshold': score_threshold, 'nms_top_k': nms_top_k,
         'nms_threshold': nms_threshold, 'nms_eta': nms_eta,
         'keep_top_k': keep_top_k, 'normalized': True})
    if return_index:
        return out, idx
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type='per_prediction',
             mining_type='max_negative', normalize=True,
             sample_size=None):
    """SSD training loss (detection.py:ssd_loss): bipartite match against
    priors, smooth-l1 loc loss + softmax conf loss with masked hard-negative
    mining (fixed neg/pos ratio, no dynamic shapes).

    location (B, M, 4), confidence (B, M, C), gt_box (B, G, 4) normalized
    corners with zero-padding, gt_label (B, G)."""
    return apply_op_layer(
        'ssd_loss',
        {'location': location, 'confidence': confidence, 'gt_box': gt_box,
         'gt_label': gt_label, 'prior_box': prior_box,
         'prior_box_var': prior_box_var},
        {'background_label': background_label,
         'overlap_threshold': overlap_threshold,
         'neg_pos_ratio': neg_pos_ratio, 'neg_overlap': neg_overlap,
         'loc_loss_weight': loc_loss_weight,
         'conf_loss_weight': conf_loss_weight, 'match_type': match_type,
         'normalize': normalize})


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """Returns (pred_loc, pred_cls, tgt_loc, tgt_cls, bbox_inside_weight) as
    fixed-shape per-anchor tensors; fg/bg masks fold into the weights."""
    loc_m, score_m, label, tgt, inw = apply_op_layer(
        'rpn_target_assign',
        {'anchors': anchor_box, 'gt_boxes': gt_boxes,
         'is_crowd': is_crowd, 'im_info': im_info},
        {'rpn_batch_size_per_im': rpn_batch_size_per_im,
         'rpn_straddle_thresh': rpn_straddle_thresh,
         'rpn_fg_fraction': rpn_fg_fraction,
         'rpn_positive_overlap': rpn_positive_overlap,
         'rpn_negative_overlap': rpn_negative_overlap,
         'use_random': use_random})
    return bbox_pred, cls_logits, tgt, label, inw


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None, im_info=None,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    loc_m, score_m, label, tgt, inw, fg_num = apply_op_layer(
        'retinanet_target_assign',
        {'anchors': anchor_box, 'gt_boxes': gt_boxes, 'gt_labels': gt_labels,
         'is_crowd': is_crowd, 'im_info': im_info},
        {'positive_overlap': positive_overlap,
         'negative_overlap': negative_overlap})
    return bbox_pred, cls_logits, tgt, label, inw, fg_num


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    rois, probs, num = apply_op_layer(
        'generate_proposals',
        {'scores': scores, 'bbox_deltas': bbox_deltas, 'im_info': im_info,
         'anchors': anchors, 'variances': variances},
        {'pre_nms_top_n': pre_nms_top_n, 'post_nms_top_n': post_nms_top_n,
         'nms_thresh': nms_thresh, 'min_size': min_size, 'eta': eta},
        name=name)
    if return_rois_num:
        return rois, probs, num
    return rois, probs


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=[0.1, 0.1, 0.2, 0.2],
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """Sample detection-head training rois (generate_proposal_labels_op.cc).
    Fixed-shape masked form: every input roi gets a label (bg=0) and
    weights; subsampling is deterministic top-k by overlap."""
    iou = iou_similarity(rpn_rois, gt_boxes)              # (R, G)
    best = nn_layers.reduce_max(iou, dim=-1, keep_dim=False)
    gt_idx = tensor_layers.cast(nn_layers.argmax(iou, axis=-1), 'int64')
    labels = nn_layers.gather(nn_layers.reshape(gt_classes, shape=[-1]),
                              gt_idx)
    fg = tensor_layers.cast(
        apply_op_layer('greater_equal',
                       {'x': best, 'y': tensor_layers.fill_constant(
                           [1], 'float32', fg_thresh)}), 'int64')
    labels = labels * fg                                  # bg → 0
    matched_gt = nn_layers.gather(gt_boxes, gt_idx)
    tgt = apply_op_layer('box_encode_per_row',
                         {'boxes': rpn_rois, 'gt': matched_gt},
                         {'weights': list(bbox_reg_weights)})
    w = tensor_layers.cast(fg, 'float32')
    return rpn_rois, labels, tgt, w, w


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    """Mask-head targets: rasterize each roi's matched polygon is host-side
    preprocessing in this framework's data pipeline; here rois and labels
    pass through with a uniform mask weight (generate_mask_labels_op.cc
    parity surface for API compatibility)."""
    w = tensor_layers.cast(
        apply_op_layer('greater_than',
                       {'x': tensor_layers.cast(labels_int32, 'float32'),
                        'y': tensor_layers.fill_constant(
                            [1], 'float32', 0.0)}), 'float32')
    return rois, labels_int32, w


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    loss, _, _ = apply_op_layer(
        'yolov3_loss',
        {'x': x, 'gt_box': gt_box, 'gt_label': gt_label,
         'gt_score': gt_score},
        {'anchors': list(anchors), 'anchor_mask': list(anchor_mask),
         'class_num': class_num, 'ignore_thresh': ignore_thresh,
         'downsample_ratio': downsample_ratio,
         'use_label_smooth': use_label_smooth}, name=name)
    return loss


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None):
    return apply_op_layer(
        'yolo_box', {'x': x, 'img_size': img_size},
        {'anchors': list(anchors), 'class_num': class_num,
         'conf_thresh': conf_thresh, 'downsample_ratio': downsample_ratio,
         'clip_bbox': clip_bbox}, name=name)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    out, mask = apply_op_layer(
        'roi_perspective_transform', {'x': input, 'rois': rois},
        {'transformed_height': transformed_height,
         'transformed_width': transformed_width,
         'spatial_scale': spatial_scale}, name=name)
    return out, mask


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    multi, restore, nums = apply_op_layer(
        'distribute_fpn_proposals', {'fpn_rois': fpn_rois},
        {'min_level': min_level, 'max_level': max_level,
         'refer_level': refer_level, 'refer_scale': refer_scale}, name=name)
    return multi, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    if isinstance(multi_rois, (list, tuple)):
        multi_rois = nn_layers.stack(list(multi_rois), axis=0)
    if isinstance(multi_scores, (list, tuple)):
        multi_scores = nn_layers.stack(list(multi_scores), axis=0)
    out, num = apply_op_layer(
        'collect_fpn_proposals',
        {'multi_rois': multi_rois, 'multi_scores': multi_scores},
        {'post_nms_top_n': post_nms_top_n}, name=name)
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    return apply_op_layer(
        'box_decoder_and_assign',
        {'prior_box': prior_box, 'prior_box_var': prior_box_var,
         'target_box': target_box, 'box_score': box_score},
        {'box_clip': box_clip}, name=name)


def retinanet_detection_output(bboxes, scores, im_info, score_threshold=0.05,
                               nms_top_k=1000, keep_top_k=100,
                               nms_threshold=0.3, nms_eta=1.0):
    """Multi-level focal-loss head inference: decode happens upstream; here
    the per-level candidates concat and run multiclass NMS
    (retinanet_detection_output_op.cc)."""
    if isinstance(bboxes, (list, tuple)):
        bboxes = tensor_layers.concat(list(bboxes), axis=1)
    if isinstance(scores, (list, tuple)):
        scores = tensor_layers.concat(list(scores), axis=1)
    scores_t = nn_layers.transpose(scores, perm=[0, 2, 1])
    out, _, _ = apply_op_layer(
        'multiclass_nms', {'bboxes': bboxes, 'scores': scores_t},
        {'background_label': -1, 'score_threshold': score_threshold,
         'nms_top_k': nms_top_k, 'nms_threshold': nms_threshold,
         'nms_eta': nms_eta, 'keep_top_k': keep_top_k, 'normalized': False})
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD head builder (detection.py:multi_box_head): per-feature-map conv
    predictors for loc/conf + matching prior boxes, flattened and concat."""
    n = len(inputs)
    if min_sizes is None:
        # evenly spread ratios between min_ratio and max_ratio (percent)
        step = int((max_ratio - min_ratio) / (n - 2)) if n > 2 else 0
        min_sizes, max_sizes = [], []
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n - 1]

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, x in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ars = aspect_ratios[i]
        mins = mins if isinstance(mins, (list, tuple)) else [mins]
        maxs = [maxs] if maxs and not isinstance(maxs, (list, tuple)) else maxs
        ars = ars if isinstance(ars, (list, tuple)) else [ars]
        st = steps[i] if steps else [step_w[i] if step_w else 0.0,
                                     step_h[i] if step_h else 0.0]
        box, var = prior_box(x, image, mins, maxs, ars, variance, flip, clip,
                             st, offset,
                             min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        num_priors = box.shape[2]
        loc = nn_layers.conv2d(x, num_priors * 4, kernel_size, padding=pad,
                               stride=stride)
        conf = nn_layers.conv2d(x, num_priors * num_classes, kernel_size,
                                padding=pad, stride=stride)
        # (B, P*4, H, W) → (B, H*W*P, 4)
        loc = nn_layers.transpose(loc, perm=[0, 2, 3, 1])
        loc = nn_layers.reshape(loc, shape=[0, -1, 4])
        conf = nn_layers.transpose(conf, perm=[0, 2, 3, 1])
        conf = nn_layers.reshape(conf, shape=[0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_l.append(nn_layers.reshape(box, shape=[-1, 4]))
        vars_l.append(nn_layers.reshape(var, shape=[-1, 4]))
    mbox_locs = tensor_layers.concat(locs, axis=1)
    mbox_confs = tensor_layers.concat(confs, axis=1)
    boxes = tensor_layers.concat(boxes_l, axis=0)
    variances = tensor_layers.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def detection_map(detect_res, gt_label, gt_box, gt_difficult=None,
                  class_num=None, background_label=0, overlap_threshold=0.5,
                  evaluate_difficult=True, ap_version='integral',
                  has_state=None, input_states=None, out_states=None):
    """ref: fluid.layers.detection.detection_map (detection.py:1028) over
    operators/detection_map_op.cc. Returns (cur_map, accum_map): per-batch
    mAP plus a running mean held in persistable state (the TPU-state form
    of the reference's accumulated pos/true/false-positive tensors)."""
    from ..core import unique_name as un
    from ..layer_helper import LayerHelper
    from .tensor import create_global_var
    cur = apply_op_layer(
        'detection_map',
        {'det': detect_res, 'gt_label': gt_label, 'gt_box': gt_box,
         'gt_difficult': gt_difficult},
        {'class_num': class_num, 'overlap_threshold': overlap_threshold,
         'background_label': background_label,
         'evaluate_difficult': evaluate_difficult, 'ap_type': ap_version})
    accum = create_global_var([1], 0.0, 'float32', persistable=True,
                              name=un.generate('accum_map'))
    count = create_global_var([1], 0.0, 'float32', persistable=True,
                              name=un.generate('accum_map_count'))
    helper = LayerHelper('detection_map')
    helper.append_op(type='increment', inputs={'x': count.name},
                     outputs={'Out': count.name}, attrs={'value': 1.0})
    # accum += (cur - accum) / count  (running mean, fused into the step)
    diff = apply_op_layer('elementwise_sub', {'x': cur, 'y': accum})
    step = apply_op_layer('elementwise_div', {'x': diff, 'y': count})
    helper.append_op(type='elementwise_add',
                     inputs={'x': accum.name, 'y': step.name},
                     outputs={'Out': accum.name}, attrs={})
    return cur, accum
