"""fluid.layers parity namespace."""
from . import common
from .nn import *  # noqa
from .tensor import *  # noqa
from .loss import *  # noqa
from .control_flow import *  # noqa
from .io import data, py_reader, double_buffer, read_file, load
from .io import create_py_reader_by_data
from . import nn, tensor, loss, io, control_flow
from .rnn import *  # noqa — exports the rnn() function over the module name
from .sequence_lod import *  # noqa
from . import sequence_lod
from .learning_rate_scheduler import *  # noqa
from . import learning_rate_scheduler
from . import distributions
from .distributions import Categorical, MultivariateNormalDiag, Normal, Uniform
from .detection import *  # noqa
from . import detection
from .math_op_patch import monkey_patch_variable
from . import utils
from .utils import (convert_to_list, is_sequence, map_structure,
                    pack_sequence_as, assert_same_structure)

monkey_patch_variable()

# accuracy / auc live in layers namespace in the reference too
from .common import apply_op_layer as _apply
from .common import generate_layer_fn
from .common import generate_layer_fn as generate_activation_fn


def autodoc(comment=''):
    """ref: layer_function_generator.autodoc — docstring passthrough."""
    def deco(fn):
        fn.__doc__ = (fn.__doc__ or '') + comment
        return fn
    return deco


def templatedoc(op_type=None):
    """ref: layer_function_generator.templatedoc — docstring passthrough
    (there are no C++ OpProto comments to template from)."""
    def deco(fn):
        return fn
    return deco


def deprecated(since='', instead='', extra_message=''):
    """ref: fluid.layers.deprecated decorator — warns on call."""
    def deco(fn):
        import functools
        import warnings

        @functools.wraps(fn)
        def wrapped(*a, **k):
            warnings.warn(
                f"{fn.__name__} is deprecated"
                + (f" since {since}" if since else '')
                + (f"; use {instead}" if instead else '')
                + (f". {extra_message}" if extra_message else ''),
                DeprecationWarning, stacklevel=2)
            return fn(*a, **k)
        return wrapped
    return deco


def accuracy(input, label, k=1, correct=None, total=None):
    out = _apply('accuracy', {'pred': input, 'label': label}, {'k': k})
    return out[0]


def auc(input, label, curve='ROC', num_thresholds=200, topk=1,
        slide_steps=1):
    """Static AUC: returns batch AUC via rank statistic (stateful accumulators
    live in metrics.Auc for the full parity path)."""
    out = _apply('auc', {'pred': input, 'label': label},
                 {'num_thresholds': num_thresholds})
    return out, [out]
