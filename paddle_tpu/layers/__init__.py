"""fluid.layers parity namespace."""
from . import common
from .nn import *  # noqa
from .tensor import *  # noqa
from .loss import *  # noqa
from .control_flow import *  # noqa
from .io import data
from . import nn, tensor, loss, io, control_flow
from .rnn import *  # noqa — exports the rnn() function over the module name
from .sequence_lod import *  # noqa
from . import sequence_lod
from .learning_rate_scheduler import *  # noqa
from . import learning_rate_scheduler
from . import distributions
from .detection import *  # noqa
from . import detection
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()

# accuracy / auc live in layers namespace in the reference too
from .common import apply_op_layer as _apply


def accuracy(input, label, k=1, correct=None, total=None):
    out = _apply('accuracy', {'pred': input, 'label': label}, {'k': k})
    return out[0]


def auc(input, label, curve='ROC', num_thresholds=200, topk=1,
        slide_steps=1):
    """Static AUC: returns batch AUC via rank statistic (stateful accumulators
    live in metrics.Auc for the full parity path)."""
    out = _apply('auc', {'pred': input, 'label': label},
                 {'num_thresholds': num_thresholds})
    return out, [out]
