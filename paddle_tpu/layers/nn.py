"""fluid.layers.nn parity (ref: python/paddle/fluid/layers/nn.py, 146 fns).

Parameter-bearing layers (fc, conv2d, batch_norm, …) create Parameters via
LayerHelper (init ops land in the startup program); everything else is a thin
wrapper over the registered jax functionals via apply_op_layer, so the same
code path serves static graph AND dygraph.
"""
from __future__ import annotations

from ..core.dtypes import convert_dtype
from ..framework import Variable, in_dygraph_mode
from ..initializer import ConstantInitializer, NormalInitializer, XavierInitializer
from ..layer_helper import LayerHelper
from .common import apply_op_layer, generate_layer_fn

__all__ = []  # filled at bottom


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """ref: layers/nn.py:fc — implemented as mul(+concat) + bias + act."""
    inputs = input if isinstance(input, (list, tuple)) else [input]
    helper = LayerHelper('fc', param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    mul_results = []
    import math
    for x in inputs:
        in_feat = math.prod(x.shape[num_flatten_dims:])
        w = helper.create_parameter(helper.param_attr, [in_feat, size], x.dtype)
        mul_results.append(apply_op_layer(
            'mul', {'x': x, 'y': w},
            {'x_num_col_dims': num_flatten_dims, 'y_num_col_dims': 1}))
    out = mul_results[0] if len(mul_results) == 1 else \
        apply_op_layer('sum', {'xs': mul_results})
    b = helper.create_parameter(helper.bias_attr, [size], 'float32', is_bias=True)
    if b is not None:
        out = apply_op_layer('elementwise_add', {'x': out, 'y': b},
                             {'axis': num_flatten_dims})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype='float32'):
    """ref: layers/nn.py:embedding."""
    helper = LayerHelper('embedding', param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, list(size), dtype,
                                default_initializer=XavierInitializer())
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    out = apply_op_layer('lookup_table', {'w': w, 'ids': input},
                         {'padding_idx': pad, 'is_sparse': is_sparse,
                          'is_distributed': is_distributed})
    # LoD travels through the lookup (ref: lookup_table_op InferShape
    # shares the ids LoD): ragged id batches keep their length var so a
    # downstream sequence_pool masks the padding steps — without this the
    # embedding+sequence_pool pair silently pooled pad rows that
    # fused_embedding_seq_pool (correctly) masked
    lv = getattr(input, '_length_var', None)
    if lv is not None:
        out._length_var = lv
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format='NCHW'):
    """ref: layers/nn.py:conv2d (use_cudnn accepted for compat; XLA decides)."""
    helper = LayerHelper('conv2d', param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    c_in = input.shape[1] if data_format == 'NCHW' else input.shape[-1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    import math
    std = math.sqrt(2.0 / (fs[0] * fs[1] * c_in))
    w = helper.create_parameter(
        helper.param_attr, [num_filters, c_in // groups, fs[0], fs[1]],
        input.dtype, default_initializer=NormalInitializer(0.0, std))
    if data_format == 'NHWC':
        # weights stay OIHW in the program; functional transposes to HWIO
        pass
    out = apply_op_layer('conv2d', {'x': input, 'weight': w},
                         {'stride': stride, 'padding': padding,
                          'dilation': dilation, 'groups': groups,
                          'data_format': data_format})
    b = helper.create_parameter(helper.bias_attr, [num_filters], 'float32',
                                is_bias=True)
    if b is not None:
        axis = 1 if data_format == 'NCHW' else 3
        out = apply_op_layer('elementwise_add', {'x': out, 'y': b}, {'axis': axis})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format='NCDHW'):
    helper = LayerHelper('conv3d', param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    c_in = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = helper.create_parameter(
        helper.param_attr, [num_filters, c_in // groups, *fs], input.dtype)
    out = apply_op_layer('conv3d', {'x': input, 'weight': w},
                         {'stride': stride, 'padding': padding,
                          'dilation': dilation, 'groups': groups})
    b = helper.create_parameter(helper.bias_attr, [num_filters], 'float32',
                                is_bias=True)
    if b is not None:
        out = apply_op_layer('elementwise_add', {'x': out, 'y': b}, {'axis': 1})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper('conv2d_transpose', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c_in = input.shape[1]
    if filter_size is None:
        raise ValueError("filter_size required (output_size-only form: "
                         "provide filter_size for the TPU build)")
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = helper.create_parameter(
        helper.param_attr, [c_in, num_filters // groups, fs[0], fs[1]],
        input.dtype)
    out = apply_op_layer('conv2d_transpose', {'x': input, 'weight': w},
                         {'stride': stride, 'padding': padding,
                          'dilation': dilation, 'groups': groups})
    b = helper.create_parameter(helper.bias_attr, [num_filters], 'float32',
                                is_bias=True)
    if b is not None:
        out = apply_op_layer('elementwise_add', {'x': out, 'y': b}, {'axis': 1})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper('conv3d_transpose', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c_in = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = helper.create_parameter(
        helper.param_attr, [c_in, num_filters // groups, *fs], input.dtype)
    out = apply_op_layer('conv3d_transpose', {'x': input, 'weight': w},
                         {'stride': stride, 'padding': padding,
                          'dilation': dilation, 'groups': groups})
    b = helper.create_parameter(helper.bias_attr, [num_filters], 'float32',
                                is_bias=True)
    if b is not None:
        out = apply_op_layer('elementwise_add', {'x': out, 'y': b}, {'axis': 1})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def pool2d(input, pool_size=-1, pool_type='max', pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, name=None,
           exclusive=True, data_format='NCHW'):
    return apply_op_layer('pool2d', {'x': input},
                          {'pool_size': pool_size, 'pool_type': pool_type,
                           'pool_stride': pool_stride,
                           'pool_padding': pool_padding,
                           'global_pooling': global_pooling,
                           'ceil_mode': ceil_mode, 'exclusive': exclusive,
                           'data_format': data_format}, name=name)


def pool3d(input, pool_size=-1, pool_type='max', pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, name=None,
           exclusive=True, data_format='NCDHW'):
    return apply_op_layer('pool3d', {'x': input},
                          {'pool_size': pool_size, 'pool_type': pool_type,
                           'pool_stride': pool_stride,
                           'pool_padding': pool_padding,
                           'global_pooling': global_pooling,
                           'ceil_mode': ceil_mode, 'exclusive': exclusive,
                           'data_format': data_format}, name=name)


def adaptive_pool2d(input, pool_size, pool_type='max', require_index=False,
                    name=None):
    return apply_op_layer('adaptive_pool2d', {'x': input},
                          {'pool_size': pool_size, 'pool_type': pool_type},
                          name=name)


def adaptive_pool3d(input, pool_size, pool_type='max', require_index=False,
                    name=None):
    return apply_op_layer('adaptive_pool3d', {'x': input},
                          {'pool_size': pool_size, 'pool_type': pool_type},
                          name=name)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False, sync_stats=False):
    """ref: layers/nn.py:batch_norm. Running stats are persistable vars whose
    MeanOut/VarianceOut aliases make the jitted step update them functionally.

    `sync_stats` (ref: layers/nn.py sync_batch_norm / the fleet
    sync_batch_norm build knob): normalize with batch statistics reduced
    over the partitioner's data axes, so a data-parallel fleet sees
    GLOBAL-batch mean/variance — the large-batch BN ingredient
    (docs/DISTRIBUTED.md "Sync-BN")."""
    helper = LayerHelper('batch_norm', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    dtype = 'float32'
    scale = helper.create_parameter(
        helper.param_attr, [c], dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr, [c], dtype, is_bias=True)
    from ..core import unique_name
    mean_name = moving_mean_name or unique_name.generate(helper.name + '.mean')
    var_name = moving_variance_name or unique_name.generate(helper.name + '.variance')

    def stat_var(nm, init_val):
        v = helper.main_program.global_block().create_var(
            name=nm, shape=[c], dtype=dtype, persistable=True,
            stop_gradient=True)
        sb = helper.startup_program.global_block()
        sv = sb.create_var(name=nm, shape=[c], dtype=dtype, persistable=True,
                           stop_gradient=True)
        ConstantInitializer(init_val)(sv, sb)
        return v

    mean = stat_var(mean_name, 0.0)
    variance = stat_var(var_name, 1.0)
    if in_dygraph_mode():
        raise RuntimeError("use dygraph.BatchNorm in imperative mode")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type='batch_norm',
        inputs={'x': input.name, 'scale': scale.name, 'bias': bias.name,
                'mean': mean.name, 'variance': variance.name},
        outputs={'Y': out.name, 'MeanOut': mean.name,
                 'VarianceOut': var_name},
        attrs={'momentum': momentum, 'epsilon': epsilon, 'is_test': is_test,
               'use_global_stats': use_global_stats,
               'data_layout': data_layout, 'sync_stats': sync_stats})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper('layer_norm', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    import math
    nshape = [math.prod(input.shape[begin_norm_axis:])]
    s = helper.create_parameter(
        helper.param_attr, nshape, input.dtype,
        default_initializer=ConstantInitializer(1.0)) if scale else None
    b = helper.create_parameter(helper.bias_attr, nshape, input.dtype,
                                is_bias=True) if shift else None
    out = apply_op_layer('layer_norm', {'x': input, 'scale': s, 'bias': b},
                         {'begin_norm_axis': begin_norm_axis,
                          'epsilon': epsilon})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper('instance_norm', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    c = input.shape[1]
    s = helper.create_parameter(helper.param_attr, [c], input.dtype,
                                default_initializer=ConstantInitializer(1.0))
    b = helper.create_parameter(helper.bias_attr, [c], input.dtype,
                                is_bias=True)
    return apply_op_layer('instance_norm', {'x': input, 'scale': s, 'bias': b},
                          {'epsilon': epsilon})


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout='NCHW', name=None):
    helper = LayerHelper('group_norm', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    s = helper.create_parameter(helper.param_attr, [c], input.dtype,
                                default_initializer=ConstantInitializer(1.0))
    b = helper.create_parameter(helper.bias_attr, [c], input.dtype,
                                is_bias=True)
    out = apply_op_layer('group_norm', {'x': input, 'scale': s, 'bias': b},
                         {'groups': groups, 'epsilon': epsilon,
                          'data_layout': data_layout})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """ref: layers/nn.py:spectral_norm — power iteration inlined in the graph
    (u/v vectors are persistable state in the ref; here re-estimated per step,
    which matches power_iters semantics under jit)."""
    return apply_op_layer('spectral_norm', {'w': weight},
                          {'dim': dim, 'power_iters': power_iters, 'eps': eps},
                          name=name)


def data_norm(input, act=None, epsilon=1e-4, param_attr=None, name=None,
              data_layout='NCHW', in_place=False, do_model_average_for_mean_and_var=True):
    helper = LayerHelper('data_norm', name=name)
    c = input.shape[-1]
    from ..core import unique_name

    def stat(nm, val):
        full = unique_name.generate(helper.name + '.' + nm)
        v = helper.main_program.global_block().create_var(
            name=full, shape=[c] if nm != 'batch_size' else [c], dtype='float32',
            persistable=True, stop_gradient=True)
        sb = helper.startup_program.global_block()
        sv = sb.create_var(name=full, shape=[c], dtype='float32',
                           persistable=True, stop_gradient=True)
        ConstantInitializer(val)(sv, sb)
        return v

    bsize = stat('batch_size', 1e4)
    bsum = stat('batch_sum', 0.0)
    bsq = stat('batch_square_sum', 1e4)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type='data_norm',
        inputs={'x': input.name, 'batch_size': bsize.name,
                'batch_sum': bsum.name, 'batch_square_sum': bsq.name},
        outputs={'Y': out.name, 'BatchSizeOut': bsize.name,
                 'BatchSumOut': bsum.name, 'BatchSquareSumOut': bsq.name},
        attrs={'epsilon': epsilon})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation='downgrade_in_infer'):
    return apply_op_layer('dropout', {'x': x},
                          {'dropout_prob': dropout_prob, 'is_test': is_test,
                           'dropout_implementation': dropout_implementation},
                          name=name)


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return apply_op_layer('softmax', {'x': input}, {'axis': axis}, name=name)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    return apply_op_layer('matmul', {'x': x, 'y': y},
                          {'transpose_x': transpose_x,
                           'transpose_y': transpose_y, 'alpha': alpha},
                          name=name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return apply_op_layer('mul', {'x': x, 'y': y},
                          {'x_num_col_dims': x_num_col_dims,
                           'y_num_col_dims': y_num_col_dims}, name=name)


def topk(input, k, name=None):
    return apply_op_layer('top_k', {'x': input}, {'k': k}, name=name)


def one_hot(input, depth, allow_out_of_range=False):
    return apply_op_layer('one_hot', {'x': input},
                          {'depth': depth,
                           'allow_out_of_range': allow_out_of_range})


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper('prelu', param_attr=param_attr, name=name)
    if mode == 'all':
        shape = [1]
    elif mode == 'channel':
        shape = [x.shape[1]]
    else:
        import math
        shape = [math.prod(x.shape[1:])]
    alpha = helper.create_parameter(
        helper.param_attr, shape, x.dtype,
        default_initializer=ConstantInitializer(0.25))
    return apply_op_layer('prelu', {'x': x, 'alpha': alpha}, {'mode': mode})


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler='uniform',
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation (ref: layers/nn.py:nce). TPU formulation:
    samples drawn inside the jitted step via the op's PRNG key."""
    helper = LayerHelper('nce', param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(helper.param_attr, [num_total_classes, dim],
                                input.dtype)
    b = helper.create_parameter(helper.bias_attr, [num_total_classes],
                                input.dtype, is_bias=True)
    return apply_op_layer('nce',
                          {'x': input, 'label': label, 'weight': w, 'bias': b},
                          {'num_total_classes': num_total_classes,
                           'num_neg_samples': num_neg_samples or 10})


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return apply_op_layer('l2_normalize', {'x': x},
                          {'axis': axis, 'epsilon': epsilon}, name=name)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    return apply_op_layer('im2sequence', {'x': input},
                          {'filter_size': filter_size, 'stride': stride,
                           'padding': padding}, name=name)


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper('row_conv', param_attr=param_attr, act=act)
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                [future_context_size + 1, d], input.dtype)
    out = apply_op_layer('row_conv', {'x': input, 'w': w})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


def multiplex(inputs, index):
    return apply_op_layer('multiplex', {'index': index, 'xs': list(inputs)})


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    return apply_op_layer('smooth_l1_loss',
                          {'x': x, 'y': y, 'inside_weight': inside_weight,
                           'outside_weight': outside_weight},
                          {'sigma': sigma if sigma is not None else 1.0})


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """ref: layers/nn.py:autoincreased_step_counter — a persistable int64
    counter bumped by an increment op each step (drives LR schedules)."""
    helper = LayerHelper('global_step_counter')
    name = counter_name or '@STEP_COUNTER@'
    block = helper.main_program.global_block()
    if block.has_var(name):
        return block.var(name)
    counter = block.create_var(name=name, shape=[1], dtype='int64',
                               persistable=True, stop_gradient=True)
    sb = helper.startup_program.global_block()
    sv = sb.create_var(name=name, shape=[1], dtype='int64', persistable=True,
                       stop_gradient=True)
    ConstantInitializer(begin - step)(sv, sb)
    helper.main_program.global_block().prepend_op(
        type='increment', inputs={'x': name}, outputs={'Out': name},
        attrs={'value': float(step)})
    return counter


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    helper = LayerHelper('bilinear_tensor_product', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    w = helper.create_parameter(helper.param_attr,
                                [size, x.shape[-1], y.shape[-1]], x.dtype)
    b = helper.create_parameter(helper.bias_attr, [size], x.dtype, is_bias=True)
    out = apply_op_layer('bilinear_tensor_product',
                         {'x': x, 'y': y, 'weight': w, 'bias': b})
    if act:
        out = apply_op_layer(act, {'x': out})
    return out


# ---------------------------------------------------------------------------
# thin generated wrappers (attr names match the reference layer signatures)
# ---------------------------------------------------------------------------

def _gen(op_type, *, fname=None, slots=None):
    fn = generate_layer_fn(op_type, in_slots=slots)
    fn.__name__ = fname or op_type
    globals()[fn.__name__] = fn
    __all__.append(fn.__name__)
    return fn


for _op in ['sigmoid', 'logsigmoid', 'exp', 'tanh', 'atan', 'tanh_shrink',
            'sqrt', 'rsqrt', 'abs', 'ceil', 'floor', 'cos', 'sin', 'acos',
            'asin', 'cosh', 'sinh', 'round', 'reciprocal', 'square',
            'softplus', 'softsign', 'softshrink', 'hard_shrink',
            'thresholded_relu', 'log_softmax',
            'relu', 'relu6', 'leaky_relu', 'elu', 'selu', 'brelu', 'soft_relu',
            'stanh', 'hard_sigmoid', 'hard_swish', 'swish', 'maxout', 'pow',
            'gelu', 'erf', 'log', 'sign', 'mean',
            'reduce_sum', 'reduce_mean', 'reduce_max', 'reduce_min',
            'reduce_prod', 'reduce_all', 'reduce_any', 'logsumexp',
            'elementwise_add', 'elementwise_sub', 'elementwise_mul',
            'elementwise_div', 'elementwise_max', 'elementwise_min',
            'elementwise_pow', 'elementwise_mod', 'elementwise_floordiv',
            'scale', 'clip', 'clip_by_norm', 'cos_sim',
            'transpose', 'squeeze', 'unsqueeze', 'reshape', 'flatten',
            'gather', 'gather_nd', 'scatter', 'scatter_nd_add',
            'expand', 'expand_as', 'pad', 'pad2d', 'pad_constant_like',
            'label_smooth', 'shard_index', 'where',
            'space_to_depth', 'shuffle_channel', 'temporal_shift',
            'grid_sampler', 'affine_channel', 'pixel_shuffle', 'unfold',
            'add_position_encoding', 'log_loss', 'unstack',
            'uniform_random', 'gaussian_random',
            'uniform_random_batch_size_like', 'gaussian_random_batch_size_like',
            'sampling_id', 'random_crop',
            'logical_and', 'logical_or', 'logical_xor', 'logical_not',
            'has_inf', 'has_nan', 'isfinite', 'mean_iou', 'cumsum']:
    _gen(_op)

_gen('slice', fname='slice')
_gen('strided_slice', fname='strided_slice')
_gen('fsp', fname='fsp_matrix')
_gen('arg_min', fname='argmin')
_gen('arg_max', fname='argmax')
_gen('argsort', fname='argsort')


def split(input, num_or_sections, dim=-1, name=None):
    n = num_or_sections if isinstance(num_or_sections, int) \
        else len(num_or_sections)
    helper_out = apply_op_layer('split', {'x': input},
                                {'num_or_sections': num_or_sections,
                                 'dim': dim}, name=name,
                                n_outputs={'Out': n})
    return helper_out if isinstance(helper_out, list) else helper_out


def stack(x, axis=0):
    return apply_op_layer('stack', {'xs': list(x)}, {'axis': axis})


def concat(input, axis=0, name=None):
    return apply_op_layer('concat', {'xs': list(input)}, {'axis': axis},
                          name=name)


def affine_grid(theta, out_shape, name=None):
    return apply_op_layer('affine_grid', {'theta': theta},
                          {'out_shape': list(out_shape)}, name=name)


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample='BILINEAR', actual_shape=None, align_corners=True,
                 align_mode=1, data_format='NCHW'):
    if out_shape is None:
        h = int(input.shape[2] * scale)
        w = int(input.shape[3] * scale)
        out_shape = [h, w]
    method = resample.lower()
    return apply_op_layer('interpolate', {'x': input},
                          {'out_shape': list(out_shape), 'method': method,
                           'align_corners': align_corners,
                           'align_mode': align_mode,
                           'data_format': data_format}, name=name)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format='NCHW'):
    return image_resize(input, out_shape, scale, name, 'BILINEAR',
                        actual_shape, align_corners, align_mode, data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True, data_format='NCHW'):
    return image_resize(input, out_shape, scale, name, 'NEAREST',
                        actual_shape, align_corners, 1, data_format)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format='NCDHW'):
    return image_resize(input, out_shape, scale, name, 'BILINEAR',
                        actual_shape, align_corners, align_mode, data_format)


def image_resize_short(input, out_short_len, resample='BILINEAR'):
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    scale = out_short_len / short
    return image_resize(input, [int(h * scale), int(w * scale)],
                        resample=resample)


def crop(x, shape=None, offsets=None, name=None):
    return apply_op_layer('crop_tensor', {'x': x},
                          {'shape': list(shape), 'offsets': offsets},
                          name=name)


crop_tensor = crop


def unique(x, dtype='int32'):
    out = apply_op_layer('unique_with_counts', {'x': x}, {'dtype': dtype})
    return out[0], out[1]


def unique_with_counts(x, dtype='int32'):
    return apply_op_layer('unique_with_counts', {'x': x}, {'dtype': dtype})


__all__ += ['fc', 'embedding', 'conv2d', 'conv3d', 'conv2d_transpose',
            'conv3d_transpose', 'pool2d', 'pool3d', 'adaptive_pool2d',
            'adaptive_pool3d', 'batch_norm', 'layer_norm', 'instance_norm',
            'group_norm', 'spectral_norm', 'data_norm', 'dropout', 'softmax',
            'matmul', 'mul', 'topk', 'one_hot', 'prelu', 'nce', 'l2_normalize',
            'im2sequence', 'row_conv', 'multiplex', 'smooth_l1',
            'autoincreased_step_counter', 'bilinear_tensor_product', 'split',
            'stack', 'concat', 'affine_grid', 'image_resize', 'resize_bilinear',
            'resize_nearest', 'resize_trilinear', 'image_resize_short', 'crop',
            'crop_tensor', 'unique', 'unique_with_counts']


# ---------------------------------------------------------------------------
# long-tail nn layers (SURVEY §2.2/§2.3 gap fill)
# ---------------------------------------------------------------------------


def linear_chain_crf(input, label, param_attr=None, length=None):
    """ref: layers/nn.py:linear_chain_crf. Creates the (N+2, N) transition
    parameter (rows 0/1 = start/stop) and returns the per-sequence NLL."""
    helper = LayerHelper('linear_chain_crf', param_attr=param_attr)
    n = input.shape[-1]
    transition = helper.create_parameter(helper.param_attr, [n + 2, n],
                                         input.dtype)
    nll, _, _, _ = apply_op_layer(
        'linear_chain_crf',
        {'emission': input, 'transition': transition, 'label': label,
         'length': length})
    return nll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with the transition param created by linear_chain_crf.
    `param_attr` may be the ParamAttr (looked up by name) or the variable."""
    from ..framework import Variable as _V
    if isinstance(param_attr, _V):
        transition = param_attr
    else:
        name = param_attr.name if hasattr(param_attr, 'name') else param_attr
        transition = helper_block_var(name)
    return apply_op_layer('crf_decoding',
                          {'emission': input, 'transition': transition,
                           'length': length})


def helper_block_var(name):
    from ..framework import default_main_program
    return default_main_program().global_block().var(name)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    return apply_op_layer(
        'chunk_eval',
        {'inference': input, 'label': label, 'length': seq_length},
        {'num_chunk_types': num_chunk_types, 'chunk_scheme': chunk_scheme,
         'excluded_chunk_types': list(excluded_chunk_types or [])})


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=-1,
                       name=None):
    out, lens = apply_op_layer('ctc_greedy_decoder',
                               {'x': input, 'length': input_length},
                               {'blank': blank,
                                'padding_value': padding_value}, name=name)
    if input_length is None:
        return out
    return out, lens


def lod_reset(x, y=None, target_lod=None):
    """TPU formulation: returns the data with a fresh (B,) `sequence_length`
    attribute (offsets→lengths) that sequence layers pick up implicitly."""
    out, lens = apply_op_layer('lod_reset', {'x': x, 'y': y},
                               {'target_lod': target_lod})
    out.sequence_length = lens
    return out


def lod_append(x, level):
    return lod_reset(x, target_lod=level if isinstance(level, (list, tuple))
                     else None, y=None if isinstance(level, (list, tuple))
                     else level)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format='NCHW'):
    if data_format == 'NHWC':   # op normalizes across dim 1 (channels)
        input = transpose(input, perm=[0, 3, 1, 2])
    out = apply_op_layer('lrn', {'x': input},
                         {'n': n, 'k': k, 'alpha': alpha, 'beta': beta},
                         name=name)
    if data_format == 'NHWC':
        out = transpose(out, perm=[0, 2, 3, 1])
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_num=None, batch_ids=None, name=None):
    out, _ = apply_op_layer('roi_pool',
                            {'x': input, 'rois': rois,
                             'batch_ids': batch_ids},
                            {'pooled_height': pooled_height,
                             'pooled_width': pooled_width,
                             'spatial_scale': spatial_scale}, name=name)
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              batch_ids=None, name=None):
    return apply_op_layer('roi_align',
                          {'x': input, 'rois': rois, 'batch_ids': batch_ids},
                          {'pooled_height': pooled_height,
                           'pooled_width': pooled_width,
                           'spatial_scale': spatial_scale,
                           'sampling_ratio': sampling_ratio}, name=name)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, batch_ids=None, name=None):
    return apply_op_layer('psroi_pool',
                          {'x': input, 'rois': rois, 'batch_ids': batch_ids},
                          {'output_channels': output_channels,
                           'spatial_scale': spatial_scale,
                           'pooled_height': pooled_height,
                           'pooled_width': pooled_width}, name=name)


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, batch_ids=None,
               name=None):
    return apply_op_layer('prroi_pool',
                          {'x': input, 'rois': rois, 'batch_ids': batch_ids},
                          {'spatial_scale': spatial_scale,
                           'pooled_height': pooled_height,
                           'pooled_width': pooled_width}, name=name)


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=1, deformable_groups=1,
                    im2col_step=1, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """ref: layers/nn.py:deformable_conv (v2 when modulated, v1 otherwise)."""
    helper = LayerHelper('deformable_conv', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    c_in = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = helper.create_parameter(helper.param_attr,
                                [num_filters, c_in // groups, fs[0], fs[1]],
                                input.dtype)
    out = apply_op_layer(
        'deformable_conv',
        {'x': input, 'offset': offset, 'mask': mask, 'weight': w},
        {'stride': stride, 'padding': padding, 'dilation': dilation,
         'groups': groups, 'deformable_groups': deformable_groups,
         'im2col_step': im2col_step, 'modulated': modulated})
    b = helper.create_parameter(helper.bias_attr, [num_filters],
                                input.dtype, is_bias=True)
    if b is not None:
        out = apply_op_layer('elementwise_add', {'x': out, 'y': b},
                             {'axis': 1})
    return out


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=[1, 1],
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           batch_ids=None, name=None):
    oc = input.shape[1] if not position_sensitive \
        else input.shape[1] // (pooled_height * pooled_width)
    ps = part_size[0] if isinstance(part_size, (list, tuple)) else part_size
    return apply_op_layer(
        'deformable_roi_pooling',
        {'x': input, 'rois': rois, 'trans': trans, 'batch_ids': batch_ids},
        {'no_trans': no_trans, 'spatial_scale': spatial_scale,
         'output_channels': oc,
         'group_size': group_size[0] if isinstance(group_size, (list, tuple))
         else group_size,
         'pooled_height': pooled_height, 'pooled_width': pooled_width,
         'part_size': ps, 'sample_per_part': sample_per_part,
         'trans_std': trans_std}, name=name)


def scatter_nd(index, updates, shape, name=None):
    return apply_op_layer('scatter_nd', {'index': index, 'updates': updates},
                          {'shape': list(shape)}, name=name)


def sum(x):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return apply_op_layer('sum', {'xs': list(xs)})


def shape(input):
    return apply_op_layer('shape', {'x': input}, dtype='int32')


def rank(input):
    return apply_op_layer('rank', {'x': input}, dtype='int32')


def size(input):
    return apply_op_layer('size', {'x': input}, dtype='int64')


def similarity_focus(input, axis, indexes, name=None):
    return apply_op_layer('similarity_focus', {'x': input},
                          {'axis': axis, 'indexes': list(indexes)}, name=name)


def hash(input, hash_size, num_hash=1, name=None):
    return apply_op_layer('hash', {'x': input},
                          {'num_hash': num_hash, 'mod_by': hash_size},
                          name=name, dtype='int64')


def merge_selected_rows(x, name=None):
    return apply_op_layer('merge_selected_rows', {'x': x}, name=name)


def get_tensor_from_selected_rows(x, name=None):
    return apply_op_layer('get_tensor_from_selected_rows', {'x': x},
                          name=name)


def continuous_value_model(input, cvm, use_cvm=True):
    return apply_op_layer('cvm', {'x': input, 'cvm_in': cvm},
                          {'use_cvm': use_cvm})


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=False,
                     out_val_if_empty=0):
    return apply_op_layer('filter_by_instag',
                          {'x': ins, 'ins_tag': ins_tag,
                           'filter_tag': filter_tag},
                          {'is_lod': is_lod,
                           'out_val_if_empty': out_val_if_empty})


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python escape hatch (ref: layers/nn.py:py_func). The callable
    runs via jax.pure_callback inside the compiled step; `out` var(s) you
    pre-create via create_variable define the result shapes/dtypes.
    backward_func is accepted for API parity; gradients stop at the callback
    (register a custom op via ops.custom_op for differentiable extensions)."""
    from ..ops.registry import has_op, register_op as _reg
    import jax as _jax
    import jax.numpy as _jnp
    import numpy as _np
    from ..core.dtypes import to_jax_dtype

    from ..core import unique_name
    outs = out if isinstance(out, (list, tuple)) else [out]
    xs = x if isinstance(x, (list, tuple)) else [x]
    shapes = [tuple(int(d) for d in o.shape) for o in outs]
    dtypes = [to_jax_dtype(o.dtype) for o in outs]
    op_name = unique_name.generate('py_func')

    def _kernel(*arrays):
        res = _jax.pure_callback(
            lambda *a: tuple(_np.asarray(r, dt) for r, dt in
                             zip(_as_tuple(func(*a)), dtypes)),
            tuple(_jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes)),
            *arrays)
        res = tuple(_jax.lax.stop_gradient(r) for r in res)
        return res if len(res) > 1 else res[0]

    _kernel.__name__ = op_name
    _reg(op_name, outputs=['Out'] if len(outs) == 1 else
         [f'Out{i}' for i in range(len(outs))])(
        _fix_positional(_kernel, len(xs)))
    helper = LayerHelper('py_func')
    helper.append_op(type=op_name,
                     inputs={f'x{i}': v.name for i, v in enumerate(xs)},
                     outputs=({'Out': [o.name for o in outs]}
                              if len(outs) == 1 else
                              {f'Out{i}': [o.name] for i, o in
                               enumerate(outs)}),
                     attrs={})
    return out


def _as_tuple(r):
    return r if isinstance(r, tuple) else (r,)


def _fix_positional(kernel, n):
    """Give the registry an n-positional-arg signature to map input slots."""
    import inspect
    params = [inspect.Parameter(f'x{i}', inspect.Parameter.POSITIONAL_OR_KEYWORD)
              for i in range(n)]
    kernel.__signature__ = inspect.Signature(params)
    return kernel


__all__ += ['linear_chain_crf', 'crf_decoding', 'chunk_eval',
            'ctc_greedy_decoder', 'lod_reset', 'lod_append', 'lrn',
            'roi_pool', 'roi_align', 'psroi_pool', 'prroi_pool',
            'deformable_conv', 'deformable_roi_pooling', 'scatter_nd', 'sum',
            'shape', 'rank', 'size', 'similarity_focus', 'hash',
            'merge_selected_rows', 'get_tensor_from_selected_rows',
            'continuous_value_model', 'filter_by_instag', 'py_func']
