"""RNN layers: cells, rnn(), dynamic_lstm/gru, beam search decoding.

Parity with reference python/paddle/fluid/layers/rnn.py (RNNCell/GRUCell/
LSTMCell, rnn, BeamSearchDecoder, dynamic_decode) and the dynamic_lstm(p)/
dynamic_gru layers of layers/nn.py — redesigned for TPU:

- whole-sequence recurrences (dynamic_lstm/gru) are ONE registered scan op
  (ops/rnn_ops.py) over padded (B, T, ...) batches with a length mask, not
  per-timestep kernels over LoD batches;
- rnn(cell, ...) captures the cell step as a StaticRNN sub-block → lax.scan;
- dynamic_decode runs a FIXED max_step_num scan with a finished mask (static
  trip count — the TPU design rule), then backtraces with the gather_tree op.
"""
from __future__ import annotations

import numpy as np

from ..framework import Variable, in_dygraph_mode
from ..layer_helper import LayerHelper
from ..initializer import XavierInitializer, ConstantInitializer
from .common import apply_op_layer
from .control_flow import StaticRNN
from . import tensor as tensor_layers
from . import nn as nn_layers

__all__ = ['RNNCell', 'GRUCell', 'LSTMCell', 'rnn', 'birnn', 'dynamic_lstm',
           'dynamic_lstmp', 'dynamic_gru', 'gru_unit', 'lstm_unit',
           'BeamSearchDecoder', 'dynamic_decode', 'beam_search',
           'beam_search_decode', 'gather_tree']


from .control_flow import _flatten, _pack_like as _pack


from .utils import map_structure as _map_structure


class RNNCell:
    """ref: layers/rnn.py:33 RNNCell — single-step recurrence unit usable with
    rnn() and dynamic_decode."""

    def call(self, inputs, states, **kwargs):
        raise NotImplementedError

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)

    @property
    def state_shape(self):
        raise NotImplementedError

    def get_initial_states(self, batch_ref, shape=None, dtype='float32',
                           init_value=0.0, batch_dim_idx=0):
        shape = shape if shape is not None else self.state_shape

        def is_shape(s):
            return isinstance(s, (list, tuple)) and \
                all(isinstance(e, int) for e in s)

        def mk(s):
            full = [-1] + list(s)
            return tensor_layers.fill_constant_batch_size_like(
                batch_ref, full, dtype, float(init_value),
                input_dim_idx=batch_dim_idx)

        def rec(s):
            if is_shape(s):
                return mk(s)
            return type(s)(rec(e) for e in s)

        return rec(shape)


class GRUCell(RNNCell):
    """ref: layers/rnn.py:200 GRUCell (BasicGRUUnit formulation):
    r,u = σ([x,h]Wg + bg); c̃ = tanh([x, r⊙h]Wc + bc); h' = u⊙h + (1-u)⊙c̃."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, dtype='float32',
                 name='GRUCell'):
        self.hidden_size = hidden_size
        self.param_attr = param_attr
        self.bias_attr = bias_attr
        self.gate_act = gate_activation or nn_layers.sigmoid
        self.act = activation or nn_layers.tanh
        self.dtype = dtype
        self.name = name
        self._built = False

    def _build(self, input_size):
        helper = LayerHelper(self.name, param_attr=self.param_attr,
                             bias_attr=self.bias_attr)
        D = self.hidden_size
        self.gate_w = helper.create_parameter(
            helper.param_attr, [input_size + D, 2 * D], self.dtype)
        self.gate_b = helper.create_parameter(
            helper.bias_attr, [2 * D], self.dtype, is_bias=True)
        self.cand_w = helper.create_parameter(
            helper.param_attr, [input_size + D, D], self.dtype)
        self.cand_b = helper.create_parameter(
            helper.bias_attr, [D], self.dtype, is_bias=True)
        self._built = True

    def call(self, inputs, states):
        if not self._built:
            self._build(inputs.shape[-1])
        h = states
        xh = tensor_layers.concat([inputs, h], axis=-1)
        gates = self.gate_act(
            nn_layers.matmul(xh, self.gate_w) + self.gate_b)
        u, r = nn_layers.split(gates, 2, dim=-1)
        xrh = tensor_layers.concat([inputs, r * h], axis=-1)
        c = self.act(nn_layers.matmul(xrh, self.cand_w) + self.cand_b)
        new_h = u * h + (1.0 - u) * c
        return new_h, new_h

    @property
    def state_shape(self):
        return [self.hidden_size]


class LSTMCell(RNNCell):
    """ref: layers/rnn.py:289 LSTMCell (BasicLSTMUnit formulation), gate
    order [i, c̃, f, o] on the fused weight."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, forget_bias=1.0,
                 dtype='float32', name='LSTMCell'):
        self.hidden_size = hidden_size
        self.param_attr = param_attr
        self.bias_attr = bias_attr
        self.gate_act = gate_activation or nn_layers.sigmoid
        self.act = activation or nn_layers.tanh
        self.forget_bias = forget_bias
        self.dtype = dtype
        self.name = name
        self._built = False

    def _build(self, input_size):
        helper = LayerHelper(self.name, param_attr=self.param_attr,
                             bias_attr=self.bias_attr)
        D = self.hidden_size
        self.weight = helper.create_parameter(
            helper.param_attr, [input_size + D, 4 * D], self.dtype)
        self.bias = helper.create_parameter(
            helper.bias_attr, [4 * D], self.dtype, is_bias=True)
        self._built = True

    def call(self, inputs, states):
        if not self._built:
            self._build(inputs.shape[-1])
        pre_h, pre_c = states
        xh = tensor_layers.concat([inputs, pre_h], axis=-1)
        gates = nn_layers.matmul(xh, self.weight) + self.bias
        i, j, f, o = nn_layers.split(gates, 4, dim=-1)
        new_c = pre_c * self.gate_act(f + self.forget_bias) \
            + self.gate_act(i) * self.act(j)
        new_h = self.act(new_c) * self.gate_act(o)
        return new_h, [new_h, new_c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


# ---------------------------------------------------------------------------
# rnn() — run a cell over time (ref: layers/rnn.py:448)
# ---------------------------------------------------------------------------


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Runs `cell` over the time dim of `inputs` (B, T, D) [or (T, B, D) if
    time_major]. Returns (outputs, final_states); padded steps (>= their
    row's sequence_length) carry states through and emit zero outputs."""
    if initial_states is None:
        initial_states = cell.get_initial_states(
            batch_ref=inputs, batch_dim_idx=1 if time_major else 0)

    if in_dygraph_mode():
        return _rnn_dygraph(cell, inputs, initial_states, sequence_length,
                            time_major, is_reverse, **kwargs)

    x = inputs if time_major else nn_layers.transpose(inputs, perm=[1, 0, 2])
    T = x.shape[0]
    if is_reverse:
        x = tensor_layers.reverse(x, axis=[0])
    mask = None
    if sequence_length is not None:
        t_idx = tensor_layers.fill_constant_array(
            np.arange(T).reshape(T, 1).astype(np.int64))
        # (T, 1) < (1, B) → (T, B) validity mask
        from .control_flow import less_than
        mask = less_than(t_idx,
                         nn_layers.reshape(
                             tensor_layers.cast(sequence_length, 'int64'),
                             shape=[1, -1]))
        if is_reverse:
            mask = tensor_layers.reverse(mask, axis=[0])
        mask = tensor_layers.cast(mask, x.dtype)

    srnn = StaticRNN()
    flat_init = _flatten(initial_states)
    out_template = None
    with srnn.step():
        x_t = srnn.step_input(x)
        m_t = srnn.step_input(mask) if mask is not None else None
        pre = [srnn.memory(init=s) for s in flat_init]
        states = _pack(initial_states, pre)
        out, new_states = cell.call(x_t, states, **kwargs)
        out_template = out
        flat_new = _flatten(new_states)
        out_flat = _flatten(out)
        if m_t is not None:
            m_col = nn_layers.reshape(m_t, shape=[-1, 1])
            flat_new = [nw * m_col + pv * (1.0 - m_col)
                        for nw, pv in zip(flat_new, pre)]
            out_flat = [o * m_col for o in out_flat]
        for pv, nw in zip(pre, flat_new):
            srnn.update_memory(pv, nw)
        for o in out_flat + flat_new:
            srnn.step_output(o)
    res = srnn()
    res = res if isinstance(res, list) else [res]
    n_states = len(flat_init)
    outs_seq, states_seq = res[:len(res) - n_states], res[len(res) - n_states:]
    # final states: masking already carried last-valid values to step T-1
    final_flat = [nn_layers.reshape(
        nn_layers.slice(s, axes=[0], starts=[T - 1], ends=[T]),
        shape=list(s.shape[1:])) for s in states_seq]
    final_states = _pack(initial_states, final_flat)
    if is_reverse:
        outs_seq = [tensor_layers.reverse(o, axis=[0]) for o in outs_seq]
    if not time_major:
        outs_seq = [nn_layers.transpose(
            o, perm=[1, 0] + list(range(2, len(o.shape)))) for o in outs_seq]
    outputs = _pack(out_template, outs_seq)
    return outputs, final_states


def _rnn_dygraph(cell, inputs, initial_states, sequence_length, time_major,
                 is_reverse, **kwargs):
    axis_t = 0 if time_major else 1
    T = inputs.shape[axis_t]
    states = initial_states
    outs = []
    steps = range(T - 1, -1, -1) if is_reverse else range(T)
    lens = sequence_length.numpy() if sequence_length is not None else None
    for t in steps:
        x_t = inputs[t] if time_major else inputs[:, t]
        out, new_states = cell.call(x_t, states, **kwargs)
        if lens is not None:
            m = (t < lens).astype('float32').reshape(-1, 1)
            from ..dygraph.tape import Tensor
            m_t = Tensor(m, stop_gradient=True)
            new_flat = [nw * m_t + pv * (1.0 - m_t) for nw, pv in
                        zip(_flatten(new_states), _flatten(states))]
            new_states = _pack(states, new_flat)
            out = _map_structure(lambda o: o * m_t, out)
        states = new_states
        outs.append(out)
    if is_reverse:
        outs = outs[::-1]
    stacked = _map_structure(
        lambda *os: nn_layers.stack(list(os), axis=axis_t), *outs)
    return stacked, states


def birnn(cell_fw, cell_bw, inputs, initial_states=None, sequence_length=None,
          time_major=False, **kwargs):
    """Bidirectional rnn: concat of forward and reverse passes."""
    states_fw, states_bw = (initial_states if initial_states is not None
                            else (None, None))
    out_fw, st_fw = rnn(cell_fw, inputs, states_fw, sequence_length,
                        time_major=time_major, **kwargs)
    out_bw, st_bw = rnn(cell_bw, inputs, states_bw, sequence_length,
                        time_major=time_major, is_reverse=True, **kwargs)
    outputs = tensor_layers.concat([out_fw, out_bw], axis=-1)
    return outputs, (st_fw, st_bw)


# ---------------------------------------------------------------------------
# dynamic_lstm / dynamic_lstmp / dynamic_gru (ref: layers/nn.py dynamic_lstm)
# — padded-batch scan ops; `sequence_length` replaces the reference's LoD
# ---------------------------------------------------------------------------


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation='sigmoid', cell_activation='tanh',
                 candidate_activation='tanh', dtype='float32', name=None,
                 sequence_length=None):
    """input: (B, T, 4*hidden) pre-projected (as in the reference, the x
    projection is an outside fc); returns (hidden (B,T,D), cell (B,T,D))."""
    helper = LayerHelper('dynamic_lstm', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = size // 4
    w = helper.create_parameter(helper.param_attr, [D, 4 * D], dtype)
    b = helper.create_parameter(helper.bias_attr, [4 * D], dtype, is_bias=True)
    peep = helper.create_parameter(
        helper.bias_attr, [3 * D], dtype, is_bias=True) if use_peepholes \
        else None
    h, c = apply_op_layer(
        'lstm',
        {'x': input, 'h0': h_0, 'c0': c_0, 'w_h': w, 'bias': b,
         'peephole': peep, 'seq_len': sequence_length},
        {'use_peepholes': use_peepholes, 'is_reverse': is_reverse,
         'gate_activation': gate_activation,
         'cell_activation': cell_activation,
         'candidate_activation': candidate_activation})
    return h, c


def dynamic_lstmp(input, size, proj_size, h_0=None, c_0=None, param_attr=None,
                  bias_attr=None, use_peepholes=True, is_reverse=False,
                  gate_activation='sigmoid', cell_activation='tanh',
                  candidate_activation='tanh', proj_activation='tanh',
                  dtype='float32', name=None, sequence_length=None):
    """LSTM with recurrent projection (ref: layers/nn.py dynamic_lstmp)."""
    helper = LayerHelper('dynamic_lstmp', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = size // 4
    w = helper.create_parameter(helper.param_attr, [proj_size, 4 * D], dtype)
    proj_w = helper.create_parameter(helper.param_attr, [D, proj_size], dtype)
    b = helper.create_parameter(helper.bias_attr, [4 * D], dtype, is_bias=True)
    peep = helper.create_parameter(
        helper.bias_attr, [3 * D], dtype, is_bias=True) if use_peepholes \
        else None
    h, c = apply_op_layer(
        'lstm',
        {'x': input, 'h0': h_0, 'c0': c_0, 'w_h': w, 'bias': b,
         'peephole': peep, 'seq_len': sequence_length, 'proj_w': proj_w},
        {'use_peepholes': use_peepholes, 'is_reverse': is_reverse,
         'gate_activation': gate_activation,
         'cell_activation': cell_activation,
         'candidate_activation': candidate_activation})
    return h, c


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation='sigmoid',
                candidate_activation='tanh', h_0=None, origin_mode=False,
                dtype='float32', name=None, sequence_length=None):
    """input: (B, T, 3*size) pre-projected; returns hidden (B, T, size)."""
    helper = LayerHelper('dynamic_gru', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = size
    gate_w = helper.create_parameter(helper.param_attr, [D, 2 * D], dtype)
    cand_w = helper.create_parameter(helper.param_attr, [D, D], dtype)
    return apply_op_layer(
        'gru',
        {'x': input, 'h0': h_0, 'gate_w': gate_w, 'cand_w': cand_w,
         'seq_len': sequence_length},
        {'is_reverse': is_reverse, 'gate_activation': gate_activation,
         'candidate_activation': candidate_activation,
         'origin_mode': origin_mode})


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation='tanh', gate_activation='sigmoid',
             origin_mode=False):
    """Single GRU step (ref: layers/nn.py gru_unit): `input` is the
    (B, 3D) projected input (the fc happens outside, as in the
    reference), `hidden` (B, D). Creates the (D, 3D) recurrent weight +
    (3D,) bias; returns (new_hidden, reset_hidden_pre, gate) like the
    reference. activation/gate_activation accept only the reference
    defaults (tanh/sigmoid — what the fused op computes)."""
    if activation != 'tanh' or gate_activation != 'sigmoid':
        raise ValueError('gru_unit supports the reference defaults '
                         "activation='tanh', gate_activation='sigmoid'")
    helper = LayerHelper('gru_unit', param_attr=param_attr,
                         bias_attr=bias_attr)
    D = size // 3
    w = helper.create_parameter(helper.param_attr, [D, 3 * D], 'float32')
    # bias shape [1, 3D] matches the reference layout (rnn.py:2675
    # bias_size = [1, 3 * size]) so exchanged checkpoints pass
    # set_program_state's shape check
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * D], 'float32',
                                   is_bias=True)
    return apply_op_layer(
        'gru_unit',
        {'x': input, 'hidden': hidden, 'weight': w, 'bias': bias},
        {'origin_mode': origin_mode})


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (ref: layers/nn.py lstm_unit): projects
    [x_t, h_prev] through a created (D_in+D, 4D) weight + bias, then runs
    the fused lstm_unit gate op. Returns (new_hidden, new_cell)."""
    helper = LayerHelper('lstm_unit', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = hidden_t_prev.shape[-1]
    in_d = x_t.shape[-1]
    w = helper.create_parameter(helper.param_attr, [in_d + D, 4 * D],
                                'float32')
    b = helper.create_parameter(helper.bias_attr, [4 * D], 'float32',
                                is_bias=True)
    xh = tensor_layers.concat([x_t, hidden_t_prev], axis=1)
    gates = apply_op_layer('elementwise_add',
                           {'x': nn_layers.matmul(xh, w), 'y': b}, {})
    return apply_op_layer(
        'lstm_unit', {'x': gates, 'cell': cell_t_prev},
        {'forget_bias': float(forget_bias)})


# ---------------------------------------------------------------------------
# beam search (ref: layers/rnn.py BeamSearchDecoder + dynamic_decode)
# ---------------------------------------------------------------------------


def gather_tree(ids, parents):
    return apply_op_layer('gather_tree', {'ids': ids, 'parents': parents}, {})


def expand_to_beam(x, beam_size):
    """(B, ...) → (B*W, ...) by tiling each row W times (shared by the
    layers and contrib beam-search decoders)."""
    ex = nn_layers.unsqueeze(x, axes=[1])
    ex = nn_layers.expand(
        ex, expand_times=[1, beam_size] + [1] * (len(x.shape) - 1))
    return nn_layers.reshape(ex, shape=[-1] + list(x.shape[1:]))


class BeamSearchDecoder:
    """ref: layers/rnn.py:758 BeamSearchDecoder. Dense (batch, beam) layout;
    all shapes static; finished beams extend only with end_token."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- beam layout helpers --
    def _merge(self, x):
        """(B, W, ...) → (B*W, ...)"""
        return nn_layers.reshape(x, shape=[-1] + list(x.shape[2:]))

    def _split(self, x, B):
        """(B*W, ...) → (B, W, ...)"""
        return nn_layers.reshape(
            x, shape=[B, self.beam_size] + list(x.shape[1:]))

    def _expand_to_beam(self, x):
        return expand_to_beam(x, self.beam_size)

    def initialize(self, initial_cell_states):
        flat = _flatten(initial_cell_states)
        B = flat[0].shape[0]
        self._batch_size = B
        W = self.beam_size
        cell_states = _pack(initial_cell_states,
                            [self._expand_to_beam(s) for s in flat])
        start_ids = tensor_layers.fill_constant_array(
            np.full((B, W), self.start_token, np.int64))
        inputs = self.embedding_fn(start_ids) if self.embedding_fn \
            else tensor_layers.cast(start_ids, 'float32')
        log_probs = tensor_layers.fill_constant_array(
            np.tile(np.array([0.0] + [-1e9] * (W - 1), np.float32), (B, 1)))
        finished = tensor_layers.fill_constant_array(
            np.zeros((B, W), np.float32))  # float mask: StaticRNN-friendly
        lengths = tensor_layers.fill_constant_array(
            np.zeros((B, W), np.int64))
        return inputs, [cell_states, log_probs, finished, lengths]

    def step(self, time, inputs, states):
        cell_states, log_probs, finished, lengths = states
        B, W = self._batch_size, self.beam_size

        flat_in = self._merge(inputs) if len(inputs.shape) > 2 else inputs
        cell_out, next_cell_states = self.cell.call(flat_in, cell_states)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        V = logits.shape[-1]
        step_lp = apply_op_layer('log_softmax', {'x': logits}, {})  # (B*W, V)
        step_lp = self._split(step_lp, B)                  # (B, W, V)
        # finished beams: only end_token continues, with additive score 0
        noend = np.full((V,), -1e9, np.float32)
        noend[self.end_token] = 0.0
        noend_t = tensor_layers.fill_constant_array(noend.reshape(1, 1, V))
        fin3 = nn_layers.reshape(finished, shape=[B, W, 1])
        step_lp = step_lp * (1.0 - fin3) + noend_t * fin3
        total = nn_layers.reshape(log_probs, shape=[B, W, 1]) + step_lp
        flat_lp = nn_layers.reshape(total, shape=[B, W * V])
        top_scores, top_idx = nn_layers.topk(flat_lp, W)   # (B, W)
        beam_idx = tensor_layers.cast(top_idx, 'int64') // np.int64(V)
        token_ids = tensor_layers.cast(top_idx, 'int64') % np.int64(V)
        # gather along the beam dim: flat index = b*W + beam_idx
        offs = tensor_layers.fill_constant_array(
            (np.arange(B) * W).reshape(B, 1).astype(np.int64))
        flat_sel = nn_layers.reshape(beam_idx + offs, shape=[B * W])

        def sel(x):
            return nn_layers.gather(x, flat_sel)

        next_cell_states = _pack(next_cell_states,
                                 [sel(s) for s in _flatten(next_cell_states)])
        fin_flat = nn_layers.reshape(finished, shape=[B * W])
        len_flat = nn_layers.reshape(lengths, shape=[B * W])
        prev_fin = nn_layers.reshape(sel(fin_flat), shape=[B, W])
        prev_len = nn_layers.reshape(sel(len_flat), shape=[B, W])
        now_end = tensor_layers.cast(
            nn_layers.reshape(token_ids, shape=[B, W]) == np.int64(self.end_token),
            'float32')
        next_finished = nn_layers.elementwise_max(prev_fin, now_end)
        next_lengths = prev_len + tensor_layers.cast(1.0 - prev_fin, 'int64')
        next_inputs = self.embedding_fn(token_ids) if self.embedding_fn \
            else tensor_layers.cast(token_ids, 'float32')
        outputs = [top_scores, token_ids, beam_idx]
        next_states = [next_cell_states, top_scores, next_finished,
                       next_lengths]
        return outputs, next_states, next_inputs, next_finished

    def finalize(self, outputs, final_states, sequence_lengths=None):
        """outputs: [scores (T,B,W), token_ids (T,B,W), parent_ids (T,B,W)]
        → backtraced ids (T, B, W) via gather_tree."""
        scores, token_ids, parent_ids = outputs
        ids = gather_tree(token_ids, parent_ids)
        return ids, scores


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, is_test=True, return_length=False,
                   **kwargs):
    """ref: layers/rnn.py:1462 dynamic_decode. Runs decoder.step for a FIXED
    max_step_num steps (static trip count; finished beams are masked), then
    decoder.finalize backtraces. Returns (outputs, final_states)
    [+ lengths if return_length]."""
    if max_step_num is None:
        max_step_num = 100
    initial_inputs, initial_states = decoder.initialize(inits)

    if in_dygraph_mode():
        return _dynamic_decode_dygraph(decoder, initial_inputs,
                                       initial_states, max_step_num,
                                       output_time_major, return_length)

    times = tensor_layers.fill_constant_array(
        np.arange(max_step_num, dtype=np.int64))
    srnn = StaticRNN()
    flat_init = _flatten([initial_inputs, initial_states])
    with srnn.step():
        t = srnn.step_input(times)
        pre = [srnn.memory(init=s) for s in flat_init]
        inputs, states = _pack([initial_inputs, initial_states], pre)
        outputs, next_states, next_inputs, next_finished = decoder.step(
            t, inputs, states, **kwargs)
        flat_new = _flatten([next_inputs, next_states])
        for pv, nw in zip(pre, flat_new):
            srnn.update_memory(pv, nw)
        for o in _flatten(outputs):
            srnn.step_output(o)
    res = srnn()
    res = res if isinstance(res, list) else [res]
    outputs_seq = _pack(outputs, res)
    final = decoder.finalize(outputs_seq, None) \
        if hasattr(decoder, 'finalize') else (outputs_seq, None)
    a, b = final
    if not output_time_major:
        a = _map_structure(_transpose_batch_time, a)
        b = _map_structure(_transpose_batch_time, b) if b is not None else b
    if return_length:
        return a, b, None
    return a, b


def _transpose_batch_time(x):
    """(T, B, ...) ↔ (B, T, ...); anything rank<2 passes through."""
    if x is None or not hasattr(x, 'name'):
        return x
    if getattr(x, 'shape', None) is not None and len(x.shape) < 2:
        return x
    return apply_op_layer('transpose_batch_time', {'x': x})


def _dynamic_decode_dygraph(decoder, inputs, states, max_step_num,
                            output_time_major, return_length):
    outs_t = []
    finished_np = None
    for t in range(max_step_num):
        from ..dygraph.tape import Tensor
        t_var = Tensor(np.int64(t), stop_gradient=True)
        outputs, states, inputs, finished = decoder.step(t_var, inputs, states)
        outs_t.append(outputs)
        finished_np = finished.numpy()
        if finished_np.min() > 0.5:
            break
    stacked = _map_structure(lambda *os: nn_layers.stack(list(os), axis=0),
                             *outs_t)
    a, b = decoder.finalize(stacked, None) \
        if hasattr(decoder, 'finalize') else (stacked, None)
    if not output_time_major:
        a = _map_structure(_transpose_batch_time, a)
        b = _map_structure(_transpose_batch_time, b) if b is not None else b
    if return_length:
        return a, b, None
    return a, b


# ---------------------------------------------------------------------------
# legacy one-step beam_search API (ref: layers/rnn.py beam_search /
# beam_search_decode over LoD beams) — dense (B*W) formulation
# ---------------------------------------------------------------------------


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One beam-search step over dense (B*W, K) candidates: select top
    beam_size continuations per batch row. Returns (selected_ids,
    selected_scores[, parent_idx])."""
    return apply_op_layer(
        'beam_search_step',
        {'pre_ids': pre_ids, 'pre_scores': pre_scores, 'ids': ids,
         'scores': scores},
        {'beam_size': beam_size, 'end_id': end_id,
         'is_accumulated': is_accumulated,
         'return_parent_idx': return_parent_idx},
        n_outputs=None)


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    """Backtrace accumulated (T, B, W) ids/parents — see gather_tree."""
    return gather_tree(ids, scores)


# ---------------------------------------------------------------------------
# Decoder / DecodeHelper family (ref: layers/rnn.py Decoder, TrainingHelper,
# GreedyEmbeddingHelper, SampleEmbeddingHelper, BasicDecoder)
# ---------------------------------------------------------------------------
import collections


class Decoder:
    """Abstract one-step decoder driven by dynamic_decode."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class DecodeHelper:
    """Samples ids from step outputs and produces the next step's inputs."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


def _gather_time(x_tm, time):
    """x_tm: (T, B, ...) var; time: int scalar var → (B, ...)."""
    idx = nn_layers.reshape(tensor_layers.cast(time, 'int64'), shape=[1])
    step = nn_layers.gather(x_tm, idx)
    return nn_layers.reshape(step, shape=list(x_tm.shape[1:]))


class TrainingHelper(DecodeHelper):
    """Teacher forcing: feeds the ground-truth sequence step by step."""

    def __init__(self, inputs, sequence_length=None, time_major=False):
        self.inputs_tm = inputs if time_major \
            else _transpose_batch_time(inputs)
        self.T = self.inputs_tm.shape[0]
        self.sequence_length = sequence_length

    def initialize(self):
        first = _gather_time(self.inputs_tm,
                             tensor_layers.fill_constant([1], 'int64', 0))
        if self.sequence_length is not None:
            fin = tensor_layers.cast(
                apply_op_layer('less_equal',
                               {'x': self.sequence_length,
                                'y': tensor_layers.fill_constant(
                                    [1], 'int64', 0)}), 'float32')
        else:
            fin = tensor_layers.fill_constant_batch_size_like(
                self.inputs_tm, [-1], 'float32', 0.0, input_dim_idx=1,
                output_dim_idx=0)
        return first, fin

    def sample(self, time, outputs, states):
        return nn_layers.reshape(
            tensor_layers.cast(nn_layers.argmax(outputs, axis=-1), 'int64'),
            shape=[-1])

    def next_inputs(self, time, outputs, states, sample_ids):
        next_time = tensor_layers.cast(time, 'int64') + np.int64(1)
        last = tensor_layers.fill_constant([1], 'int64', self.T - 1)
        clipped = nn_layers.elementwise_min(
            nn_layers.reshape(next_time, shape=[1]), last)
        nxt = _gather_time(self.inputs_tm, clipped)
        if self.sequence_length is not None:
            fin = tensor_layers.cast(
                apply_op_layer(
                    'greater_equal',
                    {'x': nn_layers.reshape(next_time, shape=[1]),
                     'y': tensor_layers.cast(self.sequence_length, 'int64')}),
                'float32')
        else:
            fin = tensor_layers.cast(
                apply_op_layer('greater_equal',
                               {'x': nn_layers.reshape(next_time, shape=[1]),
                                'y': tensor_layers.fill_constant(
                                    [1], 'int64', self.T)}), 'float32')
            ones = tensor_layers.fill_constant_batch_size_like(
                self.inputs_tm, [-1], 'float32', 1.0, input_dim_idx=1,
                output_dim_idx=0)
            fin = ones * fin     # broadcast (B,)·(1,) → per-row mask
        return fin, nxt


class GreedyEmbeddingHelper(DecodeHelper):
    """Greedy generation: argmax id → embedding as the next input."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = start_tokens
        self.end_token = int(end_token)

    def initialize(self):
        fin = tensor_layers.fill_constant_batch_size_like(
            self.start_tokens, [-1], 'float32', 0.0)
        return self.embedding_fn(self.start_tokens), fin

    def sample(self, time, outputs, states):
        return nn_layers.reshape(
            tensor_layers.cast(nn_layers.argmax(outputs, axis=-1), 'int64'),
            shape=[-1])

    def next_inputs(self, time, outputs, states, sample_ids):
        fin = tensor_layers.cast(
            apply_op_layer('equal',
                           {'x': sample_ids,
                            'y': tensor_layers.fill_constant(
                                [1], 'int64', self.end_token)}), 'float32')
        return fin, self.embedding_fn(sample_ids)


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Like GreedyEmbeddingHelper but samples ids from softmax(outputs /
    softmax_temperature)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature
        self.seed = seed

    def sample(self, time, outputs, states):
        logits = outputs if self.temperature is None \
            else outputs / float(self.temperature)
        probs = nn_layers.softmax(logits)
        ids = apply_op_layer('sampling_id', {'x': probs},
                             {'seed': self.seed or 0})
        return nn_layers.reshape(tensor_layers.cast(ids, 'int64'), shape=[-1])


BasicDecoderOutput = collections.namedtuple('BasicDecoderOutput',
                                            ('cell_outputs', 'sample_ids'))


class BasicDecoder(Decoder):
    """cell + helper one-step decoder (ref: layers/rnn.py BasicDecoder)."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        initial_inputs, initial_finished = self.helper.initialize()
        return initial_inputs, [initial_cell_states, initial_finished]

    def step(self, time, inputs, states, **kwargs):
        cell_states, finished = states
        cell_outputs, next_cell_states = self.cell.call(inputs, cell_states)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        sample_ids = self.helper.sample(time, cell_outputs, cell_states)
        helper_fin, next_inputs = self.helper.next_inputs(
            time, cell_outputs, next_cell_states, sample_ids)
        next_finished = nn_layers.elementwise_max(finished, helper_fin)
        outputs = BasicDecoderOutput(cell_outputs, sample_ids)
        return outputs, [next_cell_states, next_finished], next_inputs, \
            next_finished


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Stacked (optionally bidirectional) LSTM over time-major input
    (T, B, D) — the parity surface for the reference's cuDNN lstm op
    (layers/nn.py:lstm); lowered to lax.scan per layer instead of a cuDNN
    descriptor. Returns (out, last_h, last_c) with last_h/c shaped
    (num_layers*directions, B, hidden_size)."""
    x = input
    last_hs, last_cs = [], []
    for layer in range(num_layers):
        if is_bidirec:
            fw = LSTMCell(hidden_size, name=f'{name or "lstm"}_l{layer}_fw')
            bw = LSTMCell(hidden_size, name=f'{name or "lstm"}_l{layer}_bw')
            init = None
            if init_h is not None and init_c is not None:
                i0, i1 = 2 * layer, 2 * layer + 1
                init = ([init_h[i0], init_c[i0]], [init_h[i1], init_c[i1]])
            x, (st_fw, st_bw) = birnn(fw, bw, x, init, time_major=True)
            last_hs += [st_fw[0], st_bw[0]]
            last_cs += [st_fw[1], st_bw[1]]
        else:
            cell = LSTMCell(hidden_size, name=f'{name or "lstm"}_l{layer}')
            init = None
            if init_h is not None and init_c is not None:
                init = [init_h[layer], init_c[layer]]
            x, st = rnn(cell, x, init, time_major=True)
            last_hs.append(st[0])
            last_cs.append(st[1])
        if dropout_prob > 0.0 and not is_test and layer < num_layers - 1:
            x = nn_layers.dropout(x, dropout_prob)
    last_h = nn_layers.stack(last_hs, axis=0)
    last_c = nn_layers.stack(last_cs, axis=0)
    return x, last_h, last_c


__all__ += ['Decoder', 'DecodeHelper', 'TrainingHelper',
            'GreedyEmbeddingHelper', 'SampleEmbeddingHelper', 'BasicDecoder',
            'BasicDecoderOutput', 'lstm']
