"""Static-graph learning-rate schedules (ref: python/paddle/fluid/layers/
learning_rate_scheduler.py).

Each schedule is emitted as ordinary ops over a persistable global step
counter, so the whole schedule fuses into the jitted train step — there is no
host-side LR computation per step (the reference recomputes the LR var with
dedicated ops each `Executor.run` too, but through per-op kernel dispatch).

In dygraph mode every function returns the matching
`dygraph.learning_rate_scheduler` object, mirroring the reference's
`in_dygraph_mode()` branches.
"""
from __future__ import annotations

import math

import numpy as np

from ..framework import in_dygraph_mode
from ..core import unique_name
from .common import op_call as _op
from .tensor import create_global_var, assign, cast, fill_constant
from .control_flow import increment, less_than, greater_equal

__all__ = ['exponential_decay', 'natural_exp_decay', 'inverse_time_decay',
           'polynomial_decay', 'piecewise_decay', 'noam_decay', 'cosine_decay',
           'linear_lr_warmup']


def _decay_step_counter(begin=0):
    """Global step counter var, +1 every executor run (ref: the
    `@LR_DECAY_COUNTER@` autoincreased_step_counter). Integer-typed like the
    reference so long runs never hit float32's 2^24 increment ceiling; cast
    to float32 for the schedule arithmetic."""
    counter = create_global_var(
        [1], begin - 1, 'int64', persistable=True,
        name=unique_name.generate('lr_decay_counter'))
    counter.belong_to_optimizer = True  # io.is_belong_to_optimizer tag
    increment(counter, value=1, in_place=True)
    return cast(counter, 'float32')


def _dygraph_sched(cls, *args, **kwargs):
    from ..dygraph import learning_rate_scheduler as imperate_lr
    return getattr(imperate_lr, cls)(*args, **kwargs)


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr * d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)."""
    if in_dygraph_mode():
        return _dygraph_sched('NoamDecay', d_model, warmup_steps,
                              learning_rate=learning_rate)
    step = _decay_step_counter(begin=1)
    a = _op('pow', x=step, attrs={'factor': -0.5})
    b = (warmup_steps ** -1.5) * step
    lr = learning_rate * (d_model ** -0.5) * _op('elementwise_min', x=a, y=b)
    return lr


def _div_steps(step, decay_steps, staircase):
    div = step / float(decay_steps)
    if staircase:
        div = _op('floor', x=div)
    return div


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    if in_dygraph_mode():
        return _dygraph_sched('ExponentialDecay', learning_rate, decay_steps,
                              decay_rate, staircase)
    step = _decay_step_counter()
    div = _div_steps(step, decay_steps, staircase)
    # decay_rate ** div == exp(div * log(decay_rate))
    return learning_rate * _op('exp', x=div * math.log(decay_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    if in_dygraph_mode():
        return _dygraph_sched('NaturalExpDecay', learning_rate, decay_steps,
                              decay_rate, staircase)
    step = _decay_step_counter()
    div = _div_steps(step, decay_steps, staircase)
    return learning_rate * _op('exp', x=(-1.0 * decay_rate) * div)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    if in_dygraph_mode():
        return _dygraph_sched('InverseTimeDecay', learning_rate, decay_steps,
                              decay_rate, staircase)
    step = _decay_step_counter()
    div = _div_steps(step, decay_steps, staircase)
    return learning_rate / (1.0 + decay_rate * div)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    if in_dygraph_mode():
        return _dygraph_sched('PolynomialDecay', learning_rate, decay_steps,
                              end_learning_rate, power, cycle)
    step = _decay_step_counter()
    if cycle:
        mult = _op('ceil', x=step / float(decay_steps))
        mult = _op('elementwise_max', x=mult,
                   y=fill_constant([1], 'float32', 1.0))
        ds = mult * float(decay_steps)
    else:
        ds = fill_constant([1], 'float32', float(decay_steps))
        step = _op('elementwise_min', x=step, y=ds)
    base = 1.0 - step / ds
    frac = _op('pow', x=base, attrs={'factor': float(power)})
    return (learning_rate - end_learning_rate) * frac + end_learning_rate


def piecewise_decay(boundaries, values):
    """Branch-free piecewise schedule: the LR index is the count of
    boundaries already passed, gathered from a constant value table (the
    reference builds a Switch op chain; a gather maps better onto XLA)."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    if in_dygraph_mode():
        return _dygraph_sched('PiecewiseDecay', boundaries, values, 0)
    step = _decay_step_counter()
    bounds = assign(np.asarray(boundaries, 'float32'))
    table = assign(np.asarray(values, 'float32'))
    passed = cast(greater_equal(step, bounds), 'float32')
    idx = cast(_op('reduce_sum', x=passed, attrs={'keep_dim': True}), 'int32')
    return _op('gather', x=table, index=idx)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    if in_dygraph_mode():
        return _dygraph_sched('CosineDecay', learning_rate, step_each_epoch,
                              epochs)
    step = _decay_step_counter()
    cur_epoch = _op('floor', x=step / float(step_each_epoch))
    return learning_rate * 0.5 * (
        _op('cos', x=cur_epoch * (math.pi / epochs)) + 1.0)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear ramp start_lr→end_lr for warmup_steps, then `learning_rate`
    (which may itself be a schedule Variable). Select is computed as a mask
    blend — no control flow inside the compiled step."""
    if in_dygraph_mode():
        return _dygraph_sched('LinearLrWarmup', learning_rate, warmup_steps,
                              start_lr, end_lr)
    step = _decay_step_counter()
    if not hasattr(learning_rate, 'name'):   # python float → const var
        learning_rate = fill_constant([1], 'float32', float(learning_rate))
    warm = start_lr + (end_lr - start_lr) * (step / float(warmup_steps))
    in_warmup = cast(less_than(step, fill_constant([1], 'float32',
                                                   float(warmup_steps))),
                     'float32')
    return in_warmup * warm + (1.0 - in_warmup) * learning_rate
