"""fluid.layers.tensor parity (ref: python/paddle/fluid/layers/tensor.py)."""
from __future__ import annotations

import numpy as np

from ..core.dtypes import convert_dtype
from ..framework import Variable, in_dygraph_mode
from ..initializer import ConstantInitializer, NumpyArrayInitializer
from ..layer_helper import LayerHelper
from .common import apply_op_layer, generate_layer_fn

__all__ = ['create_tensor', 'create_parameter', 'create_global_var', 'cast',
           'concat', 'sums', 'assign', 'fill_constant',
           'fill_constant_batch_size_like', 'argmin', 'argmax', 'argsort',
           'ones', 'zeros', 'reverse', 'has_inf', 'has_nan', 'isfinite',
           'range', 'linspace', 'zeros_like', 'ones_like', 'diag', 'eye',
           'tensor_array_to_tensor']


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper('create_tensor', name=name)
    return helper.main_program.current_block().create_var(
        name=name, dtype=convert_dtype(dtype), persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import copy
    from ..param_attr import ParamAttr
    helper = LayerHelper('create_parameter', name=name)
    # copy before naming — never mutate a caller-shared ParamAttr
    attr = copy.copy(ParamAttr._to_attr(attr))
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper('global_var', name=name)
    v = helper.create_global_variable(shape, dtype, persistable=persistable,
                                      name=name)
    sb = helper.startup_program.global_block()
    sv = sb.create_var(name=v.name, shape=list(shape),
                       dtype=convert_dtype(dtype), persistable=persistable,
                       stop_gradient=True)
    ConstantInitializer(float(value))(sv, sb)
    return v


def cast(x, dtype):
    return apply_op_layer('cast', {'x': x}, {'dtype': convert_dtype(dtype)},
                          dtype=convert_dtype(dtype))


def concat(input, axis=0, name=None):
    return apply_op_layer('concat', {'xs': list(input)}, {'axis': axis},
                          name=name)


def sums(input, out=None):
    return apply_op_layer('sum', {'xs': list(input)})


def assign(input, output=None):
    from ..framework import in_dygraph_mode
    if isinstance(input, (np.ndarray, list, tuple, float, int)):
        if in_dygraph_mode():
            from ..dygraph.tape import Tensor
            input = Tensor(np.asarray(input), stop_gradient=True)
        else:
            input = fill_constant_array(np.asarray(input))
    if output is None:
        return apply_op_layer('assign', {'x': input})
    if in_dygraph_mode():
        output.set_value(input)
        return output
    helper = LayerHelper('assign')
    helper.append_op(type='assign', inputs={'x': input.name},
                     outputs={'Out': output.name})
    return output


def fill_constant_array(arr):
    """Materialize a numpy constant into the graph."""
    helper = LayerHelper('constant')
    out = helper.create_variable_for_type_inference(str(arr.dtype))
    helper.append_op(type='__constant__', inputs={},
                     outputs={'Out': out.name},
                     attrs={'value': np.asarray(arr)})
    out.shape = tuple(arr.shape)
    out.dtype = convert_dtype(str(arr.dtype))
    return out


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    v = apply_op_layer('fill_constant', {},
                       {'shape': list(shape), 'value': float(value)
                        if convert_dtype(dtype).startswith('float') else value,
                        'dtype': convert_dtype(dtype)},
                       dtype=convert_dtype(dtype))
    if getattr(v, 'shape', None) is None:
        v.shape = tuple(shape)
    return v


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    return apply_op_layer('fill_constant_batch_size_like', {'ref': input},
                          {'shape': list(shape), 'value': value,
                           'dtype': convert_dtype(dtype),
                           'input_dim_idx': input_dim_idx,
                           'output_dim_idx': output_dim_idx})


argmin = generate_layer_fn('arg_min')
argmax = generate_layer_fn('arg_max')


def argsort(input, axis=-1, descending=False, name=None):
    return apply_op_layer('argsort', {'x': input},
                          {'axis': axis, 'descending': descending}, name=name)


def ones(shape, dtype='float32', force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype='float32', force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def reverse(x, axis):
    return apply_op_layer('reverse', {'x': x}, {'axis': axis})


has_inf = generate_layer_fn('has_inf')
has_nan = generate_layer_fn('has_nan')
isfinite = generate_layer_fn('isfinite')


def range(start, end, step, dtype):
    return apply_op_layer('range', {},
                          {'start': start, 'end': end, 'step': step,
                           'dtype': convert_dtype(dtype)},
                          dtype=convert_dtype(dtype))


def linspace(start, stop, num, dtype):
    return apply_op_layer('linspace', {},
                          {'start': start, 'stop': stop, 'num': num,
                           'dtype': convert_dtype(dtype)},
                          dtype=convert_dtype(dtype))


def zeros_like(x, out=None):
    return apply_op_layer('fill_zeros_like', {'x': x})


def ones_like(x, out=None):
    return apply_op_layer('fill_any_like', {'x': x}, {'value': 1.0})


def diag(diagonal):
    return apply_op_layer('diag', {'x': diagonal})


def eye(num_rows, num_columns=None, batch_shape=None, dtype='float32'):
    out = apply_op_layer('eye', {},
                         {'num_rows': num_rows, 'num_columns': num_columns,
                          'dtype': convert_dtype(dtype)},
                         dtype=convert_dtype(dtype))
    if batch_shape:
        for _ in batch_shape:
            out = apply_op_layer('unsqueeze', {'x': out}, {'axes': [0]})
        out = apply_op_layer('expand', {'x': out},
                             {'expand_times': list(batch_shape) + [1, 1]})
    return out


def tensor_array_to_tensor(input, axis=1, name=None):
    out = apply_op_layer('stack', {'xs': list(input)}, {'axis': axis})
    return out, None
