"""Operator overloading on static Variables (ref: python/paddle/fluid/layers/
math_op_patch.py): v + w, v * 2, v > w … append elementwise ops."""
from __future__ import annotations

from ..framework import Variable
from .common import apply_op_layer


def _to_var(other, ref):
    if isinstance(other, Variable):
        return other
    from .tensor import fill_constant
    return fill_constant([1], ref.dtype, float(other))


def _binary(op_type, reverse=False):
    def impl(self, other):
        other = _to_var(other, self)
        x, y = (other, self) if reverse else (self, other)
        return apply_op_layer(op_type, {'x': x, 'y': y})
    return impl


def monkey_patch_variable():
    V = Variable
    V.__add__ = _binary('elementwise_add')
    V.__radd__ = _binary('elementwise_add', reverse=True)
    V.__sub__ = _binary('elementwise_sub')
    V.__rsub__ = _binary('elementwise_sub', reverse=True)
    V.__mul__ = _binary('elementwise_mul')
    V.__rmul__ = _binary('elementwise_mul', reverse=True)
    V.__truediv__ = _binary('elementwise_div')
    V.__rtruediv__ = _binary('elementwise_div', reverse=True)
    V.__pow__ = _binary('elementwise_pow')
    V.__mod__ = _binary('elementwise_mod')
    V.__floordiv__ = _binary('elementwise_floordiv')
    V.__neg__ = lambda self: apply_op_layer('scale', {'x': self}, {'scale': -1.0})
    V.__eq__ = _binary('equal')
    V.__ne__ = _binary('not_equal')
    V.__lt__ = _binary('less_than')
    V.__le__ = _binary('less_equal')
    V.__gt__ = _binary('greater_than')
    V.__ge__ = _binary('greater_equal')
    V.__hash__ = lambda self: hash(id(self))
    V.astype = lambda self, dtype: apply_op_layer(
        'cast', {'x': self}, {'dtype': dtype})
