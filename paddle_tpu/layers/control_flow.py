"""Control-flow layers: cond / case / switch_case / while_loop / While /
StaticRNN / TensorArray ops.

Parity with reference python/paddle/fluid/layers/control_flow.py — redesigned
for TPU: instead of the reference's sub-block interpreter ops
(conditional_block, while, ref paddle/fluid/operators/controlflow/*), each
construct captures its branches/body as sub-Blocks at build time and lowers to
ONE structured-control-flow XLA op (`lax.cond`, `lax.while_loop`,
`lax.switch`, `lax.scan`) inside the fused jitted step — no host round-trips.

Note on autodiff: `lax.while_loop` is forward-only (XLA's while has no
reverse-mode rule). Differentiable loops either use StaticRNN / layers.rnn
(lax.scan) or pass `maximum_trip_count` to `while_loop`, which lowers to a
masked lax.scan — the TPU parity path for the reference's WhileGradOp.
"""
from __future__ import annotations

import contextlib

from ..framework import (Variable, default_main_program, in_dygraph_mode)
from ..layer_helper import LayerHelper
from ..ops.registry import register_op
from .common import apply_op_layer, generate_layer_fn

__all__ = [
    'cond', 'case', 'switch_case', 'while_loop', 'While', 'StaticRNN',
    'increment', 'less_than', 'less_equal', 'greater_than', 'greater_equal',
    'equal', 'not_equal', 'array_write', 'array_read', 'array_length',
    'create_array', 'Print', 'is_empty',
]

# ---------------------------------------------------------------------------
# comparisons (layer wrappers over registered ops; `cond` kwarg writes into an
# existing bool var, as the reference's compare layers do)
# ---------------------------------------------------------------------------


def _compare(op_type):
    base = generate_layer_fn(op_type, in_slots=['x', 'y'])

    def layer(x, y, cond=None, name=None):
        out = base(x, y, name=name)
        if cond is not None:
            return assign_to(out, cond)
        return out

    layer.__name__ = op_type
    return layer


def assign_to(src, dst):
    """Copy src into dst's slot (delegates to layers.assign(input, output))."""
    from .tensor import assign
    return assign(src, output=dst)


less_than = _compare('less_than')
less_equal = _compare('less_equal')
greater_than = _compare('greater_than')
greater_equal = _compare('greater_equal')
equal = _compare('equal')
not_equal = _compare('not_equal')


def increment(x, value=1.0, in_place=True):
    """ref: fluid.layers.increment (control_flow.py:1327). in_place rebinds
    the same var name so loop-carried counters update."""
    if in_dygraph_mode():
        from ..dygraph.tape import dispatch_op
        out = dispatch_op('increment', {'x': x}, {'value': float(value)})
        if in_place:
            x.set_value(out)
            return x
        return out
    helper = LayerHelper('increment')
    if in_place:
        helper.append_op(type='increment', inputs={'x': x.name},
                         outputs={'Out': x.name}, attrs={'value': float(value)})
        return x
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='increment', inputs={'x': x.name},
                     outputs={'Out': out.name}, attrs={'value': float(value)})
    return out


# ---------------------------------------------------------------------------
# sub-block capture helper
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _sub_block(program):
    blk = program._create_block()
    try:
        yield blk
    finally:
        program._rollback()


# one nest semantics repo-wide: layers/utils.py (dicts flatten by sorted
# key, namedtuples/lists/tuples by position)
from .utils import flatten as _flatten
from .utils import pack_sequence_as as _pack_like


def _parent_writes(blk):
    """Names of parent-block variables written by ops inside `blk` (e.g. via
    assign(x, output=outer_var)) — these must be merged out of the branch,
    like the reference conditional_block's output scope promotion."""
    written = []
    for op in blk.ops:
        for n in op.output_names():
            if n not in blk.vars and n not in written:
                written.append(n)
    return written


# ---------------------------------------------------------------------------
# cond / case / switch_case
# ---------------------------------------------------------------------------


def cond(pred, true_fn=None, false_fn=None, name=None):
    """ref: fluid.layers.cond (control_flow.py:2259). Lowers to lax.cond —
    both branches are traced into the same XLA program."""
    if in_dygraph_mode():
        import numpy as np
        flag = bool(np.asarray(pred.numpy()).reshape(()))
        if flag:
            return true_fn() if true_fn is not None else None
        return false_fn() if false_fn is not None else None

    program = default_main_program()
    helper = LayerHelper('cond', name=name)
    with _sub_block(program) as tblk:
        t_out = true_fn() if true_fn is not None else None
    with _sub_block(program) as fblk:
        f_out = false_fn() if false_fn is not None else None
    writes = _parent_writes(tblk)
    writes += [w for w in _parent_writes(fblk) if w not in writes]
    if (t_out is None) != (f_out is None):
        raise ValueError(
            "cond: one branch returned a value and the other returned None; "
            "both branches must return the same structure")
    if t_out is None and not writes:
        return None
    t_flat, f_flat = _flatten(t_out), _flatten(f_out)
    if t_out is None:
        t_flat = f_flat = []
    if len(t_flat) != len(f_flat):
        raise ValueError(
            f"cond: true_fn returned {len(t_flat)} outputs but false_fn "
            f"returned {len(f_flat)}; both branches must match")
    outs = []
    for tv in t_flat:
        o = helper.create_variable_for_type_inference(tv.dtype)
        o.shape = tv.shape
        outs.append(o)
    helper.append_op(
        type='__cond__',
        inputs={'Cond': pred.name},
        outputs={'Out': [o.name for o in outs] + writes},
        attrs={'true_block': tblk.idx, 'false_block': fblk.idx,
               'true_outs': [v.name for v in t_flat],
               'false_outs': [v.name for v in f_flat],
               'writes': writes})
    return _pack_like(t_out, outs) if t_out is not None else None


def case(pred_fn_pairs, default=None, name=None):
    """ref: fluid.layers.case (control_flow.py:2457): first true pred wins.
    Composed from nested cond (→ nested lax.cond)."""
    if not pred_fn_pairs:
        raise ValueError("case: pred_fn_pairs must be non-empty")

    def build(pairs):
        pred, fn = pairs[0]
        if len(pairs) == 1:
            fallback = default if default is not None else fn
            return cond(pred, fn, fallback)
        return cond(pred, fn, lambda: build(pairs[1:]))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref: fluid.layers.switch_case (control_flow.py:2559). Lowers to
    lax.switch with the default branch appended; out-of-range indices clamp
    to the default, matching the reference."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = [(i, kv) if callable(kv) else (kv[0], kv[1])
                 for i, kv in enumerate(branch_fns)]
    keys = [int(k) for k, _ in pairs]
    fns = [fn for _, fn in pairs]
    if default is None:
        default = fns[-1]

    if in_dygraph_mode():
        idx = int(branch_index.numpy().reshape(()))
        for k, fn in zip(keys, fns):
            if k == idx:
                return fn()
        return default()

    program = default_main_program()
    helper = LayerHelper('switch_case', name=name)
    reuse_last_as_default = default is fns[-1]
    blocks, returns, sub_blks = [], [], []
    for fn in (fns if reuse_last_as_default else fns + [default]):
        with _sub_block(program) as blk:
            out = fn()
        blocks.append(blk.idx)
        sub_blks.append(blk)
        returns.append(out)
    if reuse_last_as_default:
        blocks.append(blocks[-1])
        returns.append(returns[-1])
    writes = []
    for blk in sub_blks:
        writes += [w for w in _parent_writes(blk) if w not in writes]
    if any((r is None) != (returns[0] is None) for r in returns):
        raise ValueError("switch_case: some branches returned a value and "
                         "others returned None; all must match")
    branch_outs = [[] if r is None else _flatten(r) for r in returns]
    if returns[0] is None and not writes:
        return None
    n_out = len(branch_outs[0])
    if any(len(b) != n_out for b in branch_outs):
        raise ValueError("switch_case: all branches must return the same "
                         "number of outputs")
    outs = []
    for tv in branch_outs[0]:
        o = helper.create_variable_for_type_inference(tv.dtype)
        o.shape = tv.shape
        outs.append(o)
    helper.append_op(
        type='__switch__',
        inputs={'Index': branch_index.name},
        outputs={'Out': [o.name for o in outs] + writes},
        attrs={'blocks': blocks, 'keys': keys,
               'branch_outs': [[v.name for v in b] for b in branch_outs],
               'writes': writes})
    if returns[0] is None:
        return None
    return outs[0] if n_out == 1 else outs


# ---------------------------------------------------------------------------
# while_loop (functional) + While (legacy block form)
# ---------------------------------------------------------------------------


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               maximum_trip_count=None):
    """ref: fluid.layers.while_loop (control_flow.py:1054). Lowers to
    lax.while_loop; carry = loop_vars.

    `maximum_trip_count` (TPU extension): with a static trip bound the loop
    lowers to a masked lax.scan instead, which IS reverse-differentiable —
    the parity path for the reference's WhileGradOp
    (/root/reference/paddle/fluid/operators/controlflow/while_op.cc:154).
    Without it the loop is forward-only (see module docstring)."""
    if in_dygraph_mode():
        import numpy as np
        args = list(loop_vars)
        while bool(np.asarray(cond(*args).numpy()).reshape(())):
            out = body(*args)
            args = list(out) if isinstance(out, (list, tuple)) else [out]
        return args

    program = default_main_program()
    helper = LayerHelper('while_loop', name=name)
    flat_vars = _flatten(loop_vars)
    with _sub_block(program) as cond_blk:
        c = cond(*loop_vars)
    with _sub_block(program) as body_blk:
        b_out = body(*loop_vars)
    b_flat = _flatten(b_out)
    if len(b_flat) != len(flat_vars):
        raise ValueError(
            f"while_loop: body returned {len(b_flat)} values for "
            f"{len(flat_vars)} loop_vars")
    loop_names = [v.name for v in flat_vars]
    # parent-block vars written inside the body join the loop carry, so
    # assign(x, output=outer_var) survives iterations
    writes = [w for w in _parent_writes(body_blk) if w not in loop_names]
    outs = []
    for v in flat_vars:
        o = helper.create_variable_for_type_inference(v.dtype)
        o.shape = v.shape
        outs.append(o)
    helper.append_op(
        type='__while__',
        inputs={'X': loop_names + writes},
        outputs={'Out': [o.name for o in outs] + writes},
        attrs={'cond_block': cond_blk.idx, 'body_block': body_blk.idx,
               'cond_out': c.name, 'body_outs': [v.name for v in b_flat],
               'loop_vars': loop_names, 'writes': writes,
               'max_trip_count': (None if maximum_trip_count is None
                                  else int(maximum_trip_count))})
    return _pack_like(b_out if isinstance(b_out, (list, tuple)) else loop_vars,
                      outs)


class While:
    """Legacy block-style while (ref: fluid.layers.While, control_flow.py:789).

    Usage:
        i = fill_constant([1], 'int64', 0)
        cond_var = less_than(i, n)
        w = While(cond_var)
        with w.block():
            ... increment(i) ...
            less_than(i, n, cond=cond_var)

    The loop carry is inferred as every parent-block variable written inside
    the body (including the condition var), then lowered to lax.while_loop.
    """

    def __init__(self, cond, is_test=False, name=None):
        if in_dygraph_mode():
            raise RuntimeError("While is a static-graph construct; use a "
                               "python loop in dygraph mode")
        self.cond_var = cond
        self.helper = LayerHelper('while', name=name)

    @contextlib.contextmanager
    def block(self):
        program = default_main_program()
        blk = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        written = _parent_writes(blk)
        carry = [self.cond_var.name]
        carry += [n for n in written if n != self.cond_var.name]
        parent_cur = program.current_block()
        parent_cur.append_op(
            type='__while_legacy__',
            inputs={'X': carry},
            outputs={'Out': carry},
            attrs={'body_block': blk.idx, 'carry': carry})


# ---------------------------------------------------------------------------
# StaticRNN → lax.scan
# ---------------------------------------------------------------------------


class StaticRNN:
    """ref: fluid.layers.StaticRNN (control_flow.py:409): explicit recurrence
    over the leading (time) dim. Lowers to lax.scan — differentiable, fused,
    static trip count (the TPU-native recurrence primitive)."""

    def __init__(self, name=None):
        if in_dygraph_mode():
            raise RuntimeError("StaticRNN is a static-graph construct")
        self.helper = LayerHelper('static_rnn', name=name)
        self._block = None
        self._seq_inputs = []   # (slice_name, source_name)
        self._memories = []     # dicts: pre, init, new
        self._outputs = []      # step output var names
        self._out_vars = None
        self._seq_len = None

    @contextlib.contextmanager
    def step(self):
        program = default_main_program()
        self._block = program._create_block()
        try:
            yield
        finally:
            program._rollback()
            self._complete()

    def step_input(self, x):
        if self._seq_len is None:
            self._seq_len = x.shape[0]
        v = self._block.create_var(
            name=self.helper.name + f'.in{len(self._seq_inputs)}',
            shape=x.shape[1:], dtype=x.dtype)
        self._seq_inputs.append((v.name, x.name))
        return v

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1, dtype='float32'):
        from . import tensor as tensor_layers
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs `init` or (`shape`+`batch_ref`)")
            mshape = [batch_ref.shape[ref_batch_dim_idx] if s == -1 else s
                      for s in shape]
            # build the init in the PARENT block
            program = default_main_program()
            cur = program.current_block_idx
            program.current_block_idx = self._block.parent_idx
            try:
                init = tensor_layers.fill_constant(mshape, dtype,
                                                   float(init_value))
            finally:
                program.current_block_idx = cur
        pre = self._block.create_var(
            name=self.helper.name + f'.mem{len(self._memories)}',
            shape=init.shape, dtype=init.dtype)
        self._memories.append({'pre': pre.name, 'init': init.name,
                               'new': None})
        return pre

    def update_memory(self, mem, var):
        for m in self._memories:
            if m['pre'] == mem.name:
                m['new'] = var.name
                return
        raise ValueError(f"update_memory: {mem.name} is not a memory")

    def step_output(self, o):
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        for m in self._memories:
            if m['new'] is None:
                m['new'] = m['pre']
        outs = []
        for ov in self._outputs:
            o = self.helper.create_variable_for_type_inference(ov.dtype)
            if ov.shape is not None and self._seq_len is not None:
                o.shape = (self._seq_len,) + tuple(ov.shape)
            outs.append(o)
        self.helper.append_op(
            type='__scan__',
            inputs={'X': [src for _, src in self._seq_inputs],
                    'Init': [m['init'] for m in self._memories]},
            outputs={'Out': [o.name for o in outs]},
            attrs={'block': self._block.idx,
                   'slice_names': [s for s, _ in self._seq_inputs],
                   'pre_names': [m['pre'] for m in self._memories],
                   'new_names': [m['new'] for m in self._memories],
                   'out_names': [o.name for o in self._outputs]})
        self._out_vars = outs

    def __call__(self):
        if not self._out_vars:
            raise ValueError("StaticRNN has no step_output")
        return self._out_vars[0] if len(self._out_vars) == 1 else self._out_vars


# ---------------------------------------------------------------------------
# TensorArray (ref: LoDTensorArray + array_write/array_read ops,
# python/paddle/fluid/layers/control_flow.py:1475). On TPU, arrays are Python
# lists in the traced env; indices must be trace-time constants (counters
# built from fill_constant/increment are). In-loop accumulation should use
# StaticRNN / layers.rnn (lax.scan buffers) instead.
# ---------------------------------------------------------------------------


def _concrete_index(i):
    import numpy as np
    try:
        return int(np.asarray(i).reshape(()))
    except Exception:
        raise ValueError(
            "TensorArray index must be a trace-time constant on TPU (built "
            "from fill_constant/increment); for in-loop accumulation use "
            "StaticRNN or layers.rnn (lax.scan)") from None


@register_op('__array_write__', atomic_output=True)
def _array_write_op(array, x, i):
    idx = _concrete_index(i)
    new = list(array) if array is not None else []
    while len(new) <= idx:
        new.append(None)
    new[idx] = x
    return new


@register_op('__array_read__')
def _array_read_op(array, i):
    return array[_concrete_index(i)]


@register_op('__array_length__')
def _array_length_op(array):
    import jax.numpy as jnp
    return jnp.asarray(len(array), jnp.int32)


class _DygraphTensorArray(list):
    pass


def create_array(dtype='float32'):
    if in_dygraph_mode():
        return _DygraphTensorArray()
    helper = LayerHelper('array')
    v = helper.main_program.current_block().create_var(
        name=helper.name, dtype=dtype, shape=(0,))
    v.is_tensor_array = True
    helper.append_op(type='__create_array__', inputs={},
                     outputs={'Out': v.name}, attrs={})
    return v


def array_write(x, i, array=None):
    if in_dygraph_mode():
        if array is None:
            array = _DygraphTensorArray()
        idx = int(i.numpy().reshape(())) if hasattr(i, 'numpy') else int(i)
        while len(array) <= idx:
            array.append(None)
        array[idx] = x
        return array
    helper = LayerHelper('array_write')
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(
        type='__array_write__',
        inputs={'array': array.name, 'x': x.name, 'i': i.name},
        outputs={'Out': array.name})
    return array


def array_read(array, i):
    if in_dygraph_mode():
        idx = int(i.numpy().reshape(())) if hasattr(i, 'numpy') else int(i)
        return array[idx]
    helper = LayerHelper('array_read')
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(type='__array_read__',
                     inputs={'array': array.name, 'i': i.name},
                     outputs={'Out': out.name})
    return out


def array_length(array):
    if in_dygraph_mode():
        from ..dygraph.tape import Tensor
        return Tensor(len(array), dtype='int64', stop_gradient=True)
    helper = LayerHelper('array_length')
    out = helper.create_variable_for_type_inference('int32')
    out.shape = ()
    helper.append_op(type='__array_length__', inputs={'array': array.name},
                     outputs={'Out': out.name})
    return out


# ---------------------------------------------------------------------------
# Print / is_empty
# ---------------------------------------------------------------------------


@register_op('print')
def _print_op(x, *, message=''):
    import jax
    jax.debug.print(message + '{x}', x=x)
    return x


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase='both'):
    """ref: fluid.layers.Print (control_flow.py:690) → jax.debug.print."""
    msg = (message or '') + (f" {input.name}: " if print_tensor_name else ' ')
    # escape braces: msg is spliced into jax.debug.print's format string
    msg = msg.replace('{', '{{').replace('}', '}}')
    return apply_op_layer('print', {'x': input}, {'message': msg})


@register_op('is_empty')
def _is_empty_op(x):
    import jax.numpy as jnp
    return jnp.asarray(x.size == 0)


def is_empty(x, cond=None):
    out = apply_op_layer('is_empty', {'x': x}, {})
    if cond is not None:
        return assign_to(out, cond)
    return out


# ---------------------------------------------------------------------------
# legacy block-style control flow (ref: fluid.layers.Switch / IfElse /
# DynamicRNN / lod_rank_table / reorder_lod_tensor_by_rank)
# ---------------------------------------------------------------------------


class Switch:
    """ref: control_flow.py:Switch — imperative first-true-wins case chain
    (the classic LR-schedule construct). Each case body is captured into a
    sub-block at `with switch.case(cond)` time; on exit the chain lowers to
    nested __cond__ ops (lax.cond), merging parent-var writes."""

    def __init__(self, name=None):
        self._cases = []          # [(cond_var, block)]
        self._default = None
        self._inside = False

    def __enter__(self):
        self._inside = True
        return self

    @contextlib.contextmanager
    def case(self, condition):
        if not self._inside:
            raise ValueError("Switch.case must be used inside 'with switch'")
        program = default_main_program()
        with _sub_block(program) as blk:
            yield
        self._cases.append((condition, blk))

    @contextlib.contextmanager
    def default(self):
        program = default_main_program()
        with _sub_block(program) as blk:
            yield
        self._default = blk

    def __exit__(self, exc_type, exc_val, exc_tb):
        self._inside = False
        if exc_type is not None:
            return False
        if not self._cases:
            if self._default is not None:
                raise ValueError(
                    "Switch: a default block requires at least one case")
            return False
        program = default_main_program()
        helper = LayerHelper('switch')

        def emit(i):
            """Append the __cond__ op for case i into the current block."""
            cvar, tblk = self._cases[i]
            if i == len(self._cases) - 1:
                if self._default is not None:
                    fblk = self._default
                else:
                    with _sub_block(program) as fblk:
                        pass
            else:
                with _sub_block(program) as fblk:
                    emit(i + 1)
            writes = _parent_writes(tblk)
            writes += [w for w in _parent_writes(fblk) if w not in writes]
            helper.append_op(
                type='__cond__', inputs={'Cond': cvar.name},
                outputs={'Out': writes},
                attrs={'true_block': tblk.idx, 'false_block': fblk.idx,
                       'true_outs': [], 'false_outs': [], 'writes': writes})

        emit(0)
        return False


class IfElse:
    """ref: control_flow.py:IfElse — batch-partition branching. The reference
    physically splits rows by the bool mask, runs each branch on its
    sub-batch, and merges. TPU formulation: both branches compute over the
    FULL batch (static shapes) and outputs merge rowwise with where(mask) —
    identical results for the rowwise computations this API serves."""

    def __init__(self, cond, name=None):
        self._cond = cond
        self._in_true = None
        self._outs = {True: [], False: []}

    @contextlib.contextmanager
    def true_block(self):
        self._in_true = True
        yield
        self._in_true = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_true = False
        yield
        self._in_true = None

    def input(self, x):
        return x

    def output(self, *outs):
        if self._in_true is None:
            raise ValueError("IfElse.output must be called inside a block")
        self._outs[self._in_true].extend(outs)

    def __call__(self):
        t, f = self._outs[True], self._outs[False]
        if len(t) != len(f):
            raise ValueError(
                f"IfElse: true block produced {len(t)} outputs, false block "
                f"{len(f)}; they must match")
        from .nn import where, reshape
        merged = []
        for tv, fv in zip(t, f):
            m = self._cond
            # rowwise select (never multiply-blend: 0*NaN from the unselected
            # branch must not poison the result, and int dtypes must survive)
            extra = len(tv.shape) - len(m.shape)
            if extra > 0:
                m = reshape(m, shape=list(m.shape) + [1] * extra)
            merged.append(where(m, tv, fv))
        return merged


class DynamicRNN:
    """ref: control_flow.py:DynamicRNN — RNN builder over variable-length
    batches. The reference sorts rows by length and shrinks the batch as
    sequences end; the TPU formulation runs a fixed T-step StaticRNN over
    the padded batch and freezes finished rows' memories via masking (static
    shapes, no re-sorting)."""

    def __init__(self, name=None):
        self._srnn = StaticRNN()
        self._lens = None
        self._t = None
        self._T = None
        self._B = None
        self._x_ref = None

    @contextlib.contextmanager
    def block(self):
        with self._srnn.step():
            yield

    @contextlib.contextmanager
    def _parent_block(self):
        """Emit ops into the block enclosing the step body: scan sequence
        inputs must be parent-block vars."""
        program = default_main_program()
        cur = program.current_block_idx
        program.current_block_idx = self._srnn._block.parent_idx
        try:
            yield
        finally:
            program.current_block_idx = cur

    def step_input(self, x, level=0, sequence_length=None):
        """x: (B, T, D) padded batch (+ lengths via kwarg or lod_reset)."""
        from .nn import transpose
        if self._lens is None:
            self._lens = sequence_length if sequence_length is not None \
                else getattr(x, 'sequence_length', None)
        self._x_ref = x
        with self._parent_block():
            xt = transpose(x, perm=[1, 0] + list(range(2, len(x.shape))))
            self._T = xt.shape[0]
            self._B = xt.shape[1]
            if self._t is None:
                import numpy as np
                from .tensor import fill_constant_array
                times = fill_constant_array(np.arange(self._T, dtype=np.int64))
        if self._t is None:
            self._t = self._srnn.step_input(times)
        return self._srnn.step_input(xt)

    def static_input(self, x):
        return x

    @property
    def step_idx(self):
        return self._t

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype='float32'):
        if init is None:
            if shape is None or self._x_ref is None:
                raise ValueError("DynamicRNN.memory(shape=...) must follow "
                                 "step_input (batch size comes from it)")
            from .tensor import fill_constant, fill_constant_batch_size_like
            with self._parent_block():
                if isinstance(self._B, int) and self._B > 0:
                    init = fill_constant([self._B] + list(shape), dtype,
                                         float(value))
                else:   # symbolic batch: size comes from the input at run time
                    init = fill_constant_batch_size_like(
                        self._x_ref, [-1] + list(shape), dtype, float(value))
            if getattr(init, 'shape', None) is None:
                init.shape = tuple([-1] + list(shape))
        return self._srnn.memory(init=init)

    def update_memory(self, mem, new):
        if self._lens is not None and self._t is not None:
            from .tensor import cast
            from .nn import reshape
            alive = cast(
                apply_op_layer('less_than',
                               {'x': self._t,
                                'y': cast(self._lens, 'int64')}), new.dtype)
            rank = len(new.shape if new.shape is not None else mem.shape)
            alive = reshape(alive, shape=[-1] + [1] * (rank - 1))
            new = new * alive + mem * (1.0 - alive)
        self._srnn.update_memory(mem, new)

    def output(self, *outputs):
        for o in outputs:
            self._srnn.step_output(o)

    def __call__(self):
        from .nn import transpose
        res = self._srnn()
        outs = res if isinstance(res, list) else [res]
        outs = [transpose(o, perm=[1, 0] + list(range(2, len(o.shape))))
                for o in outs]
        for o in outs:
            if self._lens is not None:
                o.sequence_length = self._lens
        return outs[0] if len(outs) == 1 else outs


def lod_rank_table(x, level=0):
    """Rank table = rows sorted by descending length. Returns the (B,)
    permutation indices (the padded-batch analogue of the reference's
    LoDRankTable)."""
    lens = getattr(x, 'sequence_length', None)
    if lens is None:
        raise ValueError("lod_rank_table: input carries no sequence_length "
                         "(use lod_reset or pass lengths)")
    neg = apply_op_layer('scale', {'x': lens}, {'scale': -1.0})
    _, idx = apply_op_layer('argsort', {'x': neg}, {'axis': 0})
    return idx


def reorder_lod_tensor_by_rank(x, rank_table):
    """Permute batch rows by a lod_rank_table permutation."""
    out = apply_op_layer('gather', {'x': x, 'index': rank_table})
    return out


__all__ += ['Switch', 'IfElse', 'DynamicRNN', 'lod_rank_table',
            'reorder_lod_tensor_by_rank']
