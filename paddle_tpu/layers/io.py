"""Data-layer declarations (ref: python/paddle/fluid/layers/io.py:data and
python/paddle/fluid/data.py:fluid.data)."""
from __future__ import annotations

from ..core.dtypes import convert_dtype
from ..framework import default_main_program, default_startup_program

__all__ = ['data', 'read_file', 'double_buffer', 'py_reader', 'load',
           'create_py_reader_by_data']


def data(name, shape, dtype='float32', lod_level=0, append_batch_size=True,
         type=None, stop_gradient=True):
    """fluid.layers.data parity: prepends a -1 batch dim unless told not to."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    for prog in (default_main_program(),):
        v = prog.global_block().create_var(
            name=name, shape=shape, dtype=convert_dtype(dtype),
            is_data=True, stop_gradient=stop_gradient, lod_level=lod_level)
        if lod_level and lod_level > 0:
            # ragged feed: a LoDTensor feed binds this companion var with
            # the per-row valid lengths (core/lod.py); sequence layers pick
            # it up implicitly via _seq_len
            lv = prog.global_block().create_var(
                name=name + '@LEN', shape=[-1], dtype='int64',
                is_data=True, stop_gradient=True)
            v._length_var = lv
    return v


def fluid_data(name, shape, dtype='float32', lod_level=0):
    """fluid.data parity: shape used as-is (may contain None/-1)."""
    shape = [-1 if s is None else s for s in shape]
    return data(name, shape, dtype, lod_level, append_batch_size=False)


def read_file(reader):
    """ref: fluid.layers.io.read_file (io.py:827): with DataLoader-backed
    readers the feed vars ARE the read results — return them."""
    vars_ = getattr(reader, '_feed_list', None)
    if not vars_:
        raise TypeError(
            f"read_file expects a py_reader/DataLoader created with a "
            f"feed list, got {type(reader).__name__}")
    return vars_


def double_buffer(reader, place=None, name=None):
    """ref: fluid.layers.io.double_buffer (io.py:549). The DataLoader's
    background device_put ring already double-buffers host→HBM; this is the
    identity on TPU."""
    return reader


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """ref: fluid.layers.io.py_reader (io.py:549) — thin shim over
    DataLoader.from_generator: returns an object with decorate_* methods,
    start()/reset(), and feed vars recoverable via read_file()."""
    from ..core import unique_name
    from ..reader import DataLoader

    base = name or unique_name.generate('_py_reader')
    lod_levels = lod_levels or [0] * len(shapes)
    feed_vars = []
    for i, (shape, dtype, lod) in enumerate(zip(shapes, dtypes, lod_levels)):
        full = [-1 if s is None else int(s) for s in shape]
        feed_vars.append(data(f"{base}_{i}", full, dtype=dtype,
                              lod_level=lod, append_batch_size=False))
    return DataLoader.from_generator(feed_list=feed_vars,
                                     capacity=capacity,
                                     use_double_buffer=use_double_buffer)


def load(out, file_path, load_as_fp16=False):
    """ref: fluid.layers.io.load — load one saved var into `out`'s slot."""
    import numpy as np
    from ..core.scope import global_scope
    arr = np.load(file_path if file_path.endswith('.npy')
                  else file_path + '.npy', allow_pickle=False)
    if load_as_fp16:
        arr = arr.astype(np.float16)
    import jax.numpy as jnp
    global_scope().set(out.name, jnp.asarray(arr))
    return out


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """ref: fluid.layers.io.create_py_reader_by_data (io.py:730): like
    py_reader but reuses existing feed vars instead of declaring new ones."""
    from ..reader import DataLoader
    return DataLoader.from_generator(feed_list=list(feed_list),
                                     capacity=capacity,
                                     use_double_buffer=use_double_buffer)
