"""Data-layer declarations (ref: python/paddle/fluid/layers/io.py:data and
python/paddle/fluid/data.py:fluid.data)."""
from __future__ import annotations

from ..core.dtypes import convert_dtype
from ..framework import default_main_program, default_startup_program

__all__ = ['data']


def data(name, shape, dtype='float32', lod_level=0, append_batch_size=True,
         type=None, stop_gradient=True):
    """fluid.layers.data parity: prepends a -1 batch dim unless told not to."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    for prog in (default_main_program(),):
        v = prog.global_block().create_var(
            name=name, shape=shape, dtype=convert_dtype(dtype),
            is_data=True, stop_gradient=stop_gradient, lod_level=lod_level)
    return v


def fluid_data(name, shape, dtype='float32', lod_level=0):
    """fluid.data parity: shape used as-is (may contain None/-1)."""
    shape = [-1 if s is None else s for s in shape]
    return data(name, shape, dtype, lod_level, append_batch_size=False)
