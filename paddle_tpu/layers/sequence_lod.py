"""Sequence layer API (ref: python/paddle/fluid/layers/sequence_lod.py).

The reference's LoD-tensor sequence layers, reformulated for TPU over padded
(B, T, ...) batches: every layer accepts a `sequence_length` kwarg (a (B,)
int vector) in place of the LoD offset table. `None` means all rows span the
full time dim. See ops/sequence_ops.py for the op semantics.
"""
from __future__ import annotations

from ..layer_helper import LayerHelper
from ..initializer import XavierInitializer
from .common import apply_op_layer


def _seq_len(input, sequence_length):
    """Explicit sequence_length wins; otherwise the length var a
    lod_level>0 data() attached travels with the tensor (LoDTensor
    unification, core/lod.py)."""
    if sequence_length is not None:
        return sequence_length
    return getattr(input, '_length_var', None)


def _carry_len(out, input, sequence_length):
    """Tag a length-preserving result so chained sequence layers keep
    resolving the ragged structure implicitly."""
    lv = _seq_len(input, sequence_length)
    if lv is not None:
        out._length_var = lv
    return out

__all__ = ['sequence_conv', 'sequence_softmax', 'sequence_pool',
           'sequence_concat', 'sequence_first_step', 'sequence_last_step',
           'sequence_slice', 'sequence_expand', 'sequence_expand_as',
           'sequence_pad', 'sequence_unpad', 'sequence_reshape',
           'sequence_scatter', 'sequence_enumerate', 'sequence_mask',
           'sequence_reverse']


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None, sequence_length=None):
    helper = LayerHelper('sequence_conv', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    D = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                [filter_size * D, num_filters], input.dtype,
                                default_initializer=XavierInitializer())
    b = helper.create_parameter(helper.bias_attr, [num_filters], input.dtype,
                                is_bias=True)
    out = apply_op_layer(
        'sequence_conv',
        {'x': input, 'w': w, 'bias': b,
         'length': _seq_len(input, sequence_length)},
        {'context_length': filter_size, 'context_start': padding_start,
         'padding': padding})
    out = helper.append_activation(out) if act else out
    return _carry_len(out, input, sequence_length)


def sequence_softmax(input, use_cudnn=False, name=None, sequence_length=None):
    out = apply_op_layer('sequence_softmax',
                         {'x': input,
                          'length': _seq_len(input, sequence_length)}, {},
                         name=name)
    return _carry_len(out, input, sequence_length)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  sequence_length=None):
    out, _ = apply_op_layer('sequence_pool',
                            {'x': input, 'length': _seq_len(input, sequence_length)},
                            {'pool_type': pool_type, 'pad_value': pad_value})
    return out


def sequence_first_step(input, sequence_length=None):
    return sequence_pool(input, 'first', sequence_length=sequence_length)


def sequence_last_step(input, sequence_length=None):
    return sequence_pool(input, 'last', sequence_length=sequence_length)


def sequence_concat(input, name=None, sequence_lengths=None):
    out, out_len = apply_op_layer(
        'sequence_concat',
        {'xs': list(input), 'lens': sequence_lengths},
        {'n_inputs': len(input)}, name=name)
    return out


def sequence_slice(input, offset, length, name=None, sequence_length=None):
    out, _ = apply_op_layer(
        'sequence_slice',
        {'x': input, 'offset': offset, 'slice_length': length,
         'length': _seq_len(input, sequence_length)}, {}, name=name)
    return out


def sequence_expand(x, y, ref_level=-1, name=None, y_length=None):
    """Dense broadcast formulation — see ops/sequence_ops.py
    sequence_expand_as note."""
    return sequence_expand_as(x, y, name=name, y_length=y_length)


def sequence_expand_as(x, y, name=None, y_length=None):
    return apply_op_layer('sequence_expand_as',
                          {'x': x, 'y': y, 'y_length': y_length}, {},
                          name=name)


def sequence_pad(x, pad_value, maxlen=None, name=None, sequence_length=None):
    out, lens = apply_op_layer(
        'sequence_pad',
        {'x': x, 'pad_value': pad_value,
         'length': _seq_len(x, sequence_length)},
        {'maxlen': -1 if maxlen is None else maxlen}, name=name)
    return out, lens


def sequence_unpad(x, length, name=None):
    return apply_op_layer('sequence_unpad', {'x': x, 'length': length}, {},
                          name=name)


def sequence_reshape(input, new_dim, sequence_length=None):
    out, _ = apply_op_layer('sequence_reshape',
                            {'x': input, 'length': _seq_len(input, sequence_length)},
                            {'new_dim': new_dim})
    return out


def sequence_scatter(input, index, updates, name=None, sequence_length=None):
    return apply_op_layer(
        'sequence_scatter',
        {'x': input, 'index': index, 'updates': updates,
         'length': _seq_len(input, sequence_length)}, {}, name=name)


def sequence_enumerate(input, win_size, pad_value=0, name=None,
                       sequence_length=None):
    return apply_op_layer('sequence_enumerate',
                          {'x': input, 'length': _seq_len(input, sequence_length)},
                          {'win_size': win_size, 'pad_value': pad_value},
                          name=name)


def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    if maxlen is None:
        raise ValueError(
            "sequence_mask on TPU needs a static maxlen (the reference "
            "derives it from data at runtime, which is not static-shape)")
    return apply_op_layer('sequence_mask', {'x': x},
                          {'maxlen': int(maxlen), 'dtype': dtype}, name=name)


def sequence_reverse(x, name=None, sequence_length=None):
    out = apply_op_layer('sequence_reverse',
                         {'x': x, 'length': _seq_len(x, sequence_length)},
                         {}, name=name)
    return _carry_len(out, x, sequence_length)
